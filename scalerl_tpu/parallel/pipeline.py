"""Pipeline parallelism: GPipe microbatch schedule over the ``pp`` axis.

No counterpart in the reference (SURVEY.md §2.4 lists PP as absent); this
completes the mesh's parallelism families.  Block stages are stacked on a
leading ``[S, ...]`` param axis sharded over ``pp``; inside ``shard_map``
each device runs its stage and hands activations to its right neighbor via
a non-cyclic ``ppermute`` shift.  The classic GPipe bubble applies:
``S + M - 1`` steps for ``M`` microbatches.  Heterogeneous models
(``embed -> S distinct blocks -> head``) are first-class via
:func:`make_hetero_pipeline_apply`; the homogeneous form is the same
schedule with identity boundary stages.

This is the correctness-first formulation (activations are dense every
step; idle stages run their *block* on zeros, but the boundary stages are
``lax.cond``-gated: embed runs only on stage 0 and the head only on the
last stage's active steps — ~M head applications instead of S*(M+S-1)).
It exists so ``pp`` is a real, executable axis — RL-parity models are far
too small to need it, which is why the flagship trainers default to
dp/fsdp.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

# stage_fn(stage_params, x[mb, ...]) -> y[mb, ...] (same shape)
StageFn = Callable[[Any, jnp.ndarray], jnp.ndarray]


def _identity_stage(params: Any, x: jnp.ndarray) -> jnp.ndarray:
    del params
    return x


def make_pipeline_apply(
    stage_fn: StageFn,
    mesh: Mesh,
    num_microbatches: int,
    axis_name: str = "pp",
):
    """Build ``apply(stacked_params, x) -> y`` running stages in pipeline.

    ``stacked_params``: pytree whose leaves lead with the stage axis
    ``[S, ...]`` (sharded over ``axis_name``).  ``x``: ``[B, ...]`` with
    ``B`` divisible by ``num_microbatches``; output has the same shape.

    The homogeneous case IS the heterogeneous pipeline with identity
    boundary stages (one schedule implementation — a fix to the GPipe
    machinery cannot drift between the two forms).
    """
    hetero = make_hetero_pipeline_apply(
        _identity_stage, stage_fn, _identity_stage, mesh,
        num_microbatches, axis_name,
    )

    def apply(stacked_params, x):
        return hetero({"embed": (), "block": stacked_params, "head": ()}, x)

    return apply


def sequential_apply(stage_fn: StageFn, stacked_params: Any, x: jnp.ndarray):
    """Reference semantics: stages applied one after another (no pipeline)."""
    S = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    for s in range(S):
        params_s = jax.tree_util.tree_map(lambda p: p[s], stacked_params)
        x = stage_fn(params_s, x)
    return x


def make_hetero_pipeline_apply(
    embed_fn: StageFn,
    block_fn: StageFn,
    head_fn: StageFn,
    mesh: Mesh,
    num_microbatches: int,
    axis_name: str = "pp",
    _loop_steps: int | None = None,
):
    """Heterogeneous pipeline: ``embed -> S blocks -> head`` over ``pp=S``
    (VERDICT r4 #8 — distinct stage params, not just stacked clones).

    Params are one pytree ``{"embed": E, "block": B, "head": H}`` where
    ``B``'s leaves lead with the stage axis ``[S, ...]`` (sharded over
    ``axis_name`` — the N-block bulk is what pipeline parallelism exists
    to partition) while the boundary trees ``E``/``H`` ride replicated
    (they are small, and only stage 0 / stage S-1 consume them).

    Shapes stay uniform without a stage-indexed ``lax.switch``: the raw
    input only ever feeds ``embed_fn`` (a ``lax.cond`` runs it on stage 0
    only, from that device's local copy of the microbatch), the
    inter-stage carry is always the block width, and ``head_fn``'s output
    (``lax.cond``-gated to the last stage's active steps) goes to a
    separate collection buffer, never onto the pipe.

    Schedule: GPipe, ``M + S - 1`` steps (``M`` microbatches) — the bubble
    fraction is ``(S-1)/(M+S-1)``; ``tests/test_pipeline.py`` asserts the
    schedule is exactly tight (one step fewer drops a microbatch).

    ``apply({"embed","block","head"}, x[B, ...]) -> y[B, ..., out_dim]``.
    """
    M = num_microbatches

    def body(params, x):
        S = jax.lax.psum(1, axis_name)
        stage = jax.lax.axis_index(axis_name)
        block_local = jax.tree_util.tree_map(lambda p: p[0], params["block"])
        B = x.shape[0]
        mb = B // M
        mbs = x.reshape((M, mb) + x.shape[1:])

        # carry width = block output width; shapes only, no runtime flops
        x0_shape = jax.eval_shape(embed_fn, params["embed"], mbs[0])
        out_shape = jax.eval_shape(head_fn, params["head"], x0_shape)
        out0 = jnp.zeros((M,) + out_shape.shape, out_shape.dtype)
        cur0 = jnp.zeros(x0_shape.shape, x0_shape.dtype)

        def step(t, carry):
            outputs, cur = carry
            k = t - stage  # microbatch index flowing through this stage
            active = jnp.logical_and(k >= 0, k < M)
            k_safe = jnp.clip(k, 0, M - 1)
            # boundary stages are lax.cond-gated, not computed-then-masked:
            # a jnp.where would run embed on every stage and head on every
            # (stage, step) pair — S*(M+S-1) head applications where only
            # the last stage's M active steps carry real data.  cond skips
            # the FLOPs entirely on the stages/steps that discard them.
            x_in = jax.lax.cond(
                stage == 0,
                lambda: embed_fn(params["embed"], mbs[k_safe]),
                lambda: cur,
            )
            y = block_fn(block_local, x_in)
            y = jnp.where(active, y, jnp.zeros_like(y))
            outputs = jax.lax.cond(
                jnp.logical_and(active, stage == S - 1),
                lambda o: o.at[k_safe].set(head_fn(params["head"], y)),
                lambda o: o,
                outputs,
            )
            # non-cyclic right shift: stage i -> i+1 (stage 0 receives zeros)
            nxt = jax.lax.ppermute(
                y, axis_name, [(i, i + 1) for i in range(S - 1)]
            )
            return outputs, nxt

        n_steps = (M + S - 1) if _loop_steps is None else _loop_steps
        outputs, _ = jax.lax.fori_loop(0, n_steps, step, (out0, cur0))
        # only the last stage holds real outputs; psum replicates them
        outputs = jax.lax.psum(
            jnp.where(stage == S - 1, outputs, jnp.zeros_like(outputs)),
            axis_name,
        )
        return outputs.reshape((B,) + outputs.shape[2:])

    sharded = shard_map(
        body,
        mesh=mesh,
        in_specs=({"embed": P(), "block": P(axis_name), "head": P()}, P()),
        out_specs=P(),
        check_rep=False,
    )
    pp = mesh.shape[axis_name]

    def apply(params, x):
        for path, leaf in jax.tree_util.tree_flatten_with_path(params["block"])[0]:
            if leaf.shape[0] != pp:
                raise ValueError(
                    f"stacked block-stage axis {leaf.shape[0]} != pp={pp} at "
                    f"{jax.tree_util.keystr(path)}; one block per pp device"
                )
        if x.shape[0] % M != 0:
            raise ValueError(
                f"batch {x.shape[0]} not divisible by num_microbatches={M}"
            )
        return sharded(params, x)

    return apply


def hetero_sequential_apply(
    embed_fn: StageFn,
    block_fn: StageFn,
    head_fn: StageFn,
    params: Any,
    x: jnp.ndarray,
):
    """Single-device reference for :func:`make_hetero_pipeline_apply`."""
    y = embed_fn(params["embed"], x)
    y = sequential_apply(block_fn, params["block"], y)
    return head_fn(params["head"], y)
