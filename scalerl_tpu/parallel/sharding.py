"""Sharding rules: how trajectories, batches, and params lay out on a mesh.

The reference's data-parallel contract is "each DDP rank samples its own
minibatch; NCCL all-reduces gradients" (``scalerl/data/replay_data.py:8-26``
+ ``accelerator.backward``, ``dqn_agent.py:173``).  Here the same contract is
*declarative*: trajectories are sharded on their batch dim over ``dp`` (and
``fsdp``), params are replicated over ``dp`` and optionally sharded over
``fsdp``/``tp``, and GSPMD inserts the gradient ``psum`` over ICI.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, batch_dim: int = 0) -> NamedSharding:
    """Shard dim ``batch_dim`` over the data-parallel axes ``(dp, fsdp)``.

    fsdp participates in batch sharding (standard ZeRO-style layout): the
    global batch splits over dp×fsdp, while *params* shard only over fsdp.
    """
    spec = [None] * batch_dim + [("dp", "fsdp")]
    return NamedSharding(mesh, P(*spec))


def trajectory_sharding(mesh: Mesh) -> NamedSharding:
    """Time-major ``[T+1, B, ...]`` chunks shard on the batch dim (dim 1)."""
    return batch_sharding(mesh, batch_dim=1)


def _path_names(path: Tuple[Any, ...]) -> Tuple[str, ...]:
    return tuple(
        str(getattr(p, "name", getattr(p, "key", getattr(p, "idx", p))))
        for p in path
    )


def batch_sharding_tree(batch_example: Any, mesh: Mesh, time_major: bool = True) -> Any:
    """Per-leaf NamedSharding pytree for a batch.

    Trajectory pytrees mix layouts: rollout tensors are time-major
    ``[T+1, B, ...]`` (batch dim 1) while recurrent ``core_state`` leaves
    are ``[B, ...]`` (batch dim 0) — see ``data/trajectory.py``.  Leaves
    whose path passes through ``core_state`` (or any rank-1+ leaf when
    ``time_major=False``) shard dim 0; the rest shard dim 1.
    """

    def spec_for(path, x):
        if not hasattr(x, "ndim") or x.ndim == 0:
            return NamedSharding(mesh, P())
        dim = 0 if (not time_major or "core_state" in _path_names(path)) else 1
        if x.ndim <= dim:
            return NamedSharding(mesh, P())
        return batch_sharding(mesh, batch_dim=dim)

    return jax.tree_util.tree_map_with_path(spec_for, batch_example)


def infer_param_spec(
    path: Tuple[Any, ...],
    x: Any,
    mesh: Mesh,
    axes: Tuple[str, ...] = ("fsdp", "tp"),
    min_shard: int = 8,
) -> P:
    """Pick a PartitionSpec for one param leaf.

    Rule (applies to any Flax/Haiku pytree without model surgery): for
    arrays of rank >= 2, shard the largest divisible dim over ``axes[0]``
    and, if a second divisible dim exists, over ``axes[1]``.  Rank-0/1 and
    non-divisible leaves replicate.  This yields real fsdp/tp layouts for
    the conv/fc stacks of AtariNet-class models; bespoke models can pass
    explicit specs instead.

    ``min_shard``: a dim is only sharded if every shard keeps at least
    this many elements.  Tiny dims (e.g. a ``[hidden, num_actions]`` policy
    head's action dim) otherwise get 2-3-element shards, and the *gradient*
    of the head's activation then carries conflicting shardings from its
    two uses — GSPMD resolves that with an involuntary full
    rematerialization (replicate-then-repartition) of the whole ``[T, B,
    A]`` logits gradient, a multi-chip perf cliff on real models.
    """
    if not hasattr(x, "ndim") or x.ndim < 2:
        return P()
    sizes = {a: mesh.shape[a] for a in axes if mesh.shape.get(a, 1) > 1}
    if not sizes:
        return P()
    spec: list = [None] * x.ndim
    # largest dims first so the big matmul dims absorb the sharding
    order = sorted(range(x.ndim), key=lambda d: -x.shape[d])
    for axis_name in axes:
        n = mesh.shape.get(axis_name, 1)
        if n <= 1:
            continue
        for d in order:
            if (
                spec[d] is None
                and x.shape[d] % n == 0
                and x.shape[d] >= max(2, min_shard) * n
            ):
                spec[d] = axis_name
                break
    return P(*spec)


def has_scanned_params(tree: Any) -> bool:
    """True when the pytree carries ``nn.scan`` core parameters (flax
    prefixes the scanned module's name with ``Scan``, e.g.
    ``Scan_LSTMCore_0``)."""
    for path, _ in jax.tree_util.tree_flatten_with_path(tree)[0]:
        if any(str(n).startswith("Scan") for n in _path_names(path)):
            return True
    return False


def param_sharding(
    params: Any, mesh: Mesh, axes: Tuple[str, ...] = ("fsdp", "tp")
) -> Any:
    """NamedSharding pytree for a param/optimizer pytree (fsdp/tp rule).

    Recurrent exception (the ``test_r2d2_enable_mesh_matches_unsharded``
    root cause): when the tree carries ``nn.scan`` core params, EVERY leaf
    replicates — batch-parallel only.  The scan's transpose (backward)
    pass stacks per-step residuals ``[T, B, feat]`` as while-loop carries;
    with any fsdp/tp-sharded param feeding the scan, GSPMD must reshard
    those carries from batch-sharded to feature-sharded layouts, which it
    can only do via an *involuntary full rematerialization* of the loop
    carry (spmd_partitioner "You probably want to enrich the sharding
    annotations"), and with a non-divisible feature dim the padded remat
    produces gradients that are numerically WRONG (~8% loss drift at
    hidden=16, not reduction-reorder noise).  Replicated params make the
    meshed step bitwise-identical to single-device at the same global
    batch; the memory win of fsdp never mattered for LSTM-sized cores.
    """
    if axes and has_scanned_params(params):
        axes = ()
    return jax.tree_util.tree_map_with_path(
        lambda path, x: NamedSharding(mesh, infer_param_spec(path, x, mesh, axes=axes)),
        params,
    )


def shard_params(params: Any, mesh: Mesh) -> Any:
    """Device-put a param pytree with the inferred fsdp/tp layout."""
    return jax.device_put(params, param_sharding(params, mesh))


def shard_batch(batch: Any, mesh: Mesh, batch_dim: int = 0) -> Any:
    """Device-put a host batch pytree sharded on its batch dimension."""
    sh = batch_sharding(mesh, batch_dim)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), batch)


def pad_to_multiple(x: np.ndarray, multiple: int, axis: int) -> np.ndarray:
    """Host-side pad so a dim divides the mesh (static shapes for XLA)."""
    size = x.shape[axis]
    rem = size % multiple
    if rem == 0:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, multiple - rem)
    return np.pad(x, pad)
