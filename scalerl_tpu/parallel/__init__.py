"""Multi-chip parallelism: device meshes, sharding rules, pjit train steps.

This package is the TPU-native replacement for every distributed-compute
mechanism in the reference (SURVEY.md §5 "Distributed communication
backend"):

- HF Accelerate / ``torch.distributed`` NCCL all-reduce
  (``scalerl/algorithms/dqn/dqn_agent.py:173-174``,
  ``scalerl/trainer/off_policy.py:118-126``) becomes a ``jax.sharding.Mesh``
  over ICI with the batch axis of the trajectory sharded on ``dp`` — XLA's
  GSPMD partitioner inserts the gradient ``psum`` automatically.
- The ``accelerate_config.yaml`` topology file becomes a one-line mesh spec
  string, e.g. ``"dp=4,fsdp=2"`` (``MeshSpec.parse``).
- Multi-node rendezvous (``hpc/worker.py:300-341`` entry handshake) becomes
  ``jax.distributed.initialize`` (``multihost.py``).

Axis vocabulary (fixed, in mesh order):
``dp`` (data), ``fsdp`` (param/optimizer shards), ``tp`` (tensor,
heuristic), ``sp`` (sequence/context), ``ep`` (expert), ``mp`` (model —
the named axis of the dp×mp sharded learner plane, driven by the logical
rule table in ``parallel/logical.py``).  RL parity only *needs* ``dp``
(SURVEY.md §2.4 parallelism inventory), but the mesh reserves the rest so
long-context policies (ring attention over ``sp``) and sharded param states
drop in without re-plumbing.
"""

from scalerl_tpu.parallel.mesh import (  # noqa: F401
    AXIS_NAMES,
    mesh_spec_from_args,
    resolve_mesh,
    MeshSpec,
    make_mesh,
)
from scalerl_tpu.parallel.sharding import (  # noqa: F401
    batch_sharding,
    has_scanned_params,
    infer_param_spec,
    param_sharding,
    replicated,
    shard_batch,
    shard_params,
    trajectory_sharding,
)
from scalerl_tpu.parallel.logical import (  # noqa: F401
    LOGICAL_RULES,
    activation_constraint,
    has_mp_params,
    make_shard_and_gather_fns,
    mp_param_sharding,
    mp_param_spec,
)
from scalerl_tpu.parallel.pipeline import (  # noqa: F401
    hetero_sequential_apply,
    make_hetero_pipeline_apply,
    make_pipeline_apply,
    sequential_apply,
)
from scalerl_tpu.parallel.train_step import (  # noqa: F401
    enable_offpolicy_mesh,
    fp32_optimizer_state,
    make_parallel_act_fn,
    make_parallel_learn_fn,
    maybe_enable_mesh_from_args,
)
from scalerl_tpu.parallel.multihost import initialize_multihost  # noqa: F401
from scalerl_tpu.parallel.sequence import (  # noqa: F401
    make_sequence_parallel_apply,
)
