"""Sequence/context parallelism: run a transformer policy with the time
axis sharded over the mesh's ``sp`` axis.

No counterpart in the reference (SURVEY.md §5: long-context machinery is
absent there); this wires :func:`scalerl_tpu.ops.ring_attention.ring_attention`
into :class:`scalerl_tpu.models.transformer.TransformerPolicy` under
``shard_map``: attention communicates k/v blocks neighbor-to-neighbor over
ICI while every position-wise layer runs shard-locally.  Memory per device
is O(T / sp), enabling trajectory contexts far beyond one chip's HBM.
"""

from __future__ import annotations

import functools

import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from scalerl_tpu.models.transformer import TransformerPolicy, TransformerOutput
from scalerl_tpu.ops.ring_attention import ring_attention


def make_sequence_parallel_apply(
    model: TransformerPolicy, mesh: Mesh, axis_name: str = "sp"
):
    """Build ``apply(params, obs) -> TransformerOutput`` with ``obs``
    ``[B, T, F]`` sequence-sharded on ``axis_name`` and params replicated.

    Positional embeddings stay globally correct: each shard computes its
    global step offset from its ring index inside the shard_map body.
    """
    ring = functools.partial(ring_attention, axis_name=axis_name, causal=True)
    sp_model = model.clone(attn_fn=ring)

    def shard_body(params, obs):
        import jax

        B, T_local = obs.shape[:2]
        offset = jax.lax.axis_index(axis_name) * T_local
        positions = jnp.broadcast_to(
            offset + jnp.arange(T_local), (B, T_local)
        )
        return sp_model.apply(params, obs, positions=positions)

    seq = P(None, axis_name)
    sharded = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(P(), P(None, axis_name, None)),
        out_specs=TransformerOutput(P(None, axis_name, None), seq),
        check_rep=False,
    )
    sp = mesh.shape[axis_name]

    def apply(params, obs):
        # Validate against the *global* sequence length here, outside the
        # shard_map body: inside, the model only sees T/sp local steps, so
        # its own max_len guard cannot catch a too-long global sequence —
        # out-of-range positions would silently clamp onto the last
        # positional-embedding row.
        T = obs.shape[1]
        if T > model.max_len:
            raise ValueError(
                f"global sequence length {T} exceeds max_len={model.max_len}"
            )
        if T % sp != 0:
            raise ValueError(
                f"global sequence length {T} not divisible by sp={sp}"
            )
        return sharded(params, obs)

    return apply
