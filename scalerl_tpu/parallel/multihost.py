"""Multi-host (DCN) bring-up: the fleet-rendezvous capability, JAX-native.

The reference bootstraps a multi-node fleet with a hand-rolled TCP entry
handshake on port 9999 (``scalerl/hpc/worker.py:300-341``: worker sends its
arg dict, server assigns a base worker id and returns the full config).
For the *mesh* itself JAX ships this: ``jax.distributed.initialize`` against
a coordinator address enrolls every host's chips into one global device
set.  Off-mesh CPU actor fleets still use the explicit transport in
``scalerl_tpu.runtime`` (the hpc-protocol parity lives there).
"""

from __future__ import annotations

import os
from typing import Optional

import jax

from scalerl_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[list] = None,
) -> bool:
    """Join the global JAX runtime; returns True if distributed init ran.

    All-``None`` args fall back to env autodetection (TPU pod metadata or
    ``JAX_COORDINATOR_ADDRESS``/``JAX_NUM_PROCESSES``/``JAX_PROCESS_ID``),
    and a plain single-host run is a no-op — so trainers can call this
    unconditionally, the way the reference calls ``Accelerator()``
    unconditionally (``examples/test_dqn.py:17``).
    """
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    if num_processes is None and os.environ.get("JAX_NUM_PROCESSES"):
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and os.environ.get("JAX_PROCESS_ID"):
        process_id = int(os.environ["JAX_PROCESS_ID"])

    if coordinator_address is None and num_processes is None:
        # single-host (or TPU-pod autodetect handled by jax itself on real
        # pod slices); nothing to do.
        return False
    # CPU backends need an explicit cross-process collectives implementation:
    # without one the client forms (rendezvous succeeds, device_count sums)
    # but the FIRST multi-process computation dies with "Multiprocess
    # computations aren't implemented on the CPU backend".  Gloo ships in
    # jaxlib; select it before the backend initializes.  TPU/GPU runtimes
    # bring their own collectives and ignore this knob, and older jax
    # versions without the option fall through to the previous behavior
    # (the multihost tests skip via tests/multihost_support.py's probe).
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu") or (
        jax.config.jax_platforms or ""
    ).startswith("cpu"):
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # noqa: BLE001 — option absent in this jax version
            logger.warning(
                "jax_cpu_collectives_implementation unavailable; "
                "multi-process CPU collectives may be unsupported"
            )
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    logger.info(
        "multihost: process %d/%d, %d local / %d global devices",
        jax.process_index(),
        jax.process_count(),
        jax.local_device_count(),
        jax.device_count(),
    )
    return True
