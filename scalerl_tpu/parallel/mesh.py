"""Device-mesh construction from a one-line spec string.

Replaces the reference's launcher topology file
(``examples/configs/accelerate_config.yaml:1-17`` — machine/GPU counts for
``accelerate``) with ``"dp=4,fsdp=2"``-style specs parsed into a
``jax.sharding.Mesh``.  Axes not named in the spec get size 1, so downstream
``PartitionSpec``s can always refer to the full axis vocabulary.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

# Fixed axis order.  dp outermost (DCN/ICI-friendly data parallel), then
# pipeline stages, then the param-sharding axis, then tensor / sequence /
# expert / model innermost where collectives are most frequent and must ride
# the fastest ICI hops.  ``mp`` is the named model-parallel axis of the
# big-policy learner plane (Podracer's dp×mp recipe): transformer/MoE
# weights shard their heads/mlp/vocab/expert dims over it via the logical
# rules in ``parallel/logical.py``, while ``tp`` remains the generic
# heuristic tensor axis of :func:`scalerl_tpu.parallel.sharding
# .infer_param_spec` — two different sharding policies, two names.
AXIS_NAMES: Tuple[str, ...] = ("dp", "pp", "fsdp", "tp", "sp", "ep", "mp")


@dataclass(frozen=True)
class MeshSpec:
    """Parsed mesh shape, e.g. ``MeshSpec.parse("dp=4,tp=2")``."""

    sizes: Dict[str, int] = field(default_factory=dict)

    @classmethod
    def parse(cls, spec: Optional[str]) -> "MeshSpec":
        sizes: Dict[str, int] = {}
        if spec:
            for part in spec.replace(" ", "").split(","):
                if not part:
                    continue
                name, _, val = part.partition("=")
                if name not in AXIS_NAMES:
                    raise ValueError(
                        f"unknown mesh axis {name!r}; valid axes: {AXIS_NAMES}"
                    )
                sizes[name] = int(val)
        return cls(sizes=sizes)

    def size(self, axis: str) -> int:
        return self.sizes.get(axis, 1)

    @property
    def total(self) -> int:
        n = 1
        for v in self.sizes.values():
            n *= v
        return n

    def shape(self) -> Tuple[int, ...]:
        return tuple(self.size(a) for a in AXIS_NAMES)


def make_mesh(
    spec: Optional[str] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a Mesh over ``devices`` (default: all) from a spec string.

    With no spec, all devices go on ``dp`` — the pure data-parallel layout
    that is the reference's only multi-device mode (MULTI_GPU DDP,
    ``accelerate_config.yaml:3``).  Unnamed axes get size 1 so every
    ``PartitionSpec`` over :data:`AXIS_NAMES` resolves.
    """
    devices = list(devices if devices is not None else jax.devices())
    parsed = MeshSpec.parse(spec)
    sizes = dict(parsed.sizes)
    named_total = parsed.total
    if spec is None or not sizes:
        sizes = {"dp": len(devices)}
        named_total = len(devices)
    if named_total != len(devices):
        raise ValueError(
            f"mesh spec {spec!r} wants {named_total} devices, got {len(devices)}"
        )
    shape = tuple(sizes.get(a, 1) for a in AXIS_NAMES)
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, AXIS_NAMES)


def resolve_mesh(mesh_or_spec) -> Mesh:
    """A ``Mesh`` passes through; a spec string (or ``None``) builds one —
    the one resolution rule shared by every ``enable_mesh`` entry point."""
    if isinstance(mesh_or_spec, Mesh):
        return mesh_or_spec
    return make_mesh(mesh_or_spec)


def mesh_spec_from_args(args, n_devices: Optional[int] = None) -> Optional[str]:
    """The mesh spec an ``RLArguments`` asks for, or ``None``.

    An explicit ``mesh_shape`` string wins (power-user escape hatch: any
    axis combination).  Otherwise ``dp_size``/``mp_size`` compose the
    sharded-learner topology ``"dp=D,mp=M"``: ``mp_size > 1`` (or
    ``dp_size > 0``) opts in, and ``dp_size == 0`` takes every remaining
    device (``n_devices // mp_size``) — the one-knob path the trainer
    families resolve through ``maybe_enable_mesh_from_args``.
    """
    spec = getattr(args, "mesh_shape", None)
    if spec:
        return spec
    mp = int(getattr(args, "mp_size", 1) or 1)
    dp = int(getattr(args, "dp_size", 0) or 0)
    if mp <= 1 and dp <= 0:
        return None
    if dp <= 0:
        if n_devices is None:
            n_devices = len(jax.devices())
        if n_devices % mp != 0:
            raise ValueError(
                f"mp_size={mp} does not divide the {n_devices} visible "
                "devices; set dp_size explicitly or adjust mp_size"
            )
        dp = n_devices // mp
    if mp <= 1:
        return f"dp={dp}"
    return f"dp={dp},mp={mp}"
