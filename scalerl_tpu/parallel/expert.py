"""Expert parallelism: run an MoE model with experts sharded over ``ep``.

Completes the mesh's parallelism families (SURVEY.md §2.4 lists EP as
absent in the reference).  No shard_map needed: the expert-batched einsums
of :class:`scalerl_tpu.models.moe.MoEMLP` carry an ``[E, ...]`` leading
axis, so sharding the expert params and constraining the dispatched-token
tensor over ``ep`` lets GSPMD derive the token all-to-alls.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def expert_param_sharding(params: Any, mesh: Mesh) -> Any:
    """NamedSharding pytree: expert-leading tensors (``w_in``/``w_out``,
    dim0 = num_experts) over ``ep``; everything else replicated."""

    def rule(path, leaf):
        name = str(path[-1].key) if path else ""
        ep = mesh.shape.get("ep", 1)
        if name in ("w_in", "w_out") and leaf.ndim == 3 and leaf.shape[0] % ep == 0:
            return NamedSharding(mesh, P("ep", None, None))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(rule, params)


def make_expert_parallel_apply(model, mesh: Mesh, params: Any):
    """jit ``model.apply`` with experts sharded over ``ep``.

    Returns ``(apply_fn, sharded_params)``; inputs stay replicated (token
    dispatch redistributes work across experts, hence across ``ep``).
    """
    p_sh = expert_param_sharding(params, mesh)
    sharded_params = jax.device_put(params, p_sh)
    rep = NamedSharding(mesh, P())

    apply_fn = jax.jit(
        model.apply, in_shardings=(p_sh, rep), out_shardings=None
    )
    return apply_fn, sharded_params
