"""pjit'd learn/act steps: the DDP-learner capability, TPU-native.

``make_parallel_learn_fn`` is the one-call replacement for the reference's
whole Accelerate integration (``accelerator.prepare`` + DDP wrapping +
``accelerator.backward`` NCCL all-reduce, ``dqn_agent.py:194-198,173-174``):
give it any pure ``(state, batch) -> (state, metrics)`` update and a mesh,
and it returns the same function jit-compiled with the batch sharded over
``dp`` and the train state laid out per the fsdp/tp param rule.  GSPMD
derives the gradient ``psum`` over ICI — there is no user-level collective
to maintain.

``make_parallel_act_fn`` shards central batched inference (SEED-RL acting
path) over the same mesh, so one learner host can serve actor fleets whose
aggregate batch exceeds a single chip.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp

from scalerl_tpu.parallel.sharding import (
    batch_sharding,
    batch_sharding_tree,
    param_sharding,
    replicated,
)


# ---------------------------------------------------------------------------
# numerical fault tolerance: the all-finite update guard


def nonfinite_score(tree: Any) -> jnp.ndarray:
    """Scalar f32 that is ``0.0`` when every inexact leaf of ``tree`` is
    finite and NaN otherwise — ONE fused multiply+reduce per leaf.

    ``x * 0`` maps finite values to ``0`` and NaN/Inf to NaN, so the sum of
    the zeroed leaves is exactly the verdict: no boolean plane is ever
    materialized and the whole check fuses into a single reduction tree
    whose scalar can ride the batched per-chunk metric read.  Integer/bool
    leaves (step counters, indices) are skipped — they cannot go NaN.
    """
    leaves = [
        x
        for x in jax.tree_util.tree_leaves(tree)
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.inexact)
    ]
    if not leaves:
        return jnp.float32(0.0)
    total = jnp.float32(0.0)
    for x in leaves:
        total = total + jnp.sum(x.astype(jnp.float32) * 0.0)
    return total


def tree_all_finite(tree: Any) -> jnp.ndarray:
    """Scalar bool: every inexact (float/complex) leaf of ``tree`` is
    finite (computed via the fused :func:`nonfinite_score` reduction)."""
    return jnp.isfinite(nonfinite_score(tree))


def guard_nonfinite_updates(
    learn_fn: Callable, check_every: int = 1
) -> Callable:
    """Wrap a pure ``(state, *args) -> (state, metrics, *aux)`` update so a
    non-finite result SKIPS the step instead of poisoning the run.

    jit-compatible by construction: the candidate update always runs; a
    ``lax.cond`` then gates which state survives — the candidate when every
    inexact leaf is finite, the *input* state otherwise (one exploding batch
    costs one skipped step, not the whole run).  On a skipped step the aux
    outputs (e.g. per-sample |TD| feeding PER priorities) are sanitized to
    finite zeros so NaN can't leak into replay through the feedback path.

    The finiteness verdict is the single fused :func:`nonfinite_score`
    reduction — no per-leaf boolean planes — and its counters ride the
    metrics dict and therefore the existing ONE batched device->host
    transfer per chunk (PR 1/PR 3 discipline): ``nonfinite_grads`` /
    ``skipped_steps`` (the host-side divergence tripwire counts consecutive
    ones).  Inside a scanned fused driver these are per-iteration flags
    that the chunk-mean reduces to a fraction.

    ``check_every`` (``RLArguments.nonfinite_check_every``) amortizes the
    guard: the reduction + state select run only on steps where
    ``state.step % check_every == 0`` (a ``lax.cond`` on the step counter —
    the *skipped* branch is a pure pass-through, so K-1 of every K steps
    pay nothing).  K=1 preserves the original check-every-step semantics; a
    divergence under K>1 is caught within K-1 steps of surfacing, which the
    tripwire's consecutive-skip window already tolerates.  States without a
    ``step`` field fall back to checking every step.

    Works under ``shard_map``: gradients are psum-ed before the optimizer
    update, so every shard evaluates the same candidate state and reaches
    the same verdict.
    """

    def guarded(state, *args):
        out = learn_fn(state, *args)
        new_state, metrics, aux = out[0], dict(out[1]), tuple(out[2:])

        def run_check(_):
            ok = tree_all_finite((new_state, aux))

            def keep(_):
                return new_state, aux

            def skip(_):
                safe_aux = jax.tree_util.tree_map(
                    lambda x: jnp.nan_to_num(
                        x, nan=0.0, posinf=0.0, neginf=0.0
                    )
                    if hasattr(x, "dtype")
                    and jnp.issubdtype(x.dtype, jnp.inexact)
                    else x,
                    aux,
                )
                return state, safe_aux

            safe_state, safe_aux = jax.lax.cond(ok, keep, skip, None)
            return safe_state, safe_aux, 1.0 - ok.astype(jnp.float32)

        def pass_through(_):
            return new_state, aux, jnp.float32(0.0)

        step = getattr(state, "step", None)
        if check_every > 1 and step is not None:
            do_check = (step % check_every) == 0
            safe_state, safe_aux, bad = jax.lax.cond(
                do_check, run_check, pass_through, None
            )
        else:
            safe_state, safe_aux, bad = run_check(None)
        metrics["nonfinite_grads"] = bad
        metrics["skipped_steps"] = bad
        return (safe_state, metrics) + safe_aux

    return guarded


def maybe_guard_nonfinite(learn_fn: Callable, args: Any) -> Callable:
    """Apply :func:`guard_nonfinite_updates` unless the config disabled it.

    Two off switches, different costs: ``RLArguments.nonfinite_guard=False``
    and the environment fast-off ``SCALERL_NONFINITE_GUARD=0`` both return
    ``learn_fn`` untouched — the guard is *compiled out entirely* (no cond,
    no reduction, no counters in the metrics dict), not skipped at runtime.
    The env var exists so a bench/bisect run can toggle the guard without
    plumbing a config change through every trainer (the r05 regression
    bisect protocol, docs/PERFORMANCE.md).  ``nonfinite_check_every``
    amortizes the enabled guard instead of removing it.
    """
    import os

    if os.environ.get("SCALERL_NONFINITE_GUARD") == "0":
        return learn_fn
    if getattr(args, "nonfinite_guard", True):
        return guard_nonfinite_updates(
            learn_fn, check_every=getattr(args, "nonfinite_check_every", 1)
        )
    return learn_fn


def make_parallel_learn_fn(
    learn_fn: Callable[[Any, Any], Tuple[Any, Any]],
    mesh,
    state_example: Any,
    batch_example: Any = None,
    batch_time_major: bool = True,
    donate_state: bool = True,
    param_specs: Any = None,
) -> Callable[[Any, Any], Tuple[Any, Any]]:
    """jit ``learn_fn`` with dp-sharded batch + sharded train state.

    State layout: ``param_specs`` (a per-leaf ``NamedSharding`` pytree —
    the mp logical-rule layout from ``parallel/logical.py`` for the
    transformer/MoE families) when given, else the heuristic fsdp/tp rule
    (``param_sharding``).  The pre-update state is DONATED by default: the
    sharded buffers of the previous step back the new step's output, so a
    billion-parameter fp32+opt state costs one copy of HBM, not two
    (graftlint JG005 pins every caller to the ``state = step(state, ...)``
    rebind idiom).

    The returned callable carries helpers:

    - ``.shard_state(state)`` — one-time device_put of the train state into
      its mesh layout (counters replicated);
    - ``.shard_batch(batch)`` — device_put a host batch pytree with its
      batch dim split over ``dp×fsdp`` (dim 1 for time-major trajectories);
    - ``.state_sharding`` / ``.batch_sharding`` — the NamedSharding pytrees.
    """
    st_sh = param_specs if param_specs is not None else param_sharding(state_example, mesh)
    if batch_example is not None:
        data_sh = batch_sharding_tree(batch_example, mesh, time_major=batch_time_major)
    else:
        # no example: leave the batch sharding UNSPECIFIED so jit follows
        # whatever layout ``shard_batch`` committed.  A single broadcast
        # NamedSharding would mis-shard mixed-layout pytrees (recurrent
        # ``core_state`` leaves are [B, ...], not [T+1, B, ...]).
        data_sh = None
    rep = replicated(mesh)

    jitted = jax.jit(
        learn_fn,
        in_shardings=(st_sh, data_sh),
        out_shardings=(st_sh, rep),
        donate_argnums=(0,) if donate_state else (),
    )

    def shard_state(state: Any) -> Any:
        return jax.device_put(state, st_sh)

    # batch sharding depends only on the pytree structure and per-leaf
    # ranks (batch_sharding_tree reads ndim + path, never sizes), so cache
    # it — replay/trajectory batches have a fixed shape after the first
    # sample and the hot learner loop calls shard_batch every step
    _sh_cache: dict = {}

    def _check_divisible(batch: Any, sh: Any) -> None:
        # fail fast with an actionable message instead of an opaque XLA
        # "dimension not divisible" error at the first learn step
        def chk(x, s):
            spec = getattr(s, "spec", None)
            if spec is None or not hasattr(x, "shape"):
                return
            for d, axes in enumerate(spec):
                if axes is None:
                    continue
                names = (axes,) if isinstance(axes, str) else tuple(axes)
                extent = 1
                for a in names:
                    extent *= mesh.shape[a]
                if extent > 1 and x.shape[d] % extent != 0:
                    raise ValueError(
                        f"batch dim {d} of size {x.shape[d]} must divide by "
                        f"the mesh extent {extent} (axes {names}) to shard; "
                        "adjust batch_size/num_envs or the mesh shape"
                    )

        jax.tree_util.tree_map(chk, batch, sh)

    def shard_batch(batch: Any) -> Any:
        if data_sh is not None:
            _check_divisible(batch, data_sh)
            return jax.device_put(batch, data_sh)
        leaves, treedef = jax.tree_util.tree_flatten(batch)
        key = (treedef, tuple(getattr(x, "ndim", 0) for x in leaves))
        sh = _sh_cache.get(key)
        if sh is None:
            sh = batch_sharding_tree(batch, mesh, time_major=batch_time_major)
            _sh_cache[key] = sh
        _check_divisible(batch, sh)
        return jax.device_put(batch, sh)

    jitted.shard_state = shard_state  # type: ignore[attr-defined]
    jitted.shard_batch = shard_batch  # type: ignore[attr-defined]
    jitted.state_sharding = st_sh  # type: ignore[attr-defined]
    jitted.batch_sharding = data_sh  # type: ignore[attr-defined]
    return jitted


def fp32_optimizer_state(tx):
    """bf16 params / fp32 optimizer state: wrap an optax transformation so
    its state (moments, scales) lives in float32 while the params — and
    the gradients the backward pass produces — stay bfloat16.

    The standard mixed-precision recipe for the sharded big-model learner
    (bf16 halves the param HBM and feeds the MXU at full rate, fp32
    moments keep RMSProp/Adam numerically stable): ``init`` builds the
    base state from an fp32 view of the params; ``update`` upcasts grads
    and params to fp32, runs the base chain, and downcasts the updates
    back to each param's own dtype so ``optax.apply_updates`` never
    promotes the params to fp32.
    """
    import optax as _optax

    def _cast(tree, dtype):
        return jax.tree_util.tree_map(
            lambda x: x.astype(dtype)
            if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.inexact)
            else x,
            tree,
        )

    def init(params):
        return tx.init(_cast(params, jnp.float32))

    def update(grads, state, params=None):
        g32 = _cast(grads, jnp.float32)
        p32 = _cast(params, jnp.float32) if params is not None else None
        updates, state = tx.update(g32, state, p32)
        updates = jax.tree_util.tree_map(
            lambda u, g: u.astype(g.dtype)
            if hasattr(g, "dtype") and jnp.issubdtype(g.dtype, jnp.inexact)
            else u,
            updates,
            grads,
        )
        return updates, state

    return _optax.GradientTransformation(init, update)


def maybe_enable_mesh_from_args(agent, args) -> bool:
    """Trainer-side mesh hookup: resolve ``RLArguments``'
    ``mesh_shape``/``dp_size``/``mp_size`` into a mesh and enable it on the
    agent.  No-op (returns False) when no mesh is requested, the agent has
    no ``enable_mesh``, or one is already enabled — idempotent, so every
    trainer family calls it unconditionally at construction and an entry
    script that already called ``agent.enable_mesh`` is left alone.
    """
    from scalerl_tpu.parallel.mesh import mesh_spec_from_args

    spec = mesh_spec_from_args(args)
    if spec is None or not hasattr(agent, "enable_mesh"):
        return False
    if getattr(agent, "mesh", None) is not None:
        return False
    agent.enable_mesh(spec)
    return True


def enable_offpolicy_mesh(agent, mesh_or_spec, donate_state: bool = True) -> None:
    """One-call DDP wiring shared by the off-policy agent families.

    The agent contract: ``args.batch_size``, ``state``, and a raw
    ``_learn_raw(state, batch) -> (state, metrics, td_abs)`` pure update
    (DQN/SAC/TD3 all match).  Shards the replay batch dim over ``dp×fsdp``,
    big params over ``fsdp/tp`` where divisible, lets GSPMD all-reduce
    gradients over ICI, and returns the per-sample |TD| replicated for PER
    feedback.  Sets ``agent.mesh`` / ``agent._learn_mesh`` /
    ``agent._shard_batch`` and re-lays-out ``agent.state``; the agents'
    ``learn`` dispatches through ``_learn_mesh`` when present.

    ``donate_state=False`` keeps the pre-update state buffers alive — required
    when actor threads read ``state.params`` concurrently (``ApexTrainer``).
    """
    from scalerl_tpu.parallel.mesh import resolve_mesh

    mesh = resolve_mesh(mesh_or_spec)
    n_batch_shards = mesh.shape["dp"] * mesh.shape["fsdp"]
    if agent.args.batch_size % n_batch_shards != 0:
        raise ValueError(
            f"batch_size ({agent.args.batch_size}) must divide by the "
            f"mesh's dp*fsdp extent ({n_batch_shards}) to shard the "
            "replay batch"
        )
    raw = agent._learn_raw

    def two_out(state, batch):
        # make_parallel_learn_fn expects (state, batch) -> (state, aux);
        # fold the per-sample |TD| into the aux pytree
        state, metrics, td_abs = raw(state, batch)
        return state, (metrics, td_abs)

    plearn = make_parallel_learn_fn(
        two_out, mesh, agent.state,
        batch_time_major=False,  # replay batches are [B, ...]
        donate_state=donate_state,
    )
    agent.mesh = mesh
    agent.state = plearn.shard_state(agent.state)
    agent._shard_batch = plearn.shard_batch
    agent._learn_mesh = plearn


def make_parallel_act_fn(
    act_fn: Callable[..., Any],
    mesh,
    params_example: Any,
) -> Callable[..., Any]:
    """jit an inference fn ``(params, *batch_args) -> ...`` for mesh serving.

    jit with no explicit in_shardings follows the layouts of its inputs, so
    the returned callable's ``.shard_params`` / ``.shard_batch`` helpers
    place params (fsdp/tp rule) and the actor batch (dim 0 over dp) and the
    compiled program runs sharded with GSPMD-inserted collectives.
    """
    p_sh = param_sharding(params_example, mesh)
    b_sh = batch_sharding(mesh, batch_dim=0)

    jitted = jax.jit(act_fn)
    jitted.shard_params = lambda p: jax.device_put(p, p_sh)  # type: ignore[attr-defined]
    jitted.shard_batch = lambda b: jax.tree_util.tree_map(  # type: ignore[attr-defined]
        lambda x: jax.device_put(x, b_sh), b
    )
    return jitted
