"""Named logical-axis sharding rules for the big-model policy families.

The heuristic ``infer_param_spec`` (``parallel/sharding.py``) shards
"whatever dims happen to divide" — fine for conv/fc stacks, wrong for a
transformer, where the *meaning* of each dim decides its axis: attention
heads, the MLP hidden, and the vocab/action head shard over the model axis
while embeddings and residual-stream dims replicate (Megatron layout).
This module is the declarative counterpart, the SNIPPETS.md patterns made
load-bearing:

- snippet [3]'s ``DEFAULT_RULES`` table — logical axis name -> mesh axis —
  becomes :data:`LOGICAL_RULES` with ``"mp"`` as the model axis;
- parameter leaves are classified by their trailing path names (module +
  param), so the same table covers the raw params, the optimizer moments
  (whose pytree paths mirror the params), and any wrapper state without
  model surgery;
- snippet [2]'s ``make_shard_and_gather_fns`` — per-leaf pjit'd placement
  and fetch functions built from partition specs — is
  :func:`make_shard_and_gather_fns`, used by the sharded checkpoint path.

Divisibility guard: a rule only shards a dim when the mesh extent divides
it; otherwise that dim silently replicates (a 6-action policy head on
``mp=4`` replicates instead of erroring — the rule table describes *big*
models, small heads degrade gracefully).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from scalerl_tpu.parallel.sharding import _path_names

# The model-parallel mesh axis of the dp×mp learner plane.
MP_AXIS = "mp"

# Logical axis -> mesh axis (None = replicated), snippet [3] shape.
LOGICAL_RULES: Dict[str, Optional[str]] = {
    "batch": "dp",
    "embed": None,   # residual stream / d_model stays replicated
    "heads": MP_AXIS,  # fused qkv output (num_heads * head_dim)
    "mlp": MP_AXIS,    # MLP hidden (mlp_ratio * d_model)
    "vocab": MP_AXIS,  # policy head output (actions / tokens)
    "experts": MP_AXIS,  # MoE expert-leading tensors (ep folded onto mp)
}

# Trailing-path-name -> per-dim logical axes.  Keys are matched against the
# last one or two path components of each leaf ((module, param) first, then
# the bare leaf name), which makes the table apply equally to
# ``params.block_0.qkv.kernel`` and the RMSProp moment
# ``opt_state[1].nu.params.block_0.qkv.kernel``.
PARAM_LOGICAL_AXES: Dict[Tuple[str, ...], Tuple[Optional[str], ...]] = {
    ("qkv", "kernel"): ("embed", "heads"),
    ("proj", "kernel"): ("heads", "embed"),
    ("mlp_in", "kernel"): ("embed", "mlp"),
    ("mlp_in", "bias"): ("mlp",),
    ("mlp_out", "kernel"): ("mlp", "embed"),
    ("mlp_out", "bias"): ("embed",),
    ("policy_head", "kernel"): ("embed", "vocab"),
    ("policy_head", "bias"): ("vocab",),
    ("value_head", "kernel"): ("embed", None),
    # MoE expert banks: the leading expert dim shards over the model axis
    # (the GShard layout — XLA derives the token all-to-alls from it).
    # The per-expert matmul dims stay unsharded: with ep folded onto mp, a
    # second mp entry would double-map the axis (and expert-internal
    # sharding buys nothing until experts outgrow a chip).
    ("w_in",): ("experts", "embed", None),
    ("w_out",): ("experts", None, "embed"),
}


def logical_to_spec(
    axes: Tuple[Optional[str], ...],
    shape: Tuple[int, ...],
    mesh: Mesh,
    rules: Optional[Dict[str, Optional[str]]] = None,
) -> P:
    """Resolve per-dim logical axes into a PartitionSpec on ``mesh``.

    A dim only shards when its mesh axis has extent > 1 AND divides the dim
    size; everything else replicates.
    """
    rules = rules if rules is not None else LOGICAL_RULES
    parts = []
    used = set()  # a mesh axis may shard at most one dim per tensor
    for dim, logical in enumerate(axes):
        mesh_axis = rules.get(logical) if logical is not None else None
        n = mesh.shape.get(mesh_axis, 1) if mesh_axis else 1
        if mesh_axis and mesh_axis not in used and n > 1 and shape[dim] % n == 0:
            parts.append(mesh_axis)
            used.add(mesh_axis)
        else:
            parts.append(None)
    return P(*parts)


def _match_axes(path: Tuple[Any, ...]) -> Optional[Tuple[Optional[str], ...]]:
    names = _path_names(path)
    for key in (tuple(names[-2:]), (names[-1],) if names else ()):
        if key and key in PARAM_LOGICAL_AXES:
            return PARAM_LOGICAL_AXES[key]
    return None


def mp_param_spec(
    path: Tuple[Any, ...],
    leaf: Any,
    mesh: Mesh,
    rules: Optional[Dict[str, Optional[str]]] = None,
) -> P:
    """PartitionSpec for one param/opt-state leaf under the logical rules.

    Unmatched leaves (embeddings, LayerNorm scales, counters, schedule
    state) replicate — safe by construction.
    """
    axes = _match_axes(path)
    if axes is None or not hasattr(leaf, "ndim") or leaf.ndim != len(axes):
        return P()
    return logical_to_spec(axes, leaf.shape, mesh, rules)


def mp_param_sharding(
    tree: Any,
    mesh: Mesh,
    rules: Optional[Dict[str, Optional[str]]] = None,
) -> Any:
    """Per-leaf ``NamedSharding`` pytree for a train state under the
    logical rule table (heads/mlp/vocab/experts over ``mp``)."""
    return jax.tree_util.tree_map_with_path(
        lambda path, x: NamedSharding(mesh, mp_param_spec(path, x, mesh, rules)),
        tree,
    )


def has_mp_params(tree: Any) -> bool:
    """True when the pytree carries leaves the logical rule table knows how
    to shard — i.e. the model is one of the mp-aware families
    (transformer/MoE policies)."""
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        axes = _match_axes(path)
        if axes is not None and getattr(leaf, "ndim", -1) == len(axes):
            return True
    return False


def activation_constraint(mesh: Mesh, batch_axis: str = "dp") -> Callable:
    """``with_sharding_constraint`` closure for inter-layer activations.

    Pins ``[B, ...]`` tensors to batch-over-``dp``, replicated over ``mp``
    — the residual stream layout between transformer blocks.  GSPMD then
    derives the per-block reshard (split on heads/mlp inside the block,
    rejoin at the residual add) from the weight shardings alone, instead of
    guessing a layout for the whole network and paying involuntary
    reshards.  Carries the mesh inside each ``NamedSharding``, so it works
    under plain ``jax.jit`` with no ambient mesh context.
    """

    def constrain(x):
        if not hasattr(x, "ndim") or x.ndim == 0:
            return x
        spec = P(*([batch_axis] + [None] * (x.ndim - 1)))
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))

    return constrain


def make_shard_and_gather_fns(shardings: Any) -> Tuple[Any, Any]:
    """Per-leaf placement/fetch functions from a ``NamedSharding`` pytree
    (the SNIPPETS.md [2] pattern, pjit identity with pinned out/in specs).

    Returns ``(shard_fns, gather_fns)`` pytrees matching ``shardings``:
    ``shard_fns`` place a host/device leaf into its mesh layout;
    ``gather_fns`` fetch a sharded leaf back to one host ndarray (used by
    the shard-aware checkpoint path to digest and restore state that never
    lives unsharded on any single chip).
    """

    def make_shard_fn(sh):
        placed = jax.jit(lambda x: x, out_shardings=sh)
        return lambda x: placed(x)

    def make_gather_fn(sh):
        gathered = jax.jit(
            lambda x: x, out_shardings=NamedSharding(sh.mesh, P())
        )
        return lambda x: jax.device_get(gathered(x))

    shard_fns = jax.tree_util.tree_map(make_shard_fn, shardings)
    gather_fns = jax.tree_util.tree_map(make_gather_fn, shardings)
    return shard_fns, gather_fns
