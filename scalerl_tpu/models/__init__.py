from scalerl_tpu.models.atari import AtariNet, AtariNetOutput  # noqa: F401
from scalerl_tpu.models.transformer import (  # noqa: F401
    TransformerOutput,
    TransformerPolicy,
)
from scalerl_tpu.models.mlp import (  # noqa: F401
    ActorCriticNet,
    ActorNet,
    C51QNet,
    CriticNet,
    NoisyDense,
    QNet,
)
