"""MLP policy/value networks in Flax.

Parity targets: ``QNet``/``ActorNet``/``CriticNet``/``ActorCriticNet``
(``scalerl/algorithms/utils/network.py:5-95``) plus the DQN architecture
flags the reference's config declares (dueling / noisy,
``scalerl/algorithms/rl_args.py:163-315``).  Compute is sized for the MXU:
plain Dense stacks in bfloat16-friendly shapes; no data-dependent control
flow.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


class NoisyDense(nn.Module):
    """Factorized-Gaussian NoisyNet linear layer (Fortunato et al. 2018).

    Noise is passed in via an explicit rng collection (``noise``) so the layer
    stays a pure function; when the collection is absent the layer runs with
    mean weights (evaluation mode).
    """

    features: int
    sigma0: float = 0.5

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        in_features = x.shape[-1]
        bound = 1.0 / jnp.sqrt(in_features)
        mu_init = nn.initializers.uniform(scale=2 * bound)

        w_mu = self.param("w_mu", lambda k, s: mu_init(k, s) - bound, (in_features, self.features))
        b_mu = self.param("b_mu", lambda k, s: mu_init(k, s) - bound, (self.features,))
        sigma_init = nn.initializers.constant(self.sigma0 / jnp.sqrt(in_features))
        w_sigma = self.param("w_sigma", sigma_init, (in_features, self.features))
        b_sigma = self.param("b_sigma", sigma_init, (self.features,))

        if self.has_rng("noise"):
            key = self.make_rng("noise")
            k1, k2 = jax.random.split(key)
            eps_in = jax.random.normal(k1, (in_features,))
            eps_out = jax.random.normal(k2, (self.features,))
            f = lambda e: jnp.sign(e) * jnp.sqrt(jnp.abs(e))
            eps_w = jnp.outer(f(eps_in), f(eps_out))
            w = w_mu + w_sigma * eps_w
            b = b_mu + b_sigma * f(eps_out)
        else:
            w, b = w_mu, b_mu
        return x @ w + b


def normalized_columns_init(std: float = 1.0):
    """Normalized-columns initializer (A3C-classic).

    Parity: ``normalized_columns_initializer``
    (``scalerl/algorithms/a3c/utils/atari_model.py:9-24``): gaussian noise
    rescaled so every output unit's weight vector has L2 norm ``std``.
    Flax kernels are ``[in, out]``, so the normalization runs over axis 0.
    """

    def init(key, shape, dtype=jnp.float32):
        out = jax.random.normal(key, shape, dtype)
        norm = jnp.sqrt(jnp.sum(jnp.square(out), axis=0, keepdims=True))
        return std * out / (norm + 1e-12)

    return init


def _parse_hidden(hidden_sizes) -> Tuple[int, ...]:
    if isinstance(hidden_sizes, str):
        return tuple(int(h) for h in hidden_sizes.split(",") if h)
    return tuple(hidden_sizes)


class QNet(nn.Module):
    """Q-network with optional dueling heads and noisy layers.

    Parity: ``network.py:5-24`` (plain), dueling/noisy per the DQN flags.
    """

    action_dim: int
    hidden_sizes: Sequence[int] = (128, 128)
    dueling: bool = False
    noisy: bool = False
    noisy_std: float = 0.5

    @nn.compact
    def __call__(self, obs: jnp.ndarray) -> jnp.ndarray:
        x = obs.astype(jnp.float32)
        if x.ndim > 2:
            x = x.reshape(x.shape[0], -1)  # flatten everything but batch
        if self.noisy:
            dense = lambda f: NoisyDense(f, sigma0=self.noisy_std)  # noqa: E731
        else:
            dense = nn.Dense
        for h in _parse_hidden(self.hidden_sizes):
            x = nn.relu(dense(h)(x))
        if self.dueling:
            adv = dense(self.action_dim)(x)
            val = dense(1)(x)
            return val + adv - adv.mean(axis=-1, keepdims=True)
        return dense(self.action_dim)(x)


class C51QNet(nn.Module):
    """Categorical (C51) distributional Q-network.

    Parity: the reference declares ``categorical_dqn``/``num_atoms``/
    ``v_min``/``v_max`` (``scalerl/algorithms/rl_args.py:201-226``) but never
    implements the head; this is the capability, with the same dueling/noisy
    composition as :class:`QNet`.  Returns per-action atom *logits*
    ``[B, A, N]``; expectations against the support live in the loss/actor
    (``scalerl_tpu.ops.losses.categorical_q_values``).
    """

    action_dim: int
    num_atoms: int = 51
    hidden_sizes: Sequence[int] = (128, 128)
    dueling: bool = False
    noisy: bool = False
    noisy_std: float = 0.5

    @nn.compact
    def __call__(self, obs: jnp.ndarray) -> jnp.ndarray:
        x = obs.astype(jnp.float32)
        if x.ndim > 2:
            x = x.reshape(x.shape[0], -1)
        if self.noisy:
            dense = lambda f: NoisyDense(f, sigma0=self.noisy_std)  # noqa: E731
        else:
            dense = nn.Dense
        for h in _parse_hidden(self.hidden_sizes):
            x = nn.relu(dense(h)(x))
        B = x.shape[0]
        if self.dueling:
            adv = dense(self.action_dim * self.num_atoms)(x).reshape(
                B, self.action_dim, self.num_atoms
            )
            val = dense(self.num_atoms)(x).reshape(B, 1, self.num_atoms)
            return val + adv - adv.mean(axis=1, keepdims=True)
        return dense(self.action_dim * self.num_atoms)(x).reshape(
            B, self.action_dim, self.num_atoms
        )


class ActorNet(nn.Module):
    """Categorical policy head (``network.py:27-46``)."""

    action_dim: int
    hidden_sizes: Sequence[int] = (128, 128)

    @nn.compact
    def __call__(self, obs: jnp.ndarray) -> jnp.ndarray:
        x = obs.astype(jnp.float32)
        for h in _parse_hidden(self.hidden_sizes):
            x = nn.relu(nn.Dense(h)(x))
        return nn.Dense(self.action_dim)(x)  # logits


class CriticNet(nn.Module):
    """State-value head (``network.py:49-67``)."""

    hidden_sizes: Sequence[int] = (128, 128)

    @nn.compact
    def __call__(self, obs: jnp.ndarray) -> jnp.ndarray:
        x = obs.astype(jnp.float32)
        for h in _parse_hidden(self.hidden_sizes):
            x = nn.relu(nn.Dense(h)(x))
        return nn.Dense(1)(x).squeeze(-1)


class ActorCriticNet(nn.Module):
    """Shared-torso actor-critic (``network.py:70-95``,
    ``a3c/parallel_a3c.py:27-68``). Returns (logits, value).

    ``normalized_init``: initialize the heads with normalized columns (std
    0.01 policy / 1.0 value), the reference A3C's scheme
    (``atari_model.py:126-131``).
    """

    action_dim: int
    hidden_sizes: Sequence[int] = (128, 128)
    normalized_init: bool = False

    @nn.compact
    def __call__(self, obs: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        x = obs.astype(jnp.float32)
        for h in _parse_hidden(self.hidden_sizes):
            x = nn.relu(nn.Dense(h)(x))
        if self.normalized_init:
            logits = nn.Dense(
                self.action_dim, kernel_init=normalized_columns_init(0.01)
            )(x)
            value = nn.Dense(1, kernel_init=normalized_columns_init(1.0))(x)
        else:
            logits = nn.Dense(self.action_dim)(x)
            value = nn.Dense(1)(x)
        return logits, value.squeeze(-1)


class TanhGaussianActor(nn.Module):
    """Squashed-Gaussian policy for continuous control (SAC).

    Beyond-parity: the reference declares continuous-capable MLP heads in
    its network zoo (``network.py:27-67``) but ships no continuous-action
    algorithm; this head makes them load-bearing.  Returns
    ``(mean_u, log_std)`` in pre-squash space; sampling/log-prob live in
    ``agents/sac.py`` so the module stays a pure function of ``obs``.
    """

    action_dim: int
    hidden_sizes: Sequence[int] = (256, 256)
    log_std_min: float = -20.0
    log_std_max: float = 2.0

    @nn.compact
    def __call__(self, obs: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        x = obs.astype(jnp.float32)
        for h in _parse_hidden(self.hidden_sizes):
            x = nn.relu(nn.Dense(h)(x))
        mean = nn.Dense(self.action_dim, name="mean")(x)
        log_std = nn.Dense(self.action_dim, name="log_std")(x)
        log_std = jnp.clip(log_std, self.log_std_min, self.log_std_max)
        return mean, log_std


class DeterministicActor(nn.Module):
    """MLP -> tanh action in [-1, 1]^d, scaled by the caller (TD3/DDPG)."""

    action_dim: int
    hidden_sizes: Sequence[int] = (256, 256)

    @nn.compact
    def __call__(self, obs: jnp.ndarray) -> jnp.ndarray:
        x = obs.astype(jnp.float32)
        for h in _parse_hidden(self.hidden_sizes):
            x = nn.relu(nn.Dense(h)(x))
        return jnp.tanh(nn.Dense(self.action_dim)(x))


class TwinQNet(nn.Module):
    """Two independent Q(s, a) critics (SAC's clipped double-Q).

    One module holding both parameter sets so a single optimizer state and
    a single ``model.apply`` cover the ensemble; returns ``(q1, q2)``.
    """

    hidden_sizes: Sequence[int] = (256, 256)

    @nn.compact
    def __call__(
        self, obs: jnp.ndarray, action: jnp.ndarray
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        x0 = jnp.concatenate(
            [obs.astype(jnp.float32), action.astype(jnp.float32)], axis=-1
        )
        qs = []
        for i in range(2):
            x = x0
            for j, h in enumerate(_parse_hidden(self.hidden_sizes)):
                x = nn.relu(nn.Dense(h, name=f"q{i}_dense{j}")(x))
            qs.append(nn.Dense(1, name=f"q{i}_out")(x).squeeze(-1))
        return qs[0], qs[1]
