"""Transformer/MoE policies on the uniform recurrent-policy signature.

``TransformerPolicy`` (``models/transformer.py``) and ``MoEPolicy``
(``models/moe.py``) speak batch-major sequence/token interfaces; the
actor-learner algorithm families (IMPALA/A3C/PPO, ``agents/``) drive every
model through the time-major recurrent signature of ``models/policy.py``::

    (obs[T,B,...], last_action[T,B], reward[T,B], done[T,B], core_state)
        -> (AtariNetOutput(policy_logits[T,B,A], baseline[T,B]), core_state)

These adapters bridge the two so the big-model families drop into every
existing trainer unchanged — and, with ``mp_size > 1``, into the dp×mp
sharded learner plane (``parallel/logical.py`` knows their param names).

Context semantics: the transformer attends causally *within the trajectory
chunk* it is given (``core_state`` is empty — attention over the ``T+1``
unroll is the memory, the R2D2 "stored state" question doesn't arise).
Acting calls see a length-1 chunk; V-trace's importance weights absorb the
resulting actor/learner context mismatch exactly as they absorb parameter
lag.
"""

from __future__ import annotations

from typing import Callable, Optional

import flax.linen as nn
import jax.numpy as jnp

from scalerl_tpu.models.atari import AtariNetOutput
from scalerl_tpu.models.moe import MoEPolicy
from scalerl_tpu.models.transformer import TransformerPolicy


class TransformerPolicyNet(nn.Module):
    """Causal transformer actor-critic on the recurrent signature.

    ``constrain``: the activation sharding seam, threaded to the inner
    :class:`TransformerPolicy` (set by ``enable_mesh`` on the mp path).
    ``dtype``/``param_dtype``: bf16 compute/params with f32 heads — the
    mixed-precision layout of the sharded learner (optimizer state stays
    f32 via ``parallel.train_step.fp32_optimizer_state``).
    """

    num_actions: int
    d_model: int = 128
    num_heads: int = 4
    num_layers: int = 2
    mlp_ratio: int = 4
    max_len: int = 1024
    use_flash: bool = False
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    constrain: Optional[Callable] = None

    def initial_state(self, batch_size: int):
        return ()

    @nn.compact
    def __call__(self, obs, last_action, reward, done, core_state=()):
        del last_action, reward, done  # context = the obs sequence itself
        out = TransformerPolicy(
            num_actions=self.num_actions,
            d_model=self.d_model,
            num_heads=self.num_heads,
            num_layers=self.num_layers,
            mlp_ratio=self.mlp_ratio,
            max_len=self.max_len,
            use_flash=self.use_flash,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            constrain=self.constrain,
            name="transformer",
        )(jnp.moveaxis(obs, 0, 1))  # [T, B, ...] -> [B, T, ...]
        return (
            AtariNetOutput(
                policy_logits=jnp.moveaxis(out.policy_logits, 0, 1),
                baseline=jnp.moveaxis(out.baseline, 0, 1),
            ),
            core_state,
        )


class MoEPolicyNet(nn.Module):
    """Switch-routed MoE actor-critic on the recurrent signature.

    Per-step obs features are flattened to a ``[T*B, obs]`` token stream
    for the expert layer (expert capacity is sized off the full chunk's
    token count).  The Switch load-balancing aux loss is computed inside
    ``MoEPolicy`` but not surfaced through this signature — at policy
    scale with ``capacity_factor >= 2`` top-1 routing stays balanced
    enough; token-level sequence-RL workloads that need the aux term
    should drive ``MoEPolicy`` directly.
    """

    num_actions: int
    d_model: int = 128
    num_experts: int = 8
    d_hidden: int = 256
    capacity_factor: float = 2.0
    constrain: Optional[Callable] = None

    def initial_state(self, batch_size: int):
        return ()

    @nn.compact
    def __call__(self, obs, last_action, reward, done, core_state=()):
        del last_action, reward, done
        T, B = obs.shape[0], obs.shape[1]
        flat = obs.reshape((T * B, -1))
        logits, baseline, _aux = MoEPolicy(
            num_actions=self.num_actions,
            d_model=self.d_model,
            num_experts=self.num_experts,
            d_hidden=self.d_hidden,
            capacity_factor=self.capacity_factor,
            constrain=self.constrain,
            name="moe_policy",
        )(flat)
        return (
            AtariNetOutput(
                policy_logits=logits.reshape(T, B, self.num_actions),
                baseline=baseline.reshape(T, B),
            ),
            core_state,
        )


def build_mp_policy(args, obs_shape, num_actions):
    """The ``policy_arch`` dispatch shared by the algorithm families'
    ``build_model`` functions: ``"transformer"``/``"moe"`` return an
    mp-shardable adapter sized from ``RLArguments`` (``d_model``,
    ``n_layers``, ``n_heads``, ``moe_experts``, ``moe_hidden``,
    ``bf16_params``); ``"auto"`` returns None — the caller keeps its
    conv/MLP zoo.
    """
    arch = getattr(args, "policy_arch", "auto")
    if arch in ("auto", "", None):
        return None
    bf16 = bool(getattr(args, "bf16_params", False))
    if arch == "transformer":
        return TransformerPolicyNet(
            num_actions=num_actions,
            d_model=getattr(args, "d_model", 128),
            num_heads=getattr(args, "n_heads", 4),
            num_layers=getattr(args, "n_layers", 2),
            # learner chunks are [T+1, B]; acting sees T=1
            max_len=int(getattr(args, "rollout_length", 20)) + 1,
            dtype=jnp.bfloat16 if bf16 else jnp.float32,
            param_dtype=jnp.bfloat16 if bf16 else jnp.float32,
        )
    if arch == "moe":
        return MoEPolicyNet(
            num_actions=num_actions,
            d_model=getattr(args, "d_model", 128),
            num_experts=getattr(args, "moe_experts", 8),
            d_hidden=getattr(args, "moe_hidden", 256),
        )
    raise ValueError(
        f"unknown policy_arch {arch!r}; expected auto | transformer | moe"
    )
