"""Recurrent Q-network for R2D2 (beyond-parity algorithm family).

Same time-major recurrent signature as ``models/atari.py:AtariNet`` —
``(obs[T,B,...], last_action[T,B], reward[T,B], done[T,B], core)`` with a
done-masked LSTM carry — but the head is a (optionally dueling) Q-value
layer instead of policy/baseline.  The torso is chosen by observation
rank: conv stack for pixel obs (rank 3 per step), Dense stack for vector
obs.  Recurrence rides the same ``nn.scan`` over ``_LSTMCore`` so rollout
chunks replay exactly as collected (Kapturowski et al. 2019, "stored
state" strategy).

Reference context: the reference ships no recurrent value-based agent at
all (its DQN family is feed-forward MLPs, ``scalerl/algorithms/dqn``);
R2D2 completes the Ape-X lineage its README cites.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from scalerl_tpu.models.atari import _LSTMCore, LSTMState


class RecurrentQOutput(NamedTuple):
    q_values: jnp.ndarray  # [T, B, num_actions]


class RecurrentQNet(nn.Module):
    num_actions: int
    use_lstm: bool = True
    hidden_size: int = 512
    lstm_layers: int = 1
    dueling: bool = True
    conv_features: Sequence[int] = (32, 64, 64)
    conv_kernels: Sequence[int] = (8, 4, 3)
    conv_strides: Sequence[int] = (4, 2, 1)
    dtype: jnp.dtype = jnp.float32

    @property
    def core_size(self) -> int:
        return self.hidden_size + self.num_actions + 1

    def initial_state(self, batch_size: int) -> LSTMState:
        if not self.use_lstm:
            return ()
        return tuple(
            (
                jnp.zeros((batch_size, self.core_size), jnp.float32),
                jnp.zeros((batch_size, self.core_size), jnp.float32),
            )
            for _ in range(self.lstm_layers)
        )

    @nn.compact
    def __call__(
        self,
        obs: jnp.ndarray,  # [T, B, ...]: rank-3 trailing = pixels, rank-1 = vector
        last_action: jnp.ndarray,  # [T, B] int32
        reward: jnp.ndarray,  # [T, B] float
        done: jnp.ndarray,  # [T, B] bool
        core_state: LSTMState = (),
    ) -> Tuple[RecurrentQOutput, LSTMState]:
        T, B = obs.shape[0], obs.shape[1]
        pixels = obs.ndim == 5
        if pixels:
            x = obs.astype(self.dtype) / jnp.asarray(255.0, self.dtype)
            x = x.reshape((T * B,) + tuple(obs.shape[2:]))
            for feat, kern, stride in zip(
                self.conv_features, self.conv_kernels, self.conv_strides
            ):
                x = nn.Conv(
                    feat, (kern, kern), strides=(stride, stride), dtype=self.dtype
                )(x)
                x = nn.relu(x)
            x = x.reshape(T * B, -1)
        else:
            x = obs.astype(self.dtype).reshape(T * B, -1)
        x = nn.relu(nn.Dense(self.hidden_size, dtype=self.dtype)(x))

        one_hot_action = jax.nn.one_hot(
            last_action.reshape(T * B), self.num_actions, dtype=self.dtype
        )
        clipped_reward = (
            jnp.clip(reward, -1.0, 1.0).reshape(T * B, 1).astype(self.dtype)
        )
        core_input = jnp.concatenate([x, one_hot_action, clipped_reward], axis=-1)

        if self.use_lstm:
            core_input = core_input.reshape(T, B, -1).astype(jnp.float32)
            if not core_state:
                core_state = self.initial_state(B)
            scan_core = nn.scan(
                _LSTMCore,
                variable_broadcast="params",
                split_rngs={"params": False},
                in_axes=0,
                out_axes=0,
            )(hidden_size=self.core_size, num_layers=self.lstm_layers)
            core_state, core_output = scan_core(core_state, (core_input, done))
            core_output = core_output.reshape(T * B, -1)
        else:
            core_output = core_input

        core_output = core_output.astype(jnp.float32)
        if self.dueling:
            value = nn.Dense(1, name="value")(
                nn.relu(nn.Dense(self.hidden_size // 2, name="value_h")(core_output))
            )
            adv = nn.Dense(self.num_actions, name="advantage")(
                nn.relu(nn.Dense(self.hidden_size // 2, name="advantage_h")(core_output))
            )
            q = value + adv - jnp.mean(adv, axis=-1, keepdims=True)
        else:
            q = nn.Dense(self.num_actions, name="q")(core_output)
        return RecurrentQOutput(q_values=q.reshape(T, B, self.num_actions)), core_state
