"""A uniform recurrent-policy interface over vector and pixel models.

Every actor-learner algorithm in this framework drives a model through one
signature::

    (params, obs[T,B,...], last_action[T,B], reward[T,B], done[T,B],
     core_state) -> (AtariNetOutput(policy_logits, baseline), core_state)

``AtariNet`` (``models/atari.py``) implements it for pixels;
``MLPPolicyNet`` here implements it for flat observations (the reference's
``ActorCriticNet`` capability, ``algorithms/utils/network.py:70-95``, lifted
to the time-major recurrent signature so IMPALA/A2C code paths are
model-agnostic).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from scalerl_tpu.models.atari import AtariNetOutput, LSTMState


class MLPPolicyNet(nn.Module):
    """Feed-forward actor-critic on flat obs with the recurrent signature."""

    num_actions: int
    hidden_sizes: Sequence[int] = (256, 256)
    normalized_init: bool = False  # A3C head init (atari_model.py:9-24)

    def initial_state(self, batch_size: int) -> LSTMState:
        return ()

    @nn.compact
    def __call__(
        self,
        obs: jnp.ndarray,  # [T, B, D]
        last_action: jnp.ndarray,  # [T, B] (unused: no action feedback in MLP)
        reward: jnp.ndarray,  # [T, B] (unused)
        done: jnp.ndarray,  # [T, B] (unused: feed-forward)
        core_state: LSTMState = (),
    ) -> Tuple[AtariNetOutput, LSTMState]:
        del last_action, reward, done
        x = obs.astype(jnp.float32)
        for h in self.hidden_sizes:
            x = nn.relu(nn.Dense(h)(x))
        if self.normalized_init:
            from scalerl_tpu.models.mlp import normalized_columns_init

            logits = nn.Dense(
                self.num_actions,
                name="policy",
                kernel_init=normalized_columns_init(0.01),
            )(x)
            baseline = nn.Dense(
                1, name="baseline", kernel_init=normalized_columns_init(1.0)
            )(x).squeeze(-1)
        else:
            logits = nn.Dense(self.num_actions, name="policy")(x)
            baseline = nn.Dense(1, name="baseline")(x).squeeze(-1)
        return AtariNetOutput(policy_logits=logits, baseline=baseline), core_state
