"""Numpy forward passes for actor-process CPU inference.

Fleet / process actors run eps-greedy rollouts on weight *snapshots*
(numpy pytrees pulled from the learner) without importing JAX in the actor
process — forking a JAX-initialized runtime into actors is both heavy and
deadlock-prone, and a 2×128 MLP forward is microseconds in numpy.

Covers the MLP families of ``models/mlp.py`` (QNet plain + dueling).  Conv
policies should use SEED-style central inference instead
(``trainer/actor_learner.py``) — shipping pixel batches to a CPU conv is
the wrong trade.
"""

from __future__ import annotations

from typing import Any, List

import numpy as np


def _dense_layers(params: Any) -> List[Any]:
    inner = params["params"] if "params" in params else params
    if any(k.startswith("NoisyDense_") for k in inner):
        raise NotImplementedError(
            "noisy nets need device inference (factorized noise resampling)"
        )
    names = sorted(
        (k for k in inner.keys() if k.startswith("Dense_")),
        key=lambda k: int(k.split("_")[-1]),
    )
    return [inner[k] for k in names]


def mlp_qnet_forward(
    params: Any, obs: np.ndarray, dueling: bool = False
) -> np.ndarray:
    """Q-values ``[B, A]`` from a ``models.mlp.QNet`` param pytree.

    Layer order matches the flax module: hidden Dense stack with relu,
    then (plain) one head, or (dueling) advantage head + value head.
    """
    x = np.asarray(obs, np.float32)
    if x.ndim > 2:
        x = x.reshape(x.shape[0], -1)
    layers = _dense_layers(params)
    n_head = 2 if dueling else 1
    hidden, heads = layers[:-n_head], layers[-n_head:]
    for layer in hidden:
        x = np.maximum(x @ np.asarray(layer["kernel"]) + np.asarray(layer["bias"]), 0.0)
    if not dueling:
        h = heads[0]
        return x @ np.asarray(h["kernel"]) + np.asarray(h["bias"])
    adv = x @ np.asarray(heads[0]["kernel"]) + np.asarray(heads[0]["bias"])
    val = x @ np.asarray(heads[1]["kernel"]) + np.asarray(heads[1]["bias"])
    return val + adv - adv.mean(axis=-1, keepdims=True)


def mlp_policy_forward(params: Any, obs: np.ndarray) -> np.ndarray:
    """Policy logits ``[B, A]`` from a ``models.policy.MLPPolicyNet`` pytree.

    The torso is the ``Dense_i`` relu stack; the actor head is the named
    ``policy`` Dense (the ``baseline`` head is learner-only and skipped).
    """
    inner = params["params"] if "params" in params else params
    x = np.asarray(obs, np.float32)
    if x.ndim > 2:
        x = x.reshape(x.shape[0], -1)
    for layer in _dense_layers(params):
        x = np.maximum(x @ np.asarray(layer["kernel"]) + np.asarray(layer["bias"]), 0.0)
    head = inner["policy"]
    return x @ np.asarray(head["kernel"]) + np.asarray(head["bias"])
