"""IMPALA Atari network: conv torso + optional done-masked LSTM core.

Parity target: ``AtariNet`` (``scalerl/algorithms/utils/atari_model.py:8-143``):
3 convs (32@8s4 / 64@4s2 / 64@3s1) -> fc(512) -> concat[one-hot last action,
clipped reward] -> optional 2-layer LSTM whose state is reset where ``done``
-> policy-logits and baseline heads.  Also covers the A3C conv-ELU-LSTM
variant (``a3c/utils/atari_model.py:57-144``) via constructor knobs.

TPU-first differences from the reference:
- NHWC frame layout (``[T, B, H, W, C]`` uint8) — XLA's preferred conv layout.
- The per-timestep Python loop with in-place state resets
  (``atari_model.py:109-120``) is an ``nn.scan`` over the time axis; the
  done-mask multiplies the carry, so the whole unroll is one fused XLA loop.
- uint8 -> float scaling happens on device, so host->HBM transfers stay uint8
  (4x less infeed bandwidth).
- Action sampling lives in the agent (pure function of rng + logits), not in
  the module, keeping the model usable under jit/vmap/pjit without rng plumbing.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

# Carry: ((c, h) per LSTM layer); () when use_lstm=False.
LSTMState = Tuple[Tuple[jnp.ndarray, jnp.ndarray], ...]


class AtariNetOutput(NamedTuple):
    policy_logits: jnp.ndarray  # [T, B, num_actions]
    baseline: jnp.ndarray  # [T, B]


class _LSTMCore(nn.Module):
    """Stacked LSTM cells applied to ONE timestep with done-masked carry."""

    hidden_size: int
    num_layers: int

    @nn.compact
    def __call__(self, carry: LSTMState, xs):
        x, done = xs  # x: [B, F], done: [B]
        keep = (~done)[:, None].astype(x.dtype)
        new_carry = []
        for i in range(self.num_layers):
            cell = nn.OptimizedLSTMCell(self.hidden_size, name=f"lstm_{i}")
            c, h = carry[i]
            (c, h), x = cell((c * keep, h * keep), x)
            new_carry.append((c, h))
        return tuple(new_carry), x


class AtariNet(nn.Module):
    """Conv + (optional) LSTM actor-critic for 84x84 pixel observations."""

    num_actions: int
    use_lstm: bool = True
    hidden_size: int = 512
    lstm_layers: int = 2
    conv_features: Sequence[int] = (32, 64, 64)
    conv_kernels: Sequence[int] = (8, 4, 3)
    conv_strides: Sequence[int] = (4, 2, 1)
    dtype: jnp.dtype = jnp.float32  # set bfloat16 for MXU-heavy runs
    # normalized-columns head init (std 0.01 policy / 1.0 value), the
    # reference A3C's scheme (a3c/utils/atari_model.py:9-24,126-131)
    normalized_init: bool = False

    @property
    def core_size(self) -> int:
        return self.hidden_size + self.num_actions + 1

    def initial_state(self, batch_size: int) -> LSTMState:
        if not self.use_lstm:
            return ()
        return tuple(
            (
                jnp.zeros((batch_size, self.core_size), jnp.float32),
                jnp.zeros((batch_size, self.core_size), jnp.float32),
            )
            for _ in range(self.lstm_layers)
        )

    @nn.compact
    def __call__(
        self,
        frame: jnp.ndarray,  # [T, B, H, W, C] uint8 (or float)
        last_action: jnp.ndarray,  # [T, B] int32
        reward: jnp.ndarray,  # [T, B] float
        done: jnp.ndarray,  # [T, B] bool
        core_state: LSTMState = (),
    ) -> Tuple[AtariNetOutput, LSTMState]:
        T, B = frame.shape[0], frame.shape[1]
        x = frame.astype(self.dtype) / jnp.asarray(255.0, self.dtype)
        x = x.reshape((T * B,) + tuple(frame.shape[2:]))
        for feat, kern, stride in zip(
            self.conv_features, self.conv_kernels, self.conv_strides
        ):
            x = nn.Conv(
                feat, (kern, kern), strides=(stride, stride), dtype=self.dtype
            )(x)
            x = nn.relu(x)
        x = x.reshape(T * B, -1)
        x = nn.relu(nn.Dense(self.hidden_size, dtype=self.dtype)(x))

        one_hot_action = jax.nn.one_hot(
            last_action.reshape(T * B), self.num_actions, dtype=self.dtype
        )
        clipped_reward = jnp.clip(reward, -1.0, 1.0).reshape(T * B, 1).astype(self.dtype)
        core_input = jnp.concatenate([x, one_hot_action, clipped_reward], axis=-1)

        if self.use_lstm:
            core_input = core_input.reshape(T, B, -1).astype(jnp.float32)
            if not core_state:
                core_state = self.initial_state(B)
            scan_core = nn.scan(
                _LSTMCore,
                variable_broadcast="params",
                split_rngs={"params": False},
                in_axes=0,
                out_axes=0,
            )(hidden_size=self.core_size, num_layers=self.lstm_layers)
            core_state, core_output = scan_core(core_state, (core_input, done))
            core_output = core_output.reshape(T * B, -1)
        else:
            core_output = core_input

        core_output = core_output.astype(jnp.float32)
        if self.normalized_init:
            from scalerl_tpu.models.mlp import normalized_columns_init

            policy_logits = nn.Dense(
                self.num_actions,
                name="policy",
                kernel_init=normalized_columns_init(0.01),
            )(core_output)
            baseline = nn.Dense(
                1, name="baseline", kernel_init=normalized_columns_init(1.0)
            )(core_output)
        else:
            policy_logits = nn.Dense(self.num_actions, name="policy")(core_output)
            baseline = nn.Dense(1, name="baseline")(core_output)
        return (
            AtariNetOutput(
                policy_logits=policy_logits.reshape(T, B, self.num_actions),
                baseline=baseline.reshape(T, B),
            ),
            core_state,
        )
