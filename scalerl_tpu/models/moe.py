"""Mixture-of-experts layer with expert parallelism over the ``ep`` axis.

No counterpart in the reference (SURVEY.md §2.4: expert parallelism absent);
this completes the mesh's parallelism vocabulary.  The design is the
GShard/Switch dispatch-combine formulation, which is the TPU-native shape
for MoE: routing becomes dense einsums over a ``[tokens, experts, capacity]``
one-hot dispatch tensor, experts are a single ``[E, ...]``-leading batch of
matmuls, and sharding that leading axis over ``ep`` makes XLA insert the
token all-to-alls — no hand-written communication.

Top-1 (Switch) routing with capacity dropping: tokens beyond an expert's
capacity pass through the residual only.  A load-balancing auxiliary loss
(Switch Transformer eq. 4) is returned for the trainer to add.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


class MoEOutput(NamedTuple):
    out: jnp.ndarray        # [N, d_model] combined expert outputs
    aux_loss: jnp.ndarray   # scalar load-balancing loss
    dispatch_frac: jnp.ndarray  # scalar: fraction of tokens not dropped


def top1_dispatch(
    gates: jnp.ndarray, capacity: int
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Build dispatch/combine tensors for top-1 routing.

    gates: [N, E] softmax router outputs.
    Returns (dispatch [N, E, C] bool-ish float, combine [N, E, C], aux).
    """
    N, E = gates.shape
    expert = jnp.argmax(gates, axis=-1)                    # [N]
    onehot = jax.nn.one_hot(expert, E, dtype=gates.dtype)  # [N, E]
    # position of each token within its expert's queue
    pos = jnp.cumsum(onehot, axis=0) * onehot - onehot     # [N, E], 0-based
    keep = (pos < capacity).astype(gates.dtype) * onehot
    pos_cap = jax.nn.one_hot(
        jnp.clip(pos.astype(jnp.int32), 0, capacity - 1), capacity,
        dtype=gates.dtype,
    )                                                      # [N, E, C]
    dispatch = keep[..., None] * pos_cap
    gate_val = jnp.sum(gates * onehot, axis=-1, keepdims=True)  # [N, 1]
    combine = dispatch * gate_val[..., None]
    # Switch aux loss: E * sum_e mean_tokens(router prob_e) * frac_tokens_e
    frac_tokens = onehot.mean(axis=0)
    mean_prob = gates.mean(axis=0)
    aux = E * jnp.sum(frac_tokens * mean_prob)
    return dispatch, combine, aux


class MoEMLP(nn.Module):
    """Switch-routed expert FFN over flattened tokens ``[N, d_model]``."""

    num_experts: int
    d_model: int
    d_hidden: int
    capacity_factor: float = 1.25

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> MoEOutput:
        N, M = x.shape
        E = self.num_experts
        C = max(int(self.capacity_factor * N / E), 1)
        gates = jax.nn.softmax(
            nn.Dense(E, use_bias=False, name="router")(x), axis=-1
        )
        dispatch, combine, aux = top1_dispatch(gates, C)
        # [E, C, M] expert input batches — the tensor whose leading axis is
        # sharded over 'ep' (XLA derives the all-to-all from the shardings)
        expert_in = jnp.einsum("nec,nm->ecm", dispatch, x)
        w_in = self.param(
            "w_in", nn.initializers.lecun_normal(), (E, M, self.d_hidden)
        )
        w_out = self.param(
            "w_out", nn.initializers.lecun_normal(), (E, self.d_hidden, M)
        )
        h = jax.nn.relu(jnp.einsum("ecm,emh->ech", expert_in, w_in))
        expert_out = jnp.einsum("ech,ehm->ecm", h, w_out)
        out = jnp.einsum("nec,ecm->nm", combine, expert_out)
        dispatched = jnp.sum(dispatch) / N
        return MoEOutput(out, aux, dispatched)


class MoEPolicy(nn.Module):
    """Small actor-critic whose trunk is dense->MoE->dense (per-step obs
    features ``[B, obs_dim]``) — the expert-parallel model family entry."""

    num_actions: int
    d_model: int = 128
    num_experts: int = 8
    d_hidden: int = 256
    capacity_factor: float = 2.0
    # Sharded-activation seam (``parallel.logical.activation_constraint``):
    # pins the token stream to batch-over-dp around the expert layer, so
    # GSPMD derives the dispatch/combine all-to-alls from the expert-bank
    # shardings (``w_in``/``w_out`` leading dim over ``mp``) alone.
    constrain: Optional[Callable] = None

    @nn.compact
    def __call__(self, obs: jnp.ndarray):
        c = self.constrain if self.constrain is not None else (lambda x: x)
        x = c(nn.relu(nn.Dense(self.d_model, name="embed")(
            obs.reshape(obs.shape[0], -1).astype(jnp.float32)
        )))
        moe = MoEMLP(
            self.num_experts,
            self.d_model,
            self.d_hidden,
            self.capacity_factor,
            name="moe",
        )(x)
        x = c(nn.LayerNorm()(x + moe.out))
        policy_logits = nn.Dense(self.num_actions, name="policy_head")(x)
        baseline = nn.Dense(1, name="value_head")(x).squeeze(-1)
        return policy_logits, baseline, moe.aux_loss


def expert_sharding_rule(path: Tuple[str, ...]) -> Optional[Tuple]:
    """Param-spec rule for :func:`scalerl_tpu.parallel.sharding
    .infer_param_spec`-style use: shard expert-leading tensors over ep."""
    name = path[-1] if path else ""
    if name in ("w_in", "w_out"):
        return ("ep", None, None)
    return None
