"""Decoder-only transformer policy for long-horizon trajectories.

No counterpart in the reference (its sequence machinery tops out at a
2-layer LSTM, ``scalerl/algorithms/utils/atari_model.py:109-120``); this is
the long-context model family the TPU build adds: a causal transformer over
the trajectory time axis producing per-step policy logits and baseline, with
an attention implementation that can be swapped for sequence-parallel
:func:`scalerl_tpu.ops.ring_attention.ring_attention` under ``shard_map``.

Design notes for sequence parallelism: everything except attention is
position-wise (LayerNorm, MLP, heads), so the module is valid when the time
axis is sharded across the ``sp`` mesh axis — callers pass ``positions``
(global step indices) so positional embeddings stay correct per shard.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import flax.linen as nn
import jax.numpy as jnp

from scalerl_tpu.ops.pallas_attention import flash_attention
from scalerl_tpu.ops.ring_attention import full_attention

# (q, k, v) -> attention output, all [B, T, H, D]
AttentionFn = Callable[[jnp.ndarray, jnp.ndarray, jnp.ndarray], jnp.ndarray]


class TransformerOutput(NamedTuple):
    policy_logits: jnp.ndarray  # [B, T, num_actions]
    baseline: jnp.ndarray  # [B, T]


class _Block(nn.Module):
    d_model: int
    num_heads: int
    mlp_ratio: int
    attn_fn: AttentionFn
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        B, T, _ = x.shape
        head_dim = self.d_model // self.num_heads
        dt = dict(dtype=self.dtype, param_dtype=self.param_dtype)
        h = nn.LayerNorm(use_bias=False, dtype=self.dtype)(x)
        qkv = nn.Dense(3 * self.d_model, use_bias=False, name="qkv", **dt)(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shape = (B, T, self.num_heads, head_dim)
        out = self.attn_fn(q.reshape(shape), k.reshape(shape), v.reshape(shape))
        out = nn.Dense(self.d_model, use_bias=False, name="proj", **dt)(
            out.reshape(B, T, self.d_model)
        )
        x = x + out
        h = nn.LayerNorm(use_bias=False, dtype=self.dtype)(x)
        h = nn.Dense(self.mlp_ratio * self.d_model, name="mlp_in", **dt)(h)
        h = nn.gelu(h)
        h = nn.Dense(self.d_model, name="mlp_out", **dt)(h)
        return x + h


class TransformerPolicy(nn.Module):
    """Causal transformer actor-critic over ``[B, T, obs_dim]`` features.

    ``attn_fn``: defaults to single-device causal :func:`full_attention`;
    pass a closed-over :func:`ring_attention` (inside ``shard_map``) for
    sequence-parallel execution.  NOTE: a custom ``attn_fn`` must apply its
    own causal masking — the default here is causal.

    ``use_flash=True`` swaps in the Pallas flash kernel
    (:func:`scalerl_tpu.ops.pallas_attention.flash_attention`): blockwise
    online-softmax attention that never materializes ``[T, T]`` scores —
    the right default on TPU once ``T`` is long (ignored when ``attn_fn``
    is given).
    """

    num_actions: int
    d_model: int = 128
    num_heads: int = 4
    num_layers: int = 2
    mlp_ratio: int = 4
    max_len: int = 4096
    attn_fn: Optional[AttentionFn] = None
    use_flash: bool = False
    # Mixed precision: blocks compute in ``dtype`` with params stored in
    # ``param_dtype`` (bf16/bf16 on the sharded learner plane); the heads
    # always emit float32 so the loss/V-trace math stays full precision.
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    # Sharded-activation seam: when set (``parallel.logical
    # .activation_constraint``), applied to the residual stream after the
    # embedding and after every block — pins inter-layer activations to
    # batch-over-dp / replicated-over-mp so GSPMD derives the per-block
    # head/mlp reshard from the weight shardings alone.
    constrain: Optional[Callable] = None

    @nn.compact
    def __call__(
        self, obs: jnp.ndarray, positions: Optional[jnp.ndarray] = None
    ) -> TransformerOutput:
        B, T = obs.shape[:2]
        if T > self.max_len:
            # out-of-range gathers clamp silently under jit, which would
            # alias every late position onto one embedding
            raise ValueError(
                f"sequence length {T} exceeds max_len={self.max_len}"
            )
        attn = self.attn_fn
        if attn is None:
            base = flash_attention if self.use_flash else full_attention
            attn = lambda q, k, v: base(q, k, v, causal=True)  # noqa: E731
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(T), (B, T))
        c = self.constrain if self.constrain is not None else (lambda x: x)
        x = nn.Dense(
            self.d_model, name="obs_embed",
            dtype=self.dtype, param_dtype=self.param_dtype,
        )(obs.reshape(B, T, -1).astype(self.dtype))
        pos_tab = self.param(
            "pos_embed",
            nn.initializers.normal(0.02),
            (self.max_len, self.d_model),
            self.param_dtype,
        )
        x = c(x + pos_tab[positions].astype(self.dtype))
        for i in range(self.num_layers):
            x = c(
                _Block(
                    self.d_model,
                    self.num_heads,
                    self.mlp_ratio,
                    attn,
                    dtype=self.dtype,
                    param_dtype=self.param_dtype,
                    name=f"block_{i}",
                )(x)
            )
        x = nn.LayerNorm(use_bias=False, name="final_norm", dtype=jnp.float32)(
            x.astype(jnp.float32)
        )
        policy_logits = nn.Dense(self.num_actions, name="policy_head")(x)
        baseline = nn.Dense(1, name="value_head")(x).squeeze(-1)
        return TransformerOutput(policy_logits, baseline)
