"""Decoder-only transformer policy for long-horizon trajectories.

No counterpart in the reference (its sequence machinery tops out at a
2-layer LSTM, ``scalerl/algorithms/utils/atari_model.py:109-120``); this is
the long-context model family the TPU build adds: a causal transformer over
the trajectory time axis producing per-step policy logits and baseline, with
an attention implementation that can be swapped for sequence-parallel
:func:`scalerl_tpu.ops.ring_attention.ring_attention` under ``shard_map``.

Design notes for sequence parallelism: everything except attention is
position-wise (LayerNorm, MLP, heads), so the module is valid when the time
axis is sharded across the ``sp`` mesh axis — callers pass ``positions``
(global step indices) so positional embeddings stay correct per shard.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from scalerl_tpu.ops.pallas_attention import flash_attention
from scalerl_tpu.ops.pallas_paged_attention import paged_attention_reference
from scalerl_tpu.ops.ring_attention import full_attention

# (q, k, v) -> attention output, all [B, T, H, D]
AttentionFn = Callable[[jnp.ndarray, jnp.ndarray, jnp.ndarray], jnp.ndarray]


class TransformerOutput(NamedTuple):
    policy_logits: jnp.ndarray  # [B, T, num_actions]
    baseline: jnp.ndarray  # [B, T]


class KVCache(NamedTuple):
    """Static-shape per-layer key/value cache for incremental decoding.

    ``k``/``v``: one ``[B, S, H, D]`` array per transformer block, where
    ``S`` is the *total* (prompt bucket + response bucket) sequence length.
    The cache is allocated once per bucket shape (:func:`init_kv_cache`),
    written with ``lax.dynamic_update_slice`` at a scalar write cursor, and
    carried through the jitted decode loop — so XLA compiles one program
    per bucket and never retraces on ragged prompt lengths (the
    ``serving/batcher.py`` bucket-ladder idea applied to the time axis).
    """

    k: Tuple[jnp.ndarray, ...]
    v: Tuple[jnp.ndarray, ...]


def init_kv_cache(
    batch: int,
    total_len: int,
    num_layers: int,
    num_heads: int,
    head_dim: int,
    dtype=jnp.float32,
) -> KVCache:
    """Zeroed cache sized for ``total_len`` (prompt + response buckets)."""
    shape = (batch, total_len, num_heads, head_dim)
    return KVCache(
        k=tuple(jnp.zeros(shape, dtype) for _ in range(num_layers)),
        v=tuple(jnp.zeros(shape, dtype) for _ in range(num_layers)),
    )


class PagedKVCache(NamedTuple):
    """Block-paged key/value cache: a fixed pool shared by every lane.

    ``k``/``v``: one ``[num_pages, page_size, H, D]`` pool per transformer
    block.  Lanes own *pages*, not contiguous rows: a host-side allocator
    (``genrl/paging.py``) hands each lane an ordered page list, and the
    decode path writes token ``p`` of a lane into page
    ``table[p // page_size]`` at slot ``p % page_size`` — so KV memory
    scales with LIVE tokens across all lanes instead of
    ``max_bucket x lanes`` (the vLLM shape).  Page 0 is the allocator's
    null page: dead-lane and pad writes are routed there and it is never
    read (every read is masked by a lane's true length).
    """

    k: Tuple[jnp.ndarray, ...]
    v: Tuple[jnp.ndarray, ...]


def init_paged_kv_cache(
    num_pages: int,
    page_size: int,
    num_layers: int,
    num_heads: int,
    head_dim: int,
    dtype=jnp.float32,
) -> PagedKVCache:
    """Zeroed page pools (page 0 = the never-read null page)."""
    shape = (num_pages, page_size, num_heads, head_dim)
    return PagedKVCache(
        k=tuple(jnp.zeros(shape, dtype) for _ in range(num_layers)),
        v=tuple(jnp.zeros(shape, dtype) for _ in range(num_layers)),
    )


def prompt_attention_mask(lengths: jnp.ndarray, total_len: int) -> jnp.ndarray:
    """``[B, T, T]`` causal mask over RIGHT-padded (compact) prompts — the
    paged-prefill twin of :func:`prefill_attention_mask`: lane ``b``'s real
    tokens occupy columns ``[0, lengths[b])``, so position ``i`` attends
    causally within the real prefix and pad-tail rows degrade to uniform
    (finite, outputs unused)."""
    cols = jnp.arange(total_len)[None, None, :]
    rows = jnp.arange(total_len)[None, :, None]
    return (cols <= rows) & (cols < lengths[:, None, None])


def prefill_attention_mask(
    lengths: jnp.ndarray, prompt_pad: int, total_len: int
) -> jnp.ndarray:
    """``[B, P, S]`` bool mask for the prefill pass over LEFT-padded prompts.

    Prompts are right-aligned inside their ``prompt_pad`` bucket (lane
    ``b``'s real tokens occupy columns ``[prompt_pad - lengths[b],
    prompt_pad)``), so every lane's *last* prompt token sits at the same
    static index and the decode steps share one scalar write cursor.  Row
    ``r`` attends causally within the prompt, never into the pad prefix and
    never into the (still empty) response region.  Fully-masked pad rows
    are harmless: softmax degrades to uniform and their outputs are unused.
    """
    cols = jnp.arange(total_len)[None, None, :]
    rows = jnp.arange(prompt_pad)[None, :, None]
    pad = (prompt_pad - lengths)[:, None, None]
    return (cols >= pad) & (cols <= rows)


def decode_attention_mask(
    lengths: jnp.ndarray, prompt_pad: int, step, total_len: int
) -> jnp.ndarray:
    """``[B, 1, S]`` mask for decode step ``step`` (0-indexed): attend to
    the real prompt plus every response token written so far, including the
    one just written at ``prompt_pad + step``."""
    cols = jnp.arange(total_len)[None, None, :]
    pad = (prompt_pad - lengths)[:, None, None]
    return (cols >= pad) & (cols <= prompt_pad + step)


def sequence_attention_mask(
    lengths: jnp.ndarray, prompt_pad: int, total_len: int
) -> jnp.ndarray:
    """``[B, S, S]`` causal mask over a full left-padded sequence — the
    learner-side twin of the prefill/decode masks, so the training forward
    recomputes exactly the distribution the generation engine sampled
    from (pad-prefix columns excluded)."""
    cols = jnp.arange(total_len)[None, None, :]
    rows = jnp.arange(total_len)[None, :, None]
    pad = (prompt_pad - lengths)[:, None, None]
    return (cols >= pad) & (cols <= rows)


def sequence_positions(
    lengths: jnp.ndarray, prompt_pad: int, total_len: int
) -> jnp.ndarray:
    """``[B, S]`` position ids for left-padded sequences: the first real
    token of every lane gets position 0 (pad positions clamp to 0 — they
    are masked out of attention and their outputs unused)."""
    pad = (prompt_pad - lengths)[:, None]
    return jnp.clip(jnp.arange(total_len)[None, :] - pad, 0, total_len - 1)


def packed_attention_mask(segment_ids: jnp.ndarray) -> jnp.ndarray:
    """``[B, S, S]`` segment-blocked causal mask over PACKED rows (the
    pad-free learner layout, ``genrl/rollout.py``): token ``i`` attends to
    ``j <= i`` iff both carry the same nonzero segment id.  Pad tokens
    (id 0) attend nowhere — their rows degrade to uniform under
    :func:`_masked_attention` (finite, outputs unused) and to exact zeros
    under the Pallas segment kernel; the loss mask excludes them either
    way."""
    seg = segment_ids.astype(jnp.int32)
    S = seg.shape[1]
    causal = jnp.arange(S)[None, :, None] >= jnp.arange(S)[None, None, :]
    return (
        causal
        & (seg[:, :, None] == seg[:, None, :])
        & (seg[:, :, None] > 0)
    )


def _masked_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mask: jnp.ndarray,
    out_dtype,
) -> jnp.ndarray:
    """Explicit masked attention: q ``[B, T, H, D]`` against k/v
    ``[B, S, H, D]`` with a ``[B, T, S]`` validity mask (True = attend).

    Scores/softmax run in float32 regardless of the compute dtype — the
    decode path feeds sampling logits, where bf16 softmax drift would show
    up directly in the behavior logprobs the learner's importance ratios
    divide by.  Fully-masked rows degrade to a uniform distribution (finite
    by construction) instead of NaN.
    """
    head_dim = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.float32(head_dim))
    scores = (
        jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32), k.astype(jnp.float32))
        * scale
    )
    scores = jnp.where(mask[:, None, :, :], scores, jnp.float32(-1e30))
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhts,bshd->bthd", probs, v.astype(jnp.float32))
    return out.astype(out_dtype)


class _Block(nn.Module):
    d_model: int
    num_heads: int
    mlp_ratio: int
    attn_fn: AttentionFn
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    paged_attn_fn: Optional[Callable] = None
    segment_attn_fn: Optional[Callable] = None

    @nn.compact
    def __call__(
        self,
        x: jnp.ndarray,
        layer_cache: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None,
        cache_index=None,
        attn_mask: Optional[jnp.ndarray] = None,
        paged_cache: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None,
        page_ids: Optional[jnp.ndarray] = None,
        page_offsets: Optional[jnp.ndarray] = None,
        page_table: Optional[jnp.ndarray] = None,
        attn_lengths: Optional[jnp.ndarray] = None,
        prefix_starts: Optional[jnp.ndarray] = None,
        segment_ids: Optional[jnp.ndarray] = None,
    ):
        """Full forward (``layer_cache=None``) or KV-cached incremental step.

        With ``layer_cache=(k, v)`` the block writes this call's keys/values
        at ``cache_index`` (a scalar — prompts are left-padded so every lane
        shares one cursor) and attends ``x``'s ``T`` positions against the
        whole cache under ``attn_mask`` ``[B, T, S]``; returns
        ``(out, (new_k, new_v))``.  With a mask but no cache it runs
        explicit masked attention against its own k/v (the learner-side
        forward over left-padded sequences).

        With ``paged_cache=(k_pages, v_pages)`` the block scatters this
        call's keys/values into pool pages — lane ``b``'s token ``t`` lands
        in ``(page_ids[b, t], page_offsets[b, t])``; dead-lane/pad writes
        are routed to the null page by the caller — then attends either
        *locally* against its own k/v under ``attn_mask`` (paged prefill: a
        fresh prompt's whole context is in-program, no pool read needed) or
        *through the pool* via ``paged_attn_fn(q, k_pages, v_pages,
        page_table, attn_lengths)`` (paged single-token decode); returns
        ``(out, (k_pages, v_pages))``.  Same params on every path.

        With ``page_table`` AND ``prefix_starts`` ``[B]`` this is the
        *shared-table tail prefill* (the prefix-cache path, ISSUE 14):
        the ``T`` tokens sit at global positions ``prefix_starts[b] + t``
        on top of a cached prefix whose K/V already lives in pool pages
        mapped by the table; this call's K/V is scattered first, then
        attention gathers the WHOLE context (cached prefix + this chunk)
        through the table under a causal-from-start mask — a plain XLA
        gather + :func:`_masked_attention`, no kernel involvement, so
        sharing stays purely a page-table fact.
        """
        B, T, _ = x.shape
        head_dim = self.d_model // self.num_heads
        dt = dict(dtype=self.dtype, param_dtype=self.param_dtype)
        h = nn.LayerNorm(use_bias=False, dtype=self.dtype)(x)
        qkv = nn.Dense(3 * self.d_model, use_bias=False, name="qkv", **dt)(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shape = (B, T, self.num_heads, head_dim)
        q, k, v = q.reshape(shape), k.reshape(shape), v.reshape(shape)
        new_cache = None
        if paged_cache is not None:
            kp, vp = paged_cache
            # flat single-axis scatter (page_id * page_size + offset): the
            # reshape is a bitcast and XLA:CPU lowers 1-level row scatters
            # measurably faster than the 2-level fancy-index form
            N, ps = kp.shape[0], kp.shape[1]
            flat_idx = (page_ids * ps + page_offsets).reshape(B * T)
            kp = (
                kp.reshape(N * ps, *kp.shape[2:])
                .at[flat_idx]
                .set(k.astype(kp.dtype).reshape(B * T, *k.shape[2:]))
                .reshape(kp.shape)
            )
            vp = (
                vp.reshape(N * ps, *vp.shape[2:])
                .at[flat_idx]
                .set(v.astype(vp.dtype).reshape(B * T, *v.shape[2:]))
                .reshape(vp.shape)
            )
            if page_table is not None and prefix_starts is not None:
                # shared-table tail prefill: gather the whole context
                # (cached prefix pages + the tail just scattered above)
                # through the table, attend causal-from-start — the
                # compute twin of the decode seam at T > 1, kernel-free.
                # The speculative verify pass (genrl/continuous.py) rides
                # this exact path with T = draft bucket + 1: slot j is
                # position prefix_starts + j, the pos <= qpos mask keeps
                # rejected slots' K/V (garbage past the cursor) out of
                # every query, so draft rollback never touches the device
                M = page_table.shape[1]
                gidx = (
                    page_table[:, :, None] * ps
                    + jnp.arange(ps)[None, None, :]
                ).reshape(B, M * ps)
                kg = kp.reshape(N * ps, *kp.shape[2:])[gidx]
                vg = vp.reshape(N * ps, *vp.shape[2:])[gidx]
                pos = jnp.arange(M * ps)[None, None, :]
                qpos = (
                    prefix_starts[:, None] + jnp.arange(T)[None, :]
                )[:, :, None]
                out = _masked_attention(
                    q, kg, vg, pos <= qpos, self.dtype
                )
            elif page_table is not None:
                paged_attn = self.paged_attn_fn or paged_attention_reference
                out = paged_attn(q, kp, vp, page_table, attn_lengths)
                out = out.astype(self.dtype)
            else:
                out = _masked_attention(q, k, v, attn_mask, self.dtype)
            new_cache = (kp, vp)
        elif layer_cache is not None:
            ck, cv = layer_cache
            idx = jnp.asarray(cache_index, jnp.int32)
            zero = jnp.zeros((), jnp.int32)
            ck = lax.dynamic_update_slice(
                ck, k.astype(ck.dtype), (zero, idx, zero, zero)
            )
            cv = lax.dynamic_update_slice(
                cv, v.astype(cv.dtype), (zero, idx, zero, zero)
            )
            out = _masked_attention(q, ck, cv, attn_mask, self.dtype)
            new_cache = (ck, cv)
        elif segment_ids is not None and self.segment_attn_fn is not None:
            # packed-row training attention through the flash seam: the
            # kernel enforces the segment-blocked causal rule and skips
            # fully-masked (cross-segment / pad) blocks entirely
            out = self.segment_attn_fn(q, k, v, segment_ids)
            out = out.astype(self.dtype)
        elif attn_mask is not None:
            out = _masked_attention(q, k, v, attn_mask, self.dtype)
        else:
            out = self.attn_fn(q, k, v)
        out = nn.Dense(self.d_model, use_bias=False, name="proj", **dt)(
            out.reshape(B, T, self.d_model)
        )
        x = x + out
        h = nn.LayerNorm(use_bias=False, dtype=self.dtype)(x)
        h = nn.Dense(self.mlp_ratio * self.d_model, name="mlp_in", **dt)(h)
        h = nn.gelu(h)
        h = nn.Dense(self.d_model, name="mlp_out", **dt)(h)
        x = x + h
        if new_cache is not None:
            return x, new_cache
        return x


class TransformerPolicy(nn.Module):
    """Causal transformer actor-critic over ``[B, T, obs_dim]`` features.

    ``attn_fn``: defaults to single-device causal :func:`full_attention`;
    pass a closed-over :func:`ring_attention` (inside ``shard_map``) for
    sequence-parallel execution.  NOTE: a custom ``attn_fn`` must apply its
    own causal masking — the default here is causal.

    ``use_flash=True`` swaps in the Pallas flash kernel
    (:func:`scalerl_tpu.ops.pallas_attention.flash_attention`): blockwise
    online-softmax attention that never materializes ``[T, T]`` scores —
    the right default on TPU once ``T`` is long (ignored when ``attn_fn``
    is given).
    """

    num_actions: int
    d_model: int = 128
    num_heads: int = 4
    num_layers: int = 2
    mlp_ratio: int = 4
    max_len: int = 4096
    attn_fn: Optional[AttentionFn] = None
    use_flash: bool = False
    # Token mode (the genrl sequence-RL plane): when set, ``obs`` is an
    # int32 ``[B, T]`` token-id array embedded through a learned table
    # instead of the Dense feature embed.  ``num_actions`` is then the
    # vocabulary the policy head scores (typically == vocab_size).
    vocab_size: Optional[int] = None
    # Mixed precision: blocks compute in ``dtype`` with params stored in
    # ``param_dtype`` (bf16/bf16 on the sharded learner plane); the heads
    # always emit float32 so the loss/V-trace math stays full precision.
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    # Sharded-activation seam: when set (``parallel.logical
    # .activation_constraint``), applied to the residual stream after the
    # embedding and after every block — pins inter-layer activations to
    # batch-over-dp / replicated-over-mp so GSPMD derives the per-block
    # head/mlp reshard from the weight shardings alone.
    constrain: Optional[Callable] = None
    # Paged-attention seam (the continuous-batching decode plane): the
    # gather-through-page-table attention used when ``paged_cache`` is
    # passed with a ``page_table`` — ``ops.pallas_paged_attention
    # .make_paged_attn_fn`` resolves Pallas-on-TPU / XLA-gather-elsewhere;
    # None defaults to the XLA reference.
    paged_attn_fn: Optional[Callable] = None
    # Packed-learner seam (the pad-free training plane, ISSUE 15): the
    # segment-blocked causal self-attention used when ``segment_ids`` is
    # passed — ``ops.pallas_attention.make_segment_attn_fn`` resolves
    # Pallas-flash-on-TPU / None-elsewhere; None builds the dense
    # :func:`packed_attention_mask` and rides ``_masked_attention``.
    segment_attn_fn: Optional[Callable] = None

    @nn.compact
    def __call__(
        self,
        obs: jnp.ndarray,
        positions: Optional[jnp.ndarray] = None,
        kv_cache: Optional[KVCache] = None,
        cache_index=None,
        attn_mask: Optional[jnp.ndarray] = None,
        paged_cache: Optional[PagedKVCache] = None,
        page_ids: Optional[jnp.ndarray] = None,
        page_offsets: Optional[jnp.ndarray] = None,
        page_table: Optional[jnp.ndarray] = None,
        attn_lengths: Optional[jnp.ndarray] = None,
        prefix_starts: Optional[jnp.ndarray] = None,
        segment_ids: Optional[jnp.ndarray] = None,
    ):
        """Full forward, masked full forward, or KV-cached incremental step.

        - ``kv_cache=None, attn_mask=None``: the original whole-trajectory
          forward (causal ``attn_fn``) returning :class:`TransformerOutput`.
        - ``kv_cache=None, attn_mask=[B, T, T]``: full forward under an
          explicit mask (:func:`sequence_attention_mask`) — the learner
          pass over left-padded generated sequences.
        - ``kv_cache=KVCache, cache_index=i, attn_mask=[B, T, S]``: write
          this call's k/v at ``i`` and attend against the cache — prefill
          (``T = prompt bucket``, ``i = 0``) and single-token decode
          (``T = 1``, ``i = prompt_pad + step``) both go through here,
          sharing every parameter with the training forward.  Returns
          ``(TransformerOutput, new_cache)``.
        - ``paged_cache=PagedKVCache`` (the continuous-batching plane):
          scatter this call's k/v into pool pages at ``(page_ids[b, t],
          page_offsets[b, t])``.  With ``attn_mask=[B, T, T]`` and no
          ``page_table`` this is paged *prefill* over RIGHT-padded compact
          prompts (:func:`prompt_attention_mask` — attention is local, the
          pool is write-only); with ``page_table=[B, M]`` +
          ``attn_lengths=[B]`` and ``T = 1`` it is paged *decode*
          (attention gathers through the table); with ``page_table`` +
          ``prefix_starts=[B]`` it is the shared-table *tail prefill*
          over a cached prefix (the prefix-cache path — see
          :class:`_Block`).  Returns
          ``(TransformerOutput, new_paged_cache)``.  Same params as every
          other path.
        - ``segment_ids=[B, S]`` (the pad-free packed learner, ISSUE 15):
          full forward over PACKED rows holding several independent
          sequences — tokens attend causally WITHIN their own nonzero
          segment only.  Callers pass per-segment ``positions`` (reset to
          0 at every segment start, ``genrl/rollout.py``).  With
          ``segment_attn_fn`` set the blocks ride the Pallas segment
          flash kernel; otherwise the dense
          :func:`packed_attention_mask` feeds the existing masked path.
          Same params as every other path.
        """
        B, T = obs.shape[:2]
        if T > self.max_len:
            # out-of-range gathers clamp silently under jit, which would
            # alias every late position onto one embedding
            raise ValueError(
                f"sequence length {T} exceeds max_len={self.max_len}"
            )
        attn = self.attn_fn
        if attn is None:
            base = flash_attention if self.use_flash else full_attention
            attn = lambda q, k, v: base(q, k, v, causal=True)  # noqa: E731
        if segment_ids is not None and self.segment_attn_fn is None:
            # dense packed fallback: ONE [B, S, S] mask shared by every
            # block — the XLA reference path and the off-TPU shape
            attn_mask = packed_attention_mask(segment_ids)
            segment_ids = None
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(T), (B, T))
        c = self.constrain if self.constrain is not None else (lambda x: x)
        if self.vocab_size is not None:
            x = nn.Embed(
                self.vocab_size, self.d_model, name="token_embed",
                dtype=self.dtype, param_dtype=self.param_dtype,
            )(obs.astype(jnp.int32))
        else:
            x = nn.Dense(
                self.d_model, name="obs_embed",
                dtype=self.dtype, param_dtype=self.param_dtype,
            )(obs.reshape(B, T, -1).astype(self.dtype))
        pos_tab = self.param(
            "pos_embed",
            nn.initializers.normal(0.02),
            (self.max_len, self.d_model),
            self.param_dtype,
        )
        x = c(x + pos_tab[positions].astype(self.dtype))
        new_k = []
        new_v = []
        for i in range(self.num_layers):
            block = _Block(
                self.d_model,
                self.num_heads,
                self.mlp_ratio,
                attn,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                paged_attn_fn=self.paged_attn_fn,
                segment_attn_fn=self.segment_attn_fn,
                name=f"block_{i}",
            )
            if paged_cache is not None:
                x, (bk, bv) = block(
                    x,
                    attn_mask=attn_mask,
                    paged_cache=(paged_cache.k[i], paged_cache.v[i]),
                    page_ids=page_ids,
                    page_offsets=page_offsets,
                    page_table=page_table,
                    attn_lengths=attn_lengths,
                    prefix_starts=prefix_starts,
                )
                new_k.append(bk)
                new_v.append(bv)
            elif kv_cache is not None:
                x, (bk, bv) = block(
                    x,
                    layer_cache=(kv_cache.k[i], kv_cache.v[i]),
                    cache_index=cache_index,
                    attn_mask=attn_mask,
                )
                new_k.append(bk)
                new_v.append(bv)
            elif segment_ids is not None:
                x = block(x, segment_ids=segment_ids)
            else:
                x = block(x, attn_mask=attn_mask)
            x = c(x)
        x = nn.LayerNorm(use_bias=False, name="final_norm", dtype=jnp.float32)(
            x.astype(jnp.float32)
        )
        policy_logits = nn.Dense(self.num_actions, name="policy_head")(x)
        baseline = nn.Dense(1, name="value_head")(x).squeeze(-1)
        out = TransformerOutput(policy_logits, baseline)
        if paged_cache is not None:
            return out, PagedKVCache(k=tuple(new_k), v=tuple(new_v))
        if kv_cache is not None:
            return out, KVCache(k=tuple(new_k), v=tuple(new_v))
        return out
