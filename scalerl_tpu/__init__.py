"""scalerl_tpu: a TPU-native (JAX/XLA/pjit/Pallas) distributed deep-RL framework.

Re-designed from scratch with the capabilities of jianzhnie/ScaleRL
(reference mounted at /root/reference), built TPU-first:

- All neural-net compute (acting inference + learning) runs on TPU inside
  jitted, batched functions (SEED-RL topology) instead of per-process CPU
  inference (reference: ``scalerl/algorithms/impala/impala_atari.py:196``).
- Learner data-parallelism is an XLA ``psum`` over an ICI device mesh
  (reference: HF Accelerate / NCCL, ``scalerl/trainer/off_policy.py:118``).
- Replay buffers are static-shape pytree ring buffers living in HBM with
  device-side sampling (reference: Python deques, ``scalerl/data/replay_buffer.py``).
- Temporal math (V-trace, n-step returns, LSTM unrolls) is ``jax.lax.scan``
  (reference: Python reverse loops, ``scalerl/algorithms/impala/vtrace.py:151``).

Package layout
--------------
- ``config``   — dataclass argument schemas + CLI parsing
- ``utils``    — logging, schedulers, timers, metrics, progress
- ``envs``     — host-side Gym/PettingZoo envs + JAX-native device envs
- ``data``     — HBM replay (uniform / n-step / prioritized), trajectory structs
- ``models``   — Flax networks (MLP heads, IMPALA AtariNet)
- ``ops``      — pure-functional RL math (V-trace, returns, losses)
- ``parallel`` — mesh construction, sharded train steps, multi-host bring-up
- ``runtime``  — actor-learner runtime: rollout queues, inference server,
                 parameter server, TCP transport, worker fleet
- ``agents``   — DQN, A3C/A2C, PPO, IMPALA, Ape-X agents
- ``trainer``  — trainer loops (off-policy, actor-learner)
"""

__version__ = "0.1.0"

from scalerl_tpu.config import (  # noqa: F401
    A3CArguments,
    ApexArguments,
    DQNArguments,
    GenRLArguments,
    ImpalaArguments,
    PPOArguments,
    RLArguments,
    parse_args,
)
