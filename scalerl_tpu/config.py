"""Argument schemas for every algorithm family, plus a dataclass-driven CLI.

Capability parity with the reference's config system
(``scalerl/algorithms/rl_args.py:8-362``: ``RLArguments`` / ``DQNArguments`` /
``A3CArguments`` dataclasses with ``metadata={'help': ...}`` parsed by tyro at
``examples/test_dqn.py:18``), with two deliberate fixes:

1. The reference's IMPALA/Ape-X read many fields that were never declared on
   any dataclass (``impala_atari.py:56,72,303,308,325-327,375,412,502`` read
   ``use_lstm``/``num_buffers``/``reward_clipping``/``discounting``/
   ``baseline_cost``/``entropy_cost``/``total_steps``/``output_dir``/
   ``disable_checkpoint`` off a bare ``RLArguments``).  Here every algorithm
   has a complete schema (``ImpalaArguments``, ``ApexArguments``) and a
   ``validate()`` hook, so config drift is a constructor error, not a crash
   three processes deep.
2. tyro is not a dependency: ``parse_args`` generates an argparse CLI directly
   from dataclass fields (type, default, and ``metadata={'help': ...}`` when a
   field declares it), so entry scripts keep the ``--field value`` surface of
   the reference examples.
"""

from __future__ import annotations

import argparse
import dataclasses
from dataclasses import dataclass, fields
from typing import Optional, Sequence, Type, TypeVar

T = TypeVar("T")


@dataclass
class RLArguments:
    """Common arguments shared by every algorithm family.

    Parity target: ``scalerl/algorithms/rl_args.py:8-159``.
    """

    # Project / run identity
    project: str = "scalerl_tpu"
    algo_name: str = "dqn"
    seed: int = 42

    # Device / mesh topology (TPU-native replacement for the reference's
    # ``device: cuda`` + accelerate YAML, rl_args.py:25 + accelerate_config.yaml)
    platform: str = "auto"  # auto | tpu | cpu
    num_devices: int = 0  # 0 = all visible devices
    mesh_shape: Optional[str] = None  # e.g. "dp=8" or "dp=4,mp=2"
    use_bfloat16: bool = True

    # Sharded big-model learner (parallel/logical.py, docs/PERFORMANCE.md
    # "Sharded learner"): mp_size > 1 shards the policy's heads/mlp/vocab/
    # expert dims over the named `mp` mesh axis so policies too big for one
    # chip's HBM train anyway; dp_size 0 = every remaining device
    # (n_devices // mp_size).  The trainer families resolve these through
    # maybe_enable_mesh_from_args; an explicit mesh_shape wins over both.
    mp_size: int = 1
    dp_size: int = 0
    # Policy architecture override for the actor-learner families:
    # "transformer" | "moe" pick the mp-shardable adapters
    # (models/transformer_policy.py); "auto" keeps the conv/MLP zoo.
    policy_arch: str = "auto"
    d_model: int = 128
    n_layers: int = 2
    n_heads: int = 4
    moe_experts: int = 8
    moe_hidden: int = 256
    # bf16 params + compute with fp32 optimizer state (the sharded-learner
    # mixed-precision layout: parallel.train_step.fp32_optimizer_state).
    # Only honored by the mp-shardable architectures.
    bf16_params: bool = False

    # Environment
    env_id: str = "CartPole-v1"
    num_envs: int = 8
    capture_video: bool = False
    env_backend: str = "gym"  # gym | jax (device-native envs)

    # Replay / rollout
    buffer_size: int = 10000
    batch_size: int = 32
    rollout_length: int = 20
    warmup_learn_steps: int = 500

    # Optimisation
    learning_rate: float = 1e-3
    gamma: float = 0.99
    max_grad_norm: float = 40.0

    # Training loop
    max_timesteps: int = 100_000
    train_frequency: int = 10
    eval_episodes: int = 5
    eval_frequency: int = 1000
    logger_frequency: int = 500

    # Actors
    num_actors: int = 4

    # Logging / checkpointing
    work_dir: str = "work_dirs"
    logger_backend: str = "tensorboard"  # tensorboard | wandb | none
    save_model: bool = True
    save_frequency: int = 10_000
    disable_checkpoint: bool = False
    # Path to a previous run directory (the one holding model_dir/tb_log) to
    # resume from: restores train state, replay cursors, and logger counters
    # (parity: tensorboard.py:65-82 / wandb.py:104-160 restore_data, which
    # the reference had but its trainers never surfaced as a flag).
    resume: str = ""

    # Supervision (runtime/supervisor.py)
    # Wall-clock resume-save cadence alongside the frame-gated
    # save_frequency: whichever fires first triggers save_resume, bounding
    # work lost to a preemption on slow-frame runs.  <= 0 disables the
    # wall-clock gate.
    checkpoint_interval_s: float = 600.0
    # How many displaced resume checkpoints to retain (resume.prev,
    # resume.prev2, ...); load falls back through the chain when the latest
    # is corrupt/partial.  0 keeps only the latest (no fallback).
    checkpoint_keep_last: int = 1
    # Stall watchdog deadline: if no trainer progress counter advances for
    # this many seconds, dump all-thread stacks + queue/ring occupancy and
    # fail fast (or invoke a recovery callback).  <= 0 disables.
    watchdog_timeout_s: float = 0.0
    # SIGTERM/SIGINT trigger save_resume at the next safe point and a clean
    # exit (TPU preemption safety); a second signal force-quits.
    handle_preemption: bool = True

    # Observability (runtime/telemetry.py, utils/profiling.py)
    # Device+host trace directory: when set, trainers/bench wrap their
    # measure loops in jax.profiler traces (utils.profiling.maybe_trace)
    # with a step_marker per fused chunk so device streams line up against
    # telemetry spans in the trace viewer.  Empty disables tracing.
    profile_dir: str = ""
    # Telemetry export directory: when set, a background loop writes
    # periodic JSONL snapshots (telemetry.jsonl) and a Prometheus-style
    # text exposition file (metrics.prom) from the process registry.
    # Empty defaults to <run_dir>/telemetry when telemetry_interval_s > 0.
    telemetry_dir: str = ""
    # Export cadence in seconds; <= 0 disables the export loop entirely.
    telemetry_interval_s: float = 30.0

    # Numerical fault tolerance (parallel/train_step.py, runtime/chaos.py)
    # All-finite update guard: a learn step whose result contains NaN/Inf is
    # skipped (lax.cond inside the jitted step — no extra dispatch) and
    # counted in the batched metrics as skipped_steps/nonfinite_grads.
    nonfinite_guard: bool = True
    # Guard amortization: run the (single fused-reduction) all-finite check
    # only on learn steps where state.step % K == 0.  K=1 (default)
    # preserves check-every-step semantics; K>1 makes the guard's cost
    # ~1/K per step — a divergence is still caught within K-1 steps, which
    # the tripwire's consecutive-skip window tolerates.  The env fast-off
    # SCALERL_NONFINITE_GUARD=0 compiles the guard out entirely instead.
    nonfinite_check_every: int = 1
    # Divergence tripwire: after this many CONSECUTIVE skipped learn steps
    # the trainer restores agent state from the last good resume checkpoint
    # (falling back through the .prev chain).  <= 0 disables rollback; the
    # guard still skips individual bad steps.
    divergence_rollback_steps: int = 0

    # Elastic fleet (runtime/autoscaler.py + fleet dynamic admission/drain)
    # Autoscaler control loop over the DCN actor fleet: reads the telemetry
    # plane's tuning triad (actor fps vs learner steps/s vs queue occupancy)
    # plus the bounded-admission shed counters, and issues scale-up /
    # drain decisions through the cluster executor — with hysteresis and a
    # cooldown so heartbeat jitter never flaps the fleet.  Off by default;
    # the fleet entry scripts wire it when enabled.
    autoscale: bool = False
    # Hard floor: a preemption wave dropping the fleet below this is
    # backfilled immediately (no hysteresis, no cooldown).
    autoscale_min_workers: int = 1
    # Hard ceiling for scale-up decisions.
    autoscale_max_workers: int = 32
    # Evaluation cadence of the control loop, seconds.
    autoscale_interval_s: float = 5.0
    # Hold window after any scale action (spawn/drain take seconds to bite;
    # re-acting on pre-action signals is how fleets flap).
    autoscale_cooldown_s: float = 30.0
    # Consecutive same-direction pressure verdicts required before acting
    # (scale-down requires one more than scale-up).
    autoscale_hysteresis: int = 2
    # Generation-tier guard (disaggregated sequence RL): consumed data
    # staler than this many learner steps (the unified staleness gauge)
    # is scale-up pressure on the generation fleet.  0 disables the rule.
    autoscale_max_staleness: float = 0.0
    # Serving-tier capacity rules (the router's replica fleet,
    # serving/router.py): aggregate p95 past the up threshold adds a
    # replica; under the down threshold drains one.  Opposite semantics
    # from the actor-fleet p95 guard — configure per autoscaler instance.
    # 0 disables either side.
    autoscale_serving_up_p95_ms: float = 0.0
    autoscale_serving_down_p95_ms: float = 0.0

    # Pallas kernels (ops/pallas_vtrace.py, ops/pallas_per.py): route the
    # V-trace target computation and the PER priority/sum-tree update
    # through the fused TPU kernels (interpret-mode on CPU for parity
    # tests).  Off by default: the XLA reference ops are the baseline the
    # kernels are bit-tolerance-tested against.
    use_pallas: bool = False

    def validate(self) -> None:
        if self.batch_size <= 0:
            raise ValueError(f"batch_size must be positive, got {self.batch_size}")
        if self.num_envs <= 0:
            raise ValueError(f"num_envs must be positive, got {self.num_envs}")
        if self.buffer_size < self.batch_size:
            raise ValueError(
                f"buffer_size ({self.buffer_size}) must be >= batch_size "
                f"({self.batch_size})"
            )
        if self.nonfinite_check_every < 1:
            raise ValueError(
                "nonfinite_check_every must be >= 1, got "
                f"{self.nonfinite_check_every}"
            )
        if self.mp_size < 1:
            raise ValueError(f"mp_size must be >= 1, got {self.mp_size}")
        if self.dp_size < 0:
            raise ValueError(f"dp_size must be >= 0, got {self.dp_size}")
        if self.policy_arch not in ("auto", "transformer", "moe"):
            raise ValueError(
                "policy_arch must be auto | transformer | moe, got "
                f"{self.policy_arch!r}"
            )
        if self.autoscale_min_workers < 0:
            raise ValueError(
                "autoscale_min_workers must be >= 0, got "
                f"{self.autoscale_min_workers}"
            )
        if self.autoscale_max_workers < self.autoscale_min_workers:
            raise ValueError(
                f"autoscale_max_workers ({self.autoscale_max_workers}) must "
                f"be >= autoscale_min_workers ({self.autoscale_min_workers})"
            )
        if self.autoscale and self.autoscale_interval_s <= 0:
            raise ValueError(
                "autoscale_interval_s must be positive with autoscale on, "
                f"got {self.autoscale_interval_s}"
            )
        if self.autoscale_hysteresis < 1:
            raise ValueError(
                "autoscale_hysteresis must be >= 1, got "
                f"{self.autoscale_hysteresis}"
            )
        if (
            self.autoscale_serving_up_p95_ms > 0
            and self.autoscale_serving_down_p95_ms
            >= self.autoscale_serving_up_p95_ms
        ):
            raise ValueError(
                "autoscale_serving_down_p95_ms "
                f"({self.autoscale_serving_down_p95_ms}) must be < "
                "autoscale_serving_up_p95_ms "
                f"({self.autoscale_serving_up_p95_ms})"
            )


@dataclass
class DQNArguments(RLArguments):
    """DQN family options. Parity target: ``rl_args.py:163-315``."""

    algo_name: str = "dqn"
    # Architecture flags
    double_dqn: bool = True
    dueling_dqn: bool = False
    noisy_dqn: bool = False
    noisy_std: float = 0.5
    # Categorical (C51) distributional head (parity: rl_args.py:201-226 —
    # declared there, implemented here)
    categorical_dqn: bool = False
    num_atoms: int = 51
    v_min: float = 0.0
    v_max: float = 200.0
    hidden_sizes: str = "128,128"
    # Exploration schedule
    eps_greedy_start: float = 1.0
    eps_greedy_end: float = 0.05
    eps_greedy_scheduler: str = "linear"  # linear | piecewise
    exploration_fraction: float = 0.5
    # Learning-rate schedule
    lr_scheduler: str = "none"  # none | linear | multistep
    min_learning_rate: float = 1e-5
    # Target network
    target_update_frequency: int = 100
    soft_update_tau: float = 0.005
    use_soft_update: bool = True
    # Replay variants
    use_per: bool = False
    per_alpha: float = 0.6
    per_beta: float = 0.4
    per_beta_final: float = 1.0
    n_steps: int = 1

    def validate(self) -> None:
        super().validate()
        if self.n_steps < 1:
            raise ValueError(f"n_steps must be >= 1, got {self.n_steps}")
        if not (0.0 <= self.per_alpha <= 1.0):
            raise ValueError(f"per_alpha must be in [0, 1], got {self.per_alpha}")
        if self.categorical_dqn:
            if self.num_atoms < 2:
                raise ValueError(f"num_atoms must be >= 2, got {self.num_atoms}")
            if not self.v_max > self.v_min:
                raise ValueError(
                    f"v_max ({self.v_max}) must exceed v_min ({self.v_min})"
                )


@dataclass
class A3CArguments(RLArguments):
    """A3C/A2C options. Parity target: ``rl_args.py:319-362``.

    The Hogwild shared-gradient design (``parallel_a3c.py:221-233``) does not
    map to XLA; the TPU build runs synchronous batched advantage actor-critic
    over the same actor fleet, so the knobs here govern that runtime.
    """

    algo_name: str = "a3c"
    num_workers: int = 8
    # the unroll is the inherited ``rollout_length`` field (default 20)
    value_loss_coef: float = 0.5
    entropy_coef: float = 0.01
    gae_lambda: float = 1.0
    hidden_sizes: str = "128,128"  # MLP torso (flat obs)
    use_lstm: bool = True  # pixel obs: conv+LSTM (a3c/utils/atari_model.py:57-144)
    hidden_size: int = 256  # pixel obs: LSTM width (reference LSTMCell(256))
    max_episode_steps: int = 500
    max_grad_norm: float = 50.0  # reference clip(50), parallel_a3c.py:368
    # running mean/std obs normalization (atari_env.py:87-122) and
    # normalized-columns head init (atari_model.py:9-24)
    normalize_obs: bool = False
    normalized_init: bool = False


@dataclass
class SACArguments(RLArguments):
    """SAC options (beyond-parity: continuous control).

    The reference declares continuous-capable actor/critic MLPs in its
    network zoo (``network.py:27-67``) but ships no continuous-action
    algorithm; SAC (Haarnoja et al. 2018) completes that story: squashed-
    Gaussian actor, clipped double-Q critics, automatic entropy
    temperature, soft target updates — the whole update one jitted program
    over device-replay batches.
    """

    algo_name: str = "sac"
    env_id: str = "Pendulum-v1"  # continuous algo -> continuous default env
    hidden_sizes: str = "256,256"
    # Soft target update
    soft_update_tau: float = 0.005
    # Entropy temperature: alpha auto-tunes toward target entropy
    # (= -action_dim * target_entropy_scale)
    auto_alpha: bool = True
    init_alpha: float = 0.2
    target_entropy_scale: float = 1.0
    alpha_learning_rate: float = 3e-4
    actor_learning_rate: float = 3e-4  # critics use the base learning_rate
    # Replay (uniform or PER, sharing the DQN pipeline fields)
    use_per: bool = False
    per_alpha: float = 0.6
    per_beta: float = 0.4
    per_beta_final: float = 1.0
    n_steps: int = 1

    def validate(self) -> None:
        super().validate()
        if not 0.0 < self.soft_update_tau <= 1.0:
            raise ValueError(
                f"soft_update_tau must be in (0, 1], got {self.soft_update_tau}"
            )
        if self.init_alpha <= 0.0:
            raise ValueError(f"init_alpha must be positive, got {self.init_alpha}")
        if self.n_steps < 1:
            raise ValueError(f"n_steps must be >= 1, got {self.n_steps}")


@dataclass
class TD3Arguments(RLArguments):
    """TD3 options (beyond-parity continuous control, companion to SAC):
    deterministic tanh actor + exploration noise, clipped double-Q,
    target policy smoothing, delayed actor/target updates."""

    algo_name: str = "td3"
    env_id: str = "Pendulum-v1"
    hidden_sizes: str = "256,256"
    soft_update_tau: float = 0.005
    policy_delay: int = 2
    explore_noise_std: float = 0.1  # fraction of action scale
    target_noise_std: float = 0.2
    target_noise_clip: float = 0.5
    actor_learning_rate: float = 3e-4
    use_per: bool = False
    per_alpha: float = 0.6
    per_beta: float = 0.4
    per_beta_final: float = 1.0
    n_steps: int = 1

    def validate(self) -> None:
        super().validate()
        if self.policy_delay < 1:
            raise ValueError(
                f"policy_delay must be >= 1, got {self.policy_delay}"
            )
        if not 0.0 < self.soft_update_tau <= 1.0:
            raise ValueError(
                f"soft_update_tau must be in (0, 1], got {self.soft_update_tau}"
            )
        if self.n_steps < 1:
            raise ValueError(f"n_steps must be >= 1, got {self.n_steps}")


@dataclass
class R2D2Arguments(RLArguments):
    """R2D2 options (beyond-parity: recurrent replay distributed DQN,
    Kapturowski et al. 2019 — the Ape-X lineage the reference's README
    cites without a recurrent member).

    Sequences of ``rollout_length`` steps are stored with the actor's
    entering LSTM state; the learner burns in the first ``burn_in`` rows
    (no gradient) to de-stale the stored state, trains Q on the rest with
    n-step double-Q targets under the h-rescaling, and feeds back
    per-sequence priorities ``eta * max|td| + (1 - eta) * mean|td|``.
    """

    algo_name: str = "r2d2"
    # Model
    use_lstm: bool = True
    hidden_size: int = 256
    lstm_layers: int = 1
    dueling_dqn: bool = True
    # Sequence pipeline (actor side = the host actor plane's [T+1, B] slots)
    rollout_length: int = 20
    burn_in: int = 8
    num_actors: int = 2
    num_buffers: int = 16
    # Exploration: per-actor eps ladder (Ape-X convention)
    eps_base: float = 0.4
    eps_alpha: float = 7.0
    # Learning
    n_steps: int = 3
    batch_size: int = 16  # sequences per update
    replay_capacity: int = 2048  # sequences
    warmup_sequences: int = 64
    train_intensity: int = 1  # learn steps per inserted slot batch
    target_update_frequency: int = 400
    # PER over sequences
    per_alpha: float = 0.6
    per_beta: float = 0.4
    priority_eta: float = 0.9
    # Value rescaling h(x) = sign(x)(sqrt(|x|+1)-1) + eps*x
    value_rescale_eps: float = 1e-3

    def validate(self) -> None:
        super().validate()
        if not 0 <= self.burn_in < self.rollout_length:
            raise ValueError(
                f"burn_in ({self.burn_in}) must be in [0, rollout_length="
                f"{self.rollout_length})"
            )
        if self.n_steps < 1:
            raise ValueError(f"n_steps must be >= 1, got {self.n_steps}")
        if self.burn_in + self.n_steps >= self.rollout_length + 1:
            raise ValueError(
                "rollout_length must leave at least one trainable row: need "
                f"burn_in ({self.burn_in}) + n_steps ({self.n_steps}) <= "
                f"rollout_length ({self.rollout_length})"
            )
        if not 0.0 <= self.priority_eta <= 1.0:
            raise ValueError(
                f"priority_eta must be in [0, 1], got {self.priority_eta}"
            )


@dataclass
class PPOArguments(RLArguments):
    """PPO options (beyond-parity algorithm family).

    The reference ships A3C/DQN/Ape-X/IMPALA and lists DD-PPO in its
    architecture bibliography (``README.md:21-53``) without implementing it;
    this schema drives the PPO agent (``agents/ppo.py``) on the same
    on-policy runtime as A3C.  Data-parallel PPO over a mesh
    (``agent.enable_mesh``) is the DD-PPO topology: every chip runs the
    full epochs x minibatches schedule with gradients all-reduced per
    minibatch step.

    Learning-rate convention: losses use the repo-wide SUM over [T, b]
    (see ``agents/ppo.py:ppo_loss``), not the per-element mean of SB3/
    baselines PPO — so the effective gradient scale grows with
    ``rollout_length`` and lanes per minibatch, and published PPO lrs
    (3e-4 etc.) must be divided by the minibatch element count (or
    retuned) when transferring configs.
    """

    algo_name: str = "ppo"
    num_workers: int = 8
    # Clipped-surrogate objective
    clip_range: float = 0.2
    clip_range_vf: float = 0.0  # 0 disables value clipping
    ppo_epochs: int = 4
    num_minibatches: int = 4  # minibatches per epoch, split over env lanes
    gae_lambda: float = 0.95
    value_loss_coef: float = 0.5
    entropy_coef: float = 0.01
    normalize_advantage: bool = True
    # "sum" (repo convention, gradient scale grows with minibatch elements)
    # or "mean" (SB3/baselines convention: published lrs transfer as-is)
    loss_reduction: str = "sum"
    # Model (same zoo as A3C: MLP for flat obs, conv[+LSTM] for pixels)
    hidden_sizes: str = "128,128"
    use_lstm: bool = False
    hidden_size: int = 256
    max_episode_steps: int = 500
    max_grad_norm: float = 0.5
    normalize_obs: bool = False
    normalized_init: bool = False

    def validate(self) -> None:
        super().validate()
        if self.num_minibatches <= 0:
            raise ValueError(
                f"num_minibatches must be positive, got {self.num_minibatches}"
            )
        if self.num_workers % self.num_minibatches != 0:
            raise ValueError(
                "minibatches split over env lanes (full sequences, so LSTM "
                f"carries stay valid): num_workers ({self.num_workers}) must "
                f"divide by num_minibatches ({self.num_minibatches})"
            )
        if self.loss_reduction not in ("sum", "mean"):
            raise ValueError(
                f"loss_reduction must be 'sum' or 'mean', got {self.loss_reduction!r}"
            )
        if self.ppo_epochs <= 0:
            raise ValueError(f"ppo_epochs must be positive, got {self.ppo_epochs}")


@dataclass
class ImpalaArguments(RLArguments):
    """IMPALA options: the complete schema the reference never declared.

    Every field the reference's trainer reads off ``args``
    (``impala_atari.py:44-515``) exists here.
    """

    algo_name: str = "impala"
    # Model
    use_lstm: bool = True
    hidden_size: int = 512
    # Compute dtype for the conv/dense torso ("float32" | "bfloat16").
    # bfloat16 feeds the MXU at full rate; params and the V-trace math stay
    # float32 (standard mixed precision)
    compute_dtype: str = "float32"
    # Rollout pipeline
    rollout_length: int = 80
    num_actors: int = 8
    # host actor topology: "threads" = SEED-style central inference
    # (HostActorLearnerTrainer); "process" = monobeast-style actor processes
    # with local CPU inference over the shm ring (the reference's topology,
    # impala_atari.py:153-220); "serving" = the full centralized inference
    # plane (scalerl_tpu/serving/): actors act through RemotePolicyClient
    # against an InferenceServer holding the one hot policy, with dynamic
    # batching, generation-tagged params, and latency SLO telemetry
    actor_mode: str = "threads"
    # Inference-plane knobs (ServingConfig.from_args; only read when
    # actor_mode="serving" or by the standalone server entrypoints):
    # flush a serve batch at this many pending env lanes ...
    serve_max_batch: int = 64
    # ... or once the oldest pending request has waited this long
    serve_max_wait_ms: float = 5.0
    # bounded admission: shed act requests beyond this queue depth instead
    # of letting the queue (and therefore latency + policy lag) grow
    # without bound; 0 disables shedding
    serve_max_pending: int = 256
    num_buffers: int = 32  # free/full queue depth (impala_atari.py:72)
    num_learner_threads: int = 1
    batch_size: int = 8
    # Loss (the discount is the inherited ``gamma`` field — no duplicate knob)
    reward_clipping: str = "abs_one"  # abs_one | none
    baseline_cost: float = 0.5
    entropy_cost: float = 0.01
    # optional linear entropy anneal: cost goes entropy_cost ->
    # entropy_cost_end over entropy_anneal_frames env frames (None/0 =
    # constant, the reference's behavior).  High-early/low-late keeps
    # exploration alive through a long incubation (the Breakout rally
    # plateau) without paying a permanently noisy policy
    entropy_cost_end: Optional[float] = None
    entropy_anneal_frames: int = 0
    vtrace_rho_clip: float = 1.0
    vtrace_c_clip: float = 1.0
    # Optimiser (RMSProp parity, impala_atari.py:313-320)
    learning_rate: float = 6e-4
    rmsprop_alpha: float = 0.99
    rmsprop_eps: float = 0.01
    rmsprop_momentum: float = 0.0
    max_grad_norm: float = 40.0
    # Run (the frame budget is the inherited ``max_timesteps`` field; the
    # wall-clock save cadence is the inherited ``checkpoint_interval_s``,
    # default 600 s — the reference's 10-minute IMPALA checkpoints)
    max_timesteps: int = 30_000_000

    # Reference-vocabulary aliases (read-only; the CLI flags are --gamma and
    # --max-timesteps — one knob per quantity, no config drift)
    @property
    def discounting(self) -> float:
        return self.gamma

    @property
    def total_steps(self) -> int:
        return self.max_timesteps

    def validate(self) -> None:
        super().validate()
        # num_buffers counts SLOTS (each slot holds one actor's vector-env
        # lanes) while batch_size counts LANES; the reference's constructor
        # check (impala_atari.py:74-77, num_buffers >= 2*batch_size) compares
        # like units because monobeast's batch_size counts rollouts/slots.
        # Porting that formula verbatim here silently forced queues ~16x
        # deeper than needed (32 slots for a 2-slot learn batch), and queue
        # depth IS worst-case policy lag — the host plane's Breakout arm
        # stalled on exactly this.  The slot-aware floor
        # (num_buffers >= max(2 * batch_size/envs_per_actor, num_actors))
        # needs the runtime env fleet shape, so the trainers enforce it;
        # here only the shape-independent minimum holds.
        if self.num_buffers < max(2, self.num_actors):
            raise ValueError(
                "num_buffers (slot count) must be at least "
                "max(2, num_actors) "
                f"(got {self.num_buffers}, num_actors={self.num_actors})"
            )
        if self.actor_mode not in ("threads", "process", "serving"):
            raise ValueError(
                "actor_mode must be threads | process | serving, got "
                f"{self.actor_mode!r}"
            )
        if self.serve_max_batch < 1:
            raise ValueError(
                f"serve_max_batch must be >= 1, got {self.serve_max_batch}"
            )
        if self.serve_max_wait_ms < 0:
            raise ValueError(
                f"serve_max_wait_ms must be >= 0, got {self.serve_max_wait_ms}"
            )
        if self.serve_max_pending < 0:
            raise ValueError(
                f"serve_max_pending must be >= 0, got {self.serve_max_pending}"
            )


@dataclass
class ImpactArguments(ImpalaArguments):
    """IMPACT options (arxiv 1912.00167): clipped target networks + a
    circular surrogate buffer on the IMPALA actor plane.

    The sample-efficiency counterweight to the sharded big-model learner:
    as the learner step gets heavier (mp-sharded transformer/MoE), the
    async actors fall behind — IMPACT keeps the chips busy by replaying
    each trajectory chunk ``replay_times`` times from a circular buffer,
    while a slow-moving *target network* anchors the surrogate objective
    (PPO-style ratio clip against the target policy, V-trace corrections
    computed target-vs-behavior) so the extra replays don't destabilize
    training the way raw IMPALA replays would.
    """

    algo_name: str = "impact"
    # learner steps between target-network refreshes (pi_target <- pi)
    target_update_frequency: int = 16
    # how many learner updates each inserted chunk participates in
    replay_times: int = 2
    # circular surrogate buffer depth, in trajectory chunks
    surrogate_capacity: int = 16
    # PPO-style clip width for the pi/pi_target surrogate ratio
    impact_clip: float = 0.3

    def validate(self) -> None:
        super().validate()
        if self.target_update_frequency < 1:
            raise ValueError(
                "target_update_frequency must be >= 1, got "
                f"{self.target_update_frequency}"
            )
        if self.replay_times < 1:
            raise ValueError(
                f"replay_times must be >= 1, got {self.replay_times}"
            )
        if self.surrogate_capacity < 1:
            raise ValueError(
                f"surrogate_capacity must be >= 1, got {self.surrogate_capacity}"
            )
        if not 0.0 < self.impact_clip < 1.0:
            raise ValueError(
                f"impact_clip must be in (0, 1), got {self.impact_clip}"
            )


@dataclass
class ApexArguments(DQNArguments):
    """Ape-X distributed prioritized replay options.

    The reference's Ape-X skeleton (``apex/apex_train.py``) reads ad-hoc
    attributes; this is the declared schema.
    """

    algo_name: str = "apex"
    use_per: bool = True
    num_actors: int = 4
    actor_update_frequency: int = 100  # publish a weight snapshot every N learn steps
    priority_update_frequency: int = 1
    eps_greedy_base: float = 0.4
    eps_greedy_alpha: float = 7.0  # per-actor eps = base ** (1 + i/(N-1) * alpha)

    def validate(self) -> None:
        super().validate()
        if self.rollout_length < self.n_steps:
            raise ValueError(
                f"rollout_length ({self.rollout_length}) must be >= n_steps "
                f"({self.n_steps}): actors fold n-step windows inside each chunk"
            )


@dataclass
class GenRLArguments(RLArguments):
    """Token-level sequence-RL options (the ``genrl/`` plane).

    One generation *round* = generate ``genrl_batch`` sequences with the
    KV-cached engine, score them with the task's rule-based reward, pack
    them into the prioritized sequence replay, sample
    ``genrl_sample_batch`` sequences, and take one token-PPO learn step.
    Model size rides the shared ``d_model``/``n_layers``/``n_heads``
    fields; the dp×mp sharded learner rides ``dp_size``/``mp_size``.
    """

    algo_name: str = "token_ppo"
    learning_rate: float = 3e-3
    max_grad_norm: float = 1.0

    # Vocabulary / sequence geometry.  Prompt and response lengths pad up
    # power-of-two bucket ladders inside the engine; the transformer's
    # max_len is derived as (prompt bucket + response bucket).
    vocab_size: int = 16
    prompt_len: int = 4  # the task's maximum true prompt length
    max_new_tokens: int = 4
    eos_token: int = -1  # < 0: fixed-length responses (no early stop)

    # Sampling (the behavior distribution — stored logprobs are under
    # EXACTLY this distribution, temperature and top-k included).
    temperature: float = 1.0
    top_k: int = 0

    # Token-PPO objective.
    clip_range: float = 0.2
    value_cost: float = 0.5
    entropy_cost: float = 0.01
    # KL-to-reference penalty (the frozen initial params); 0 disables the
    # anchor forward entirely (compiled out, not skipped at runtime).
    kl_cost: float = 0.0
    adv_norm: bool = True

    # Round geometry / replay.
    genrl_rounds: int = 200
    genrl_batch: int = 32  # sequences generated per round
    genrl_sample_batch: int = 32  # sequences per learn step
    genrl_buffer_sequences: int = 64  # sequence-replay capacity
    # Publish a param generation to the engine every N learn steps (1 =
    # per-step, the near-on-policy default; higher values trade staleness
    # for fewer device-side snapshot copies).
    genrl_push_every: int = 1
    # Decode-loop fusion: scan | unroll | auto (backend-resolved, the PR 6
    # iter_mode verdict — unroll on XLA:CPU, scan on TPU/GPU).
    genrl_iter_mode: str = "auto"

    # Engine selection (ISSUE 11): "cohort" = the fixed-cohort bucket-pair
    # engine (one jitted round, every lane runs the full response bucket);
    # "continuous" = the persistent continuous-batching engine (paged KV,
    # macro-steps, admission into freed lanes).  The trainer rides either.
    genrl_engine: str = "cohort"
    genrl_lanes: int = 0  # continuous decode lanes; 0 -> genrl_batch
    genrl_page_size: int = 8  # KV pool page size (tokens per page)
    genrl_num_pages: int = 0  # KV pool pages; 0 -> all-lane worst case
    genrl_macro_steps: int = 4  # decode substeps fused per macro-step
    # Admission flush deadline (ms): the oldest queued prompt waits at most
    # this long before a flush fires even with lanes to spare (the serving
    # batcher's max_wait_s on the admission queue); 0 = admit immediately.
    genrl_admit_wait_ms: float = 0.0
    genrl_max_pending: int = 0  # admission queue bound (0 = unbounded)
    genrl_paged_attn: str = "auto"  # pallas | xla | auto (backend)
    # Group sampling (ISSUE 14): generate this many completions per
    # prompt — the GRPO data layout.  Rounds sample genrl_batch /
    # samples_per_prompt distinct prompts; on the continuous engine each
    # group admits via submit_group (shared-prefix CoW fork, ~1/n of the
    # prefill), on the cohort engine prompts are tiled (layout only).
    samples_per_prompt: int = 1
    # Macro-step pipelining: K macro dispatches in flight, host read
    # lagging by K-1 so harvest/admission/prefill overlap device decode
    # (1 = the old synchronous semantics, parity-pinned).
    genrl_steps_in_flight: int = 2
    # Shared-prefix KV reuse: cache full prompt pages and share them
    # copy-on-write into later admissions of the same prefix (flushed on
    # every param push; off = always prefill from scratch).
    genrl_prefix_cache: bool = True
    # Speculative decoding (ISSUE 16, continuous engine only): each pass,
    # lanes self-draft up to spec_k tokens from their own n-gram table
    # (no draft model — nothing extra on the snapshot plane) and ONE
    # batched verify pass accepts/rejects them under the exact
    # speculative-sampling rule, so the output distribution is unchanged.
    # Off by default: the win depends on the task's draft acceptance rate
    # (see docs/SEQUENCE_RL.md "Speculative decoding").
    spec_enable: bool = False
    spec_k: int = 4  # draft tokens per pass when spec_enable (>= 1)
    spec_ngram: int = 3  # n-gram width the self-drafter matches

    # Pad-free packed learner (ISSUE 15): bin-pack completed sequences
    # (compact prompt+response, no intra-sequence pad) into fixed
    # [rows, learner_pack_len] rows with per-token segment ids and
    # per-segment position reset; the learn step runs segment-blocked
    # causal attention so tokens never see their row-mates.  Off (the
    # default) keeps the padded bucket-pair layout — the packed path's
    # parity twin (loss/grads agree to 1e-5 on the same sequences).
    learner_packing: bool = False
    # Packed row length; 0 derives the engine bucket pair (prompt bucket
    # + response bucket), so one row fits the longest possible sequence.
    learner_pack_len: int = 0
    # Segment attention impl for the packed forward: pallas = the flash
    # training kernel (fwd + custom_vjp bwd, cross-segment/pad blocks
    # skipped), xla = dense packed mask, auto = pallas on TPU else xla.
    learner_packed_attn: str = "auto"

    # Disaggregated dataflow (genrl/disagg.py, ISSUE 12): N generation
    # hosts behind jax-free shells stream completed sequences over the
    # fleet wire into this learner's sequence replay, with quantized
    # generation-tagged param snapshots flowing back.
    disagg_hosts: int = 2
    # Engine-shell admission capacity per host; 0 derives
    # max(1, genrl_batch // disagg_hosts) so one round's worth of lanes
    # spreads across the fleet.
    disagg_lanes_per_host: int = 0
    disagg_quantize: str = "int8"  # snapshot wire format: int8 | none
    disagg_upload_batch: int = 4  # completed sequences per uplink frame
    # How long one train round may wait for the generation fleet to
    # deliver its sequence batch before raising (a dead fleet must surface
    # as an error, not a silent hang).
    disagg_round_timeout_s: float = 120.0
    # Durable learner ledger directory (ISSUE 19): non-empty enables the
    # preemption-tolerant plane — SIGTERM at the between-rounds safe-point
    # saves lease table + dedup keys + replay + snapshot generation into
    # <dir>/learner_ledger, and the next run against the same dir resumes
    # at the same learn step under a bumped learner epoch.
    disagg_ledger_dir: str = ""

    def validate(self) -> None:
        super().validate()
        if self.vocab_size < 4:
            raise ValueError(f"vocab_size must be >= 4, got {self.vocab_size}")
        if self.prompt_len < 1 or self.max_new_tokens < 1:
            raise ValueError(
                "prompt_len and max_new_tokens must be >= 1, got "
                f"{self.prompt_len}/{self.max_new_tokens}"
            )
        if self.temperature < 0:
            raise ValueError(
                f"temperature must be >= 0 (0 = greedy), got "
                f"{self.temperature}"
            )
        if not 0.0 < self.clip_range < 1.0:
            raise ValueError(
                f"clip_range must be in (0, 1), got {self.clip_range}"
            )
        if self.kl_cost < 0 or self.value_cost < 0:
            raise ValueError(
                "kl_cost and value_cost must be >= 0, got "
                f"{self.kl_cost}/{self.value_cost}"
            )
        if self.genrl_batch < 1 or self.genrl_sample_batch < 1:
            raise ValueError(
                "genrl_batch and genrl_sample_batch must be >= 1, got "
                f"{self.genrl_batch}/{self.genrl_sample_batch}"
            )
        if self.genrl_buffer_sequences < self.genrl_batch:
            raise ValueError(
                f"genrl_buffer_sequences ({self.genrl_buffer_sequences}) "
                f"must be >= genrl_batch ({self.genrl_batch})"
            )
        if self.genrl_push_every < 1:
            raise ValueError(
                f"genrl_push_every must be >= 1, got {self.genrl_push_every}"
            )
        if self.genrl_iter_mode not in ("auto", "scan", "unroll"):
            raise ValueError(
                "genrl_iter_mode must be auto | scan | unroll, got "
                f"{self.genrl_iter_mode!r}"
            )
        if self.genrl_engine not in ("cohort", "continuous"):
            raise ValueError(
                "genrl_engine must be cohort | continuous, got "
                f"{self.genrl_engine!r}"
            )
        if self.genrl_lanes < 0 or self.genrl_page_size < 1:
            raise ValueError(
                "genrl_lanes must be >= 0 and genrl_page_size >= 1, got "
                f"{self.genrl_lanes}/{self.genrl_page_size}"
            )
        if self.genrl_macro_steps < 1:
            raise ValueError(
                f"genrl_macro_steps must be >= 1, got {self.genrl_macro_steps}"
            )
        if self.genrl_paged_attn not in ("auto", "pallas", "xla"):
            raise ValueError(
                "genrl_paged_attn must be auto | pallas | xla, got "
                f"{self.genrl_paged_attn!r}"
            )
        if self.samples_per_prompt < 1:
            raise ValueError(
                f"samples_per_prompt must be >= 1, got "
                f"{self.samples_per_prompt}"
            )
        if self.genrl_batch % self.samples_per_prompt != 0:
            raise ValueError(
                f"genrl_batch ({self.genrl_batch}) must be a multiple of "
                f"samples_per_prompt ({self.samples_per_prompt}) so rounds "
                "hold whole groups"
            )
        if self.genrl_steps_in_flight < 1:
            raise ValueError(
                f"genrl_steps_in_flight must be >= 1, got "
                f"{self.genrl_steps_in_flight}"
            )
        if self.spec_enable and self.genrl_engine != "continuous":
            raise ValueError(
                "spec_enable requires genrl_engine='continuous' (the "
                "cohort engine's fused round has no verify pass), got "
                f"{self.genrl_engine!r}"
            )
        if self.spec_enable and self.spec_k < 1:
            raise ValueError(
                f"spec_k must be >= 1 when spec_enable, got {self.spec_k}"
            )
        if self.spec_ngram < 1:
            raise ValueError(
                f"spec_ngram must be >= 1, got {self.spec_ngram}"
            )
        if self.learner_packed_attn not in ("auto", "pallas", "xla"):
            raise ValueError(
                "learner_packed_attn must be auto | pallas | xla, got "
                f"{self.learner_packed_attn!r}"
            )
        if self.learner_pack_len < 0:
            raise ValueError(
                f"learner_pack_len must be >= 0, got "
                f"{self.learner_pack_len}"
            )
        if self.learner_pack_len and (
            self.learner_pack_len < self.prompt_len + self.max_new_tokens
        ):
            raise ValueError(
                f"learner_pack_len ({self.learner_pack_len}) must fit one "
                "maximum-length sequence (prompt_len + max_new_tokens = "
                f"{self.prompt_len + self.max_new_tokens}) or every "
                "full-length completion would be shed"
            )
        if self.disagg_hosts < 1:
            raise ValueError(
                f"disagg_hosts must be >= 1, got {self.disagg_hosts}"
            )
        if self.disagg_lanes_per_host < 0 or self.disagg_upload_batch < 1:
            raise ValueError(
                "disagg_lanes_per_host must be >= 0 and "
                "disagg_upload_batch >= 1, got "
                f"{self.disagg_lanes_per_host}/{self.disagg_upload_batch}"
            )
        if self.disagg_quantize not in ("int8", "none"):
            raise ValueError(
                "disagg_quantize must be int8 | none, got "
                f"{self.disagg_quantize!r}"
            )
        if self.disagg_round_timeout_s <= 0:
            raise ValueError(
                "disagg_round_timeout_s must be positive, got "
                f"{self.disagg_round_timeout_s}"
            )


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------

_BOOL_TRUE = {"1", "true", "yes", "on"}
_BOOL_FALSE = {"0", "false", "no", "off"}


def _str2bool(v: str) -> bool:
    lv = v.lower()
    if lv in _BOOL_TRUE:
        return True
    if lv in _BOOL_FALSE:
        return False
    raise argparse.ArgumentTypeError(f"expected a boolean, got {v!r}")


def build_parser(cls: Type[T], parser: Optional[argparse.ArgumentParser] = None) -> argparse.ArgumentParser:
    """Generate an argparse parser from a dataclass schema (tyro-free)."""
    parser = parser or argparse.ArgumentParser(description=cls.__doc__)
    for f in fields(cls):  # type: ignore[arg-type]
        if not f.init:
            continue
        name = "--" + f.name.replace("_", "-")
        default = (
            f.default
            if f.default is not dataclasses.MISSING
            else f.default_factory()  # type: ignore[misc]
        )
        help_text = f.metadata.get("help", "") if f.metadata else ""
        ftype = f.type if isinstance(f.type, type) else None
        # Resolve string annotations like "int" / "Optional[str]"
        if ftype is None:
            tname = str(f.type)
            ftype = {
                "int": int,
                "float": float,
                "str": str,
                "bool": bool,
            }.get(tname, str if "str" in tname else type(default) if default is not None else str)
        if ftype is bool:
            # accept both bare `--flag` (== true) and `--flag false`
            parser.add_argument(
                name,
                type=_str2bool,
                nargs="?",
                const=True,
                default=default,
                help=help_text,
            )
        else:
            parser.add_argument(name, type=ftype, default=default, help=help_text)
    return parser


def parse_args(
    cls: Type[T] = RLArguments,  # type: ignore[assignment]
    argv: Optional[Sequence[str]] = None,
) -> T:
    """Parse CLI args into an instance of ``cls`` and validate it."""
    parser = build_parser(cls)
    ns = parser.parse_args(argv)
    kwargs = {f.name: getattr(ns, f.name) for f in fields(cls) if f.init}  # type: ignore[arg-type]
    args = cls(**kwargs)  # type: ignore[call-arg]
    if hasattr(args, "validate"):
        args.validate()
    return args
