"""Async subprocess vector env with a shared-memory observation plane.

Parity target: ``AsyncPettingZooVecEnv``
(``scalerl/envs/vector/pz_async_vec_env.py:36-897``, the reference's largest
component): subprocess-per-env, an async DEFAULT/WAITING_RESET/WAITING_STEP/
WAITING_CALL state machine, ``call``/``get_attr``/``set_attr`` passthrough,
autoreset, per-worker error funneling via an ``error_queue`` with targeted
teardown, and zero-copy shared-memory observations.

Works for any env speaking the PettingZoo *parallel* API (``possible_agents``,
``reset``, dict-keyed ``step``) — including single-agent gym envs via
``SingleAgentAdapter`` — so this one class is both the multi-agent vec env
and the shared-memory infeed staging plane for the TPU learner host.
"""

from __future__ import annotations

import enum
import multiprocessing as mp
import sys
import time
import traceback
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from scalerl_tpu.envs.vector.spec import ExperienceSpec, SharedObservationPlane
from scalerl_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class AsyncState(enum.Enum):
    DEFAULT = "default"
    WAITING_RESET = "reset"
    WAITING_STEP = "step"
    WAITING_CALL = "call"


class AlreadyPendingCallError(RuntimeError):
    pass


class NoAsyncCallError(RuntimeError):
    pass


class ClosedEnvError(RuntimeError):
    pass


def _probe_spaces(env_fn: Callable[[], Any]):
    """Create one env in-process to read agent names + obs/action spaces."""
    env = env_fn()
    try:
        agents = list(env.possible_agents)
        obs_spaces = {}
        action_spaces = {}
        for a in agents:
            space = env.observation_space(a)
            obs_spaces[a] = (tuple(space.shape), space.dtype)
            action_spaces[a] = env.action_space(a)
        return agents, obs_spaces, action_spaces
    finally:
        close = getattr(env, "close", None)
        if close:
            close()


class AsyncMultiAgentVecEnv:
    """N env subprocesses writing observations into a shared plane.

    ``context``: when unset and a JAX backend already lives in this
    process, workers start via ``"spawn"`` automatically — the default
    start method on Linux is fork, and forking after JAX has started
    backend threads can deadlock the child.  Env factories must be
    picklable under those contexts (module-level callables, not lambdas).
    """

    def __init__(
        self,
        env_fns: Sequence[Callable[[], Any]],
        obs_spaces: Optional[Dict[str, Tuple[Tuple[int, ...], Any]]] = None,
        autoreset: bool = True,
        context: Optional[str] = None,
    ) -> None:
        from scalerl_tpu.utils.platform import safe_mp_context

        self.num_envs = len(env_fns)
        ctx = mp.get_context(safe_mp_context(context))
        if obs_spaces is None:
            self.agents, obs_spaces, self.action_spaces = _probe_spaces(env_fns[0])
        else:
            self.agents = list(obs_spaces.keys())
            self.action_spaces = {}
        self.spec = ExperienceSpec(obs_spaces, self.num_envs)
        self.plane = SharedObservationPlane(self.spec, ctx=ctx)
        self.error_queue: mp.Queue = ctx.Queue()
        self._state = AsyncState.DEFAULT
        self._closed = False
        # replies still owed per worker after a _collect timeout; discarded
        # before the next fresh recv (replies are FIFO per worker)
        self._stale = [0] * self.num_envs
        self.parent_pipes = []
        self.processes = []
        for index, env_fn in enumerate(env_fns):
            parent, child = ctx.Pipe(duplex=True)
            proc = ctx.Process(
                target=_async_worker,
                args=(
                    index,
                    env_fn,
                    child,
                    parent,
                    self.plane,
                    self.agents,
                    autoreset,
                    self.error_queue,
                ),
                daemon=True,
            )
            proc.start()
            child.close()
            self.parent_pipes.append(parent)
            self.processes.append(proc)

    # -- async API -----------------------------------------------------
    def _assert_default(self, op: str) -> None:
        if self._closed:
            raise ClosedEnvError("vec env is closed")
        if self._state is not AsyncState.DEFAULT:
            raise AlreadyPendingCallError(
                f"cannot {op} while waiting for `{self._state.value}`"
            )

    def reset_async(self, seed: Optional[int] = None, options=None) -> None:
        self._assert_default("reset")
        for i, pipe in enumerate(self.parent_pipes):
            env_seed = None if seed is None else seed + i
            pipe.send(("reset", (env_seed, options)))
        self._state = AsyncState.WAITING_RESET

    def reset_wait(self, timeout: Optional[float] = 60.0):
        if self._state is not AsyncState.WAITING_RESET:
            raise NoAsyncCallError("no reset pending")
        results, successes = self._collect(timeout)
        self._state = AsyncState.DEFAULT
        self._raise_if_errors(successes)
        infos = [r for r in results]
        return self.plane.read_batch(), infos

    def reset(self, seed: Optional[int] = None, options=None, timeout=60.0):
        self.reset_async(seed=seed, options=options)
        return self.reset_wait(timeout)

    def step_async(self, actions: Dict[str, np.ndarray]) -> None:
        """``actions[agent]`` is a length-``num_envs`` batch; transposed to
        per-env dicts (reference ``pz_vec_env.py:53-68``)."""
        self._assert_default("step")
        for i, pipe in enumerate(self.parent_pipes):
            per_env = {agent: np.asarray(acts)[i] for agent, acts in actions.items()}
            pipe.send(("step", per_env))
        self._state = AsyncState.WAITING_STEP

    def step_wait(self, timeout: Optional[float] = 60.0):
        if self._state is not AsyncState.WAITING_STEP:
            raise NoAsyncCallError("no step pending")
        results, successes = self._collect(timeout)
        self._state = AsyncState.DEFAULT
        self._raise_if_errors(successes)
        rewards = {a: np.zeros(self.num_envs, np.float32) for a in self.agents}
        terms = {a: np.zeros(self.num_envs, np.bool_) for a in self.agents}
        truncs = {a: np.zeros(self.num_envs, np.bool_) for a in self.agents}
        infos: List[dict] = []
        for i, (rew, term, trunc, info) in enumerate(results):
            for a in self.agents:
                rewards[a][i] = rew.get(a, 0.0)
                terms[a][i] = term.get(a, True)
                truncs[a][i] = trunc.get(a, False)
            infos.append(info)
        return self.plane.read_batch(), rewards, terms, truncs, infos

    def step(self, actions: Dict[str, np.ndarray], timeout: Optional[float] = 60.0):
        self.step_async(actions)
        return self.step_wait(timeout)

    # -- attribute passthrough ----------------------------------------
    def call_async(self, name: str, *args, **kwargs) -> None:
        self._assert_default("call")
        for pipe in self.parent_pipes:
            pipe.send(("call", (name, args, kwargs)))
        self._state = AsyncState.WAITING_CALL

    def call_wait(self, timeout: Optional[float] = 60.0) -> list:
        if self._state is not AsyncState.WAITING_CALL:
            raise NoAsyncCallError("no call pending")
        results, successes = self._collect(timeout)
        self._state = AsyncState.DEFAULT
        self._raise_if_errors(successes)
        return results

    def call(self, name: str, *args, **kwargs) -> list:
        self.call_async(name, *args, **kwargs)
        return self.call_wait()

    def get_attr(self, name: str) -> list:
        return self.call(name)

    def set_attr(self, name: str, values: Any) -> None:
        if not isinstance(values, (list, tuple)):
            values = [values] * self.num_envs
        if len(values) != self.num_envs:
            raise ValueError(
                f"set_attr needs {self.num_envs} values, got {len(values)}"
            )
        self._assert_default("set_attr")
        for pipe, value in zip(self.parent_pipes, values):
            pipe.send(("setattr", (name, value)))
        self._state = AsyncState.WAITING_CALL
        self.call_wait()

    # -- plumbing ------------------------------------------------------
    def _collect(self, timeout: Optional[float]):
        """Gather one (result, success) pair per worker, with deadline.

        On timeout the state machine resets to DEFAULT before raising
        (gymnasium ``AsyncVectorEnv`` semantics) so the env is not wedged in
        a WAITING state forever.  Every worker that had not delivered its
        reply by the deadline is marked as owing one stale reply, which the
        next ``_collect`` discards before reading a fresh one — replies are
        FIFO per worker, so results can never desynchronize across steps.
        """
        deadline = None if timeout is None else time.monotonic() + timeout
        results, successes = [], []
        for i, pipe in enumerate(self.parent_pipes):
            try:
                # discard replies left over from a previous timed-out round
                while self._stale[i]:
                    remaining = (
                        None if deadline is None else deadline - time.monotonic()
                    )
                    if remaining is not None and (
                        remaining <= 0 or not pipe.poll(remaining)
                    ):
                        raise TimeoutError(
                            f"worker {i} did not respond in {timeout}s"
                        )
                    pipe.recv()
                    self._stale[i] -= 1
                remaining = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if remaining is not None and (
                    remaining <= 0 or not pipe.poll(remaining)
                ):
                    raise TimeoutError(f"worker {i} did not respond in {timeout}s")
            except TimeoutError:
                self._state = AsyncState.DEFAULT
                for j in range(i, self.num_envs):
                    self._stale[j] += 1
                raise
            result, ok = pipe.recv()
            results.append(result)
            successes.append(ok)
        return results, successes

    def _raise_if_errors(self, successes: Sequence[bool]) -> None:
        if all(successes):
            return
        num_errors = successes.count(False)
        last: Optional[BaseException] = None
        for _ in range(num_errors):
            index, exc_name, tb = self.error_queue.get()
            logger.error("env worker %d failed:\n%s", index, tb)
            # targeted teardown of the failed worker only
            self.parent_pipes[index].close()
            proc = self.processes[index]
            proc.join(timeout=1.0)
            if proc.is_alive():
                proc.terminate()
            last = RuntimeError(f"env worker {index} raised {exc_name}:\n{tb}")
        assert last is not None
        raise last

    def close(self, terminate: bool = False) -> None:
        if self._closed:
            return
        self._closed = True
        for pipe in self.parent_pipes:
            try:
                if not terminate:
                    pipe.send(("close", None))
            except (BrokenPipeError, OSError):
                pass
        for proc in self.processes:
            proc.join(timeout=2.0)
            if proc.is_alive():
                proc.terminate()
        for pipe in self.parent_pipes:
            try:
                pipe.close()
            except OSError:
                pass

    def __del__(self):
        try:
            self.close(terminate=True)
        except Exception:
            pass


def _fill_missing(obs: dict, agents: Sequence[str], spec: ExperienceSpec) -> dict:
    """Dead agents keep zero observations (reference 'fill dead agents',
    ``pz_async_vec_env.py:844-856``)."""
    out = dict(obs)
    for a in agents:
        if a not in out:
            slot = spec.slots[a]
            out[a] = np.zeros(slot.shape, slot.dtype)
    return out


def _async_worker(
    index: int,
    env_fn: Callable[[], Any],
    pipe,
    parent_pipe,
    plane: SharedObservationPlane,
    agents: Sequence[str],
    autoreset: bool,
    error_queue,
) -> None:
    parent_pipe.close()
    env = None
    try:
        env = env_fn()
        episode_return = {a: 0.0 for a in agents}
        episode_length = 0
        while True:
            command, payload = pipe.recv()
            if command == "reset":
                seed, options = payload
                obs, infos = env.reset(seed=seed, options=options)
                plane.write_env(index, _fill_missing(obs, agents, plane.spec))
                episode_return = {a: 0.0 for a in agents}
                episode_length = 0
                pipe.send((infos, True))
            elif command == "step":
                obs, rew, term, trunc, infos = env.step(payload)
                episode_length += 1
                for a, r in rew.items():
                    episode_return[a] = episode_return.get(a, 0.0) + float(r)
                all_done = all(
                    term.get(a, True) or trunc.get(a, False) for a in agents
                )
                if all_done and autoreset:
                    infos = dict(infos) if infos else {}
                    infos["final_observation"] = obs
                    infos["episode"] = {
                        "r": dict(episode_return),
                        "l": episode_length,
                    }
                    obs, reset_infos = env.reset()
                    episode_return = {a: 0.0 for a in agents}
                    episode_length = 0
                plane.write_env(index, _fill_missing(obs, agents, plane.spec))
                pipe.send(((rew, term, trunc, infos), True))
            elif command == "call":
                name, args, kwargs = payload
                if name in ("reset", "step", "close"):
                    raise ValueError(
                        f"use the dedicated API for `{name}`, not call()"
                    )
                attr = getattr(env, name)
                result = attr(*args, **kwargs) if callable(attr) else attr
                pipe.send((result, True))
            elif command == "setattr":
                name, value = payload
                setattr(env, name, value)
                pipe.send((None, True))
            elif command == "close":
                pipe.send((None, True))
                break
            else:
                raise RuntimeError(f"unknown command {command!r}")
    except (KeyboardInterrupt, EOFError):
        pass
    except Exception:
        error_queue.put((index, type(sys.exc_info()[1]).__name__,
                         traceback.format_exc()))
        try:
            pipe.send((None, False))
        except (BrokenPipeError, OSError):
            pass
    finally:
        if env is not None and hasattr(env, "close"):
            try:
                env.close()
            except Exception:
                pass
