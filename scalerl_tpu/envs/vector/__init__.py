from scalerl_tpu.envs.vector.async_vec import (  # noqa: F401
    AlreadyPendingCallError,
    AsyncMultiAgentVecEnv,
    AsyncState,
    ClosedEnvError,
    NoAsyncCallError,
)
from scalerl_tpu.envs.vector.spec import (  # noqa: F401
    ExperienceSpec,
    SharedObservationPlane,
)
