"""Shared-memory observation plane for vectorized (multi-agent) envs.

Parity target: the ``SharedMemory`` / ``Observations`` /
``PettingZooExperienceSpec`` trio of the reference's largest file
(``scalerl/envs/vector/pz_async_vec_env.py:544-788``): N env subprocesses
write observations into one process-shared buffer; the parent exposes
zero-copy per-agent views.

TPU-shaped differences: the reference flattened everything into one float32
``RawArray`` with boundary-indexed 1-D slots; here each agent gets its own
dtype-matched ``RawArray`` laid out **agent-major** — ``[num_envs, *shape]``
contiguous per agent — so the per-agent batch *is* the infeed staging buffer
(one ``jax.device_put`` per agent, no gather/stack).  uint8 pixel planes
stay uint8 (4× smaller than the reference's all-float32 plane).
"""

from __future__ import annotations

import multiprocessing as mp
from dataclasses import dataclass
from typing import Any, Dict, Mapping, Sequence, Tuple

import numpy as np


@dataclass(frozen=True)
class AgentSlot:
    shape: Tuple[int, ...]
    dtype: np.dtype

    @property
    def width(self) -> int:
        return int(np.prod(self.shape)) if self.shape else 1


class ExperienceSpec:
    """Per-agent observation layout for a fleet of ``num_envs`` envs."""

    def __init__(
        self, obs_spaces: Mapping[str, Tuple[Tuple[int, ...], Any]], num_envs: int
    ) -> None:
        self.num_envs = num_envs
        self.slots: Dict[str, AgentSlot] = {
            agent: AgentSlot(tuple(shape), np.dtype(dtype))
            for agent, (shape, dtype) in obs_spaces.items()
        }

    @property
    def agents(self) -> Sequence[str]:
        return list(self.slots.keys())

    def total_bytes(self) -> int:
        return sum(
            s.width * s.dtype.itemsize * self.num_envs for s in self.slots.values()
        )


class SharedObservationPlane:
    """Process-shared, zero-copy observation buffers (one per agent).

    Both the parent and the env subprocesses hold numpy views over the same
    ``mp.RawArray`` memory: workers write rows, the parent reads batches —
    no serialization on the obs path (the design that made the reference's
    async vec env its fastest component).
    """

    def __init__(self, spec: ExperienceSpec, ctx=None) -> None:
        ctx = ctx or mp.get_context()
        self.spec = spec
        self._raw: Dict[str, Any] = {}
        self._view_cache: Dict[str, np.ndarray] = {}
        for agent, slot in spec.slots.items():
            nbytes = slot.width * slot.dtype.itemsize * spec.num_envs
            self._raw[agent] = ctx.RawArray("b", nbytes)

    def __getstate__(self):
        # numpy views over shared memory don't pickle; each process
        # rebuilds its own cache lazily over the (picklable) RawArrays
        state = self.__dict__.copy()
        state["_view_cache"] = {}
        return state

    def view(self, agent: str) -> np.ndarray:
        """Writable ``[num_envs, *shape]`` view of the agent's plane
        (cached per process — this is the hot obs path)."""
        cached = self._view_cache.get(agent)
        if cached is not None:
            return cached
        slot = self.spec.slots[agent]
        arr = np.frombuffer(self._raw[agent], dtype=slot.dtype).reshape(
            (self.spec.num_envs,) + slot.shape
        )
        self._view_cache[agent] = arr
        return arr

    def views(self) -> Dict[str, np.ndarray]:
        return {agent: self.view(agent) for agent in self.spec.slots}

    def write_env(self, env_index: int, obs: Mapping[str, np.ndarray]) -> None:
        """Write one env's per-agent observations (worker side)."""
        for agent, value in obs.items():
            slot = self.spec.slots[agent]
            self.view(agent)[env_index] = np.asarray(value, dtype=slot.dtype).reshape(
                slot.shape
            )

    def zero_env(self, env_index: int, agent: str) -> None:
        self.view(agent)[env_index] = 0

    def read_batch(self, copy: bool = True) -> Dict[str, np.ndarray]:
        """Per-agent ``[num_envs, ...]`` batches; ``copy=False`` returns the
        live shared views (valid until the next ``step``)."""
        out = self.views()
        if copy:
            out = {k: v.copy() for k, v in out.items()}
        return out
