"""Host-side Gymnasium environment factories.

Parity targets: ``make_gym_env`` (``scalerl/envs/gym_env.py:6-33``) and
``make_vect_envs`` / ``make_multi_agent_vect_envs``
(``scalerl/envs/env_utils.py:85-120``).  The vector path uses gymnasium's
``AsyncVectorEnv`` with shared-memory observations — one subprocess per env
writing into a shared plane, which is exactly the staging buffer a TPU
infeed wants (SURVEY.md §2.2).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional, Sequence

import gymnasium as gym


def make_gym_env(
    env_id: str,
    seed: int = 42,
    idx: int = 0,
    capture_video: bool = False,
    video_dir: Optional[str] = None,
    atari: bool = False,
    normalize_obs: bool = False,
    wrappers: Optional[Sequence[Callable[[gym.Env], gym.Env]]] = None,
    **env_kwargs,
) -> Callable[[], gym.Env]:
    """Return a thunk building one env (thunks are what vector ctors want).

    ``env_id`` accepts either a gymnasium registry id or a direct
    ``"pkg.module:ClassName"`` path — the latter imports and constructs the
    class with ``env_kwargs``, no registration required (handy for custom
    envs in spawned actor processes, whose registries start fresh).

    ``wrappers``: callables applied outermost-last, after the built-in
    chain — the generic form of the reference's skill-wrapper factory
    (``env_utils.py:109-120``, ``make_skill_vect_envs``).  Under async
    vector envs they must be picklable (module-level classes/functions).
    """

    def thunk() -> gym.Env:
        # idempotent + cheap, and inside the thunk on purpose: vector-env
        # spawn children run this with a fresh gymnasium registry, so
        # parent-side registration would not survive the pickle boundary
        from scalerl_tpu.envs.synthetic_gym import register_synthetic_envs

        register_synthetic_envs()
        render_mode = "rgb_array" if (capture_video and idx == 0) else None
        mod_name, _, cls_name = env_id.partition(":")
        if cls_name.isidentifier():
            # "pkg.module:ClassName" — a direct class path.  Gymnasium's own
            # "module:EnvId" import syntax (e.g. "ale_py:ALE/Pong-v5") has a
            # registry id, never a bare identifier, on the right-hand side,
            # so it falls through to gym.make below.
            import importlib

            env_cls = getattr(importlib.import_module(mod_name), cls_name)
            env = env_cls(render_mode=render_mode, **env_kwargs)
        else:
            env = gym.make(env_id, render_mode=render_mode, **env_kwargs)
        if capture_video and idx == 0 and video_dir is not None:
            env = gym.wrappers.RecordVideo(env, video_dir)
        env = gym.wrappers.RecordEpisodeStatistics(env)
        if atari:
            from scalerl_tpu.envs.atari import wrap_deepmind

            env = wrap_deepmind(env)
        if normalize_obs:
            from scalerl_tpu.envs.atari import NormalizedEnv

            env = NormalizedEnv(env)
        for wrap in wrappers or ():
            env = wrap(env)
        env.action_space.seed(seed + idx)
        return env

    return thunk


def make_vect_envs(
    env_id: str,
    num_envs: int = 1,
    seed: int = 42,
    async_envs: bool = True,
    capture_video: bool = False,
    video_dir: Optional[str] = None,
    atari: bool = False,
    **env_kwargs,
) -> gym.vector.VectorEnv:
    """Vectorized env pool; async uses subprocess workers + shared memory."""
    thunks = [
        make_gym_env(
            env_id,
            seed=seed,
            idx=i,
            capture_video=capture_video,
            video_dir=video_dir,
            atari=atari,
            **env_kwargs,
        )
        for i in range(num_envs)
    ]
    # SAME_STEP autoreset: on done, step() returns the reset obs and stashes
    # the true terminal obs in infos["final_obs"] — the classic-gym semantics
    # the reference's replay path assumes (store next_obs = final_obs on done).
    mode = gym.vector.AutoresetMode.SAME_STEP
    if async_envs and num_envs > 1:
        # spawn, not fork: the parent holds JAX (multithreaded) and, in the
        # actor-learner path, live actor threads — forked children inherit
        # locked mutexes and deadlock (CPython popen_fork warning).
        return gym.vector.AsyncVectorEnv(
            thunks, shared_memory=True, autoreset_mode=mode, context="spawn"
        )
    return gym.vector.SyncVectorEnv(thunks, autoreset_mode=mode)


def make_multi_agent_vect_envs(
    env_fn: Callable,
    num_envs: int = 1,
    **env_kwargs,
):
    """PettingZoo parallel-env pool (``env_utils.py:97-120`` parity)."""
    from scalerl_tpu.envs.vector import AsyncMultiAgentVecEnv

    env_fns = [partial(env_fn, **env_kwargs) for _ in range(num_envs)]
    return AsyncMultiAgentVecEnv(env_fns)
