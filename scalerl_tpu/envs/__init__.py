from scalerl_tpu.envs.atari import (  # noqa: F401
    NormalizedEnv,
    create_atari_env,
    make_atari_env,
    wrap_deepmind,
)
from scalerl_tpu.envs.gym_env import (  # noqa: F401
    make_gym_env,
    make_multi_agent_vect_envs,
    make_vect_envs,
)
from scalerl_tpu.envs.jax_envs import (  # noqa: F401
    JaxCartPole,
    JaxBreakout,
    JaxCatch,
    JaxRecall,
    JaxVecEnv,
    SyntheticPixelEnv,
    make_jax_vec_env,
)
from scalerl_tpu.envs.multi_agent import (  # noqa: F401
    AutoResetParallelWrapper,
    PursuitToyEnv,
    SingleAgentAdapter,
    make_multi_agent_vec_env,
    make_shared_vec_envs,
)
from scalerl_tpu.envs.vector import (  # noqa: F401
    AsyncMultiAgentVecEnv,
    SharedObservationPlane,
)
