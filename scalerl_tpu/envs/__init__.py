from scalerl_tpu.envs.gym_env import make_gym_env, make_vect_envs  # noqa: F401
from scalerl_tpu.envs.jax_envs import (  # noqa: F401
    JaxCartPole,
    JaxVecEnv,
    SyntheticPixelEnv,
    make_jax_vec_env,
)
