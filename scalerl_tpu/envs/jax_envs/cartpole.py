"""Pure-JAX CartPole with the classic Gym dynamics and auto-reset.

Matches gymnasium's CartPole-v1 physics (gravity 9.8, masscart 1.0, masspole
0.1, pole half-length 0.5, force 10, tau 0.02, Euler integration; terminate
at |x| > 2.4 or |theta| > 12 deg; reward 1 per step; truncate at max_steps),
so policies trained here transfer to the host env for evaluation parity with
``examples/test_dqn.py``.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from scalerl_tpu.envs.jax_envs.base import JaxEnv


class CartPoleState(NamedTuple):
    x: jnp.ndarray
    x_dot: jnp.ndarray
    theta: jnp.ndarray
    theta_dot: jnp.ndarray
    t: jnp.ndarray  # step counter


class JaxCartPole(JaxEnv):
    GRAVITY = 9.8
    MASSCART = 1.0
    MASSPOLE = 0.1
    TOTAL_MASS = MASSCART + MASSPOLE
    LENGTH = 0.5
    POLEMASS_LENGTH = MASSPOLE * LENGTH
    FORCE_MAG = 10.0
    TAU = 0.02
    THETA_LIMIT = 12 * 2 * jnp.pi / 360
    X_LIMIT = 2.4

    def __init__(self, max_steps: int = 500) -> None:
        self.max_steps = max_steps

    @property
    def observation_shape(self) -> Tuple[int, ...]:
        return (4,)

    @property
    def num_actions(self) -> int:
        return 2

    def _obs(self, s: CartPoleState) -> jnp.ndarray:
        return jnp.stack([s.x, s.x_dot, s.theta, s.theta_dot]).astype(jnp.float32)

    def reset(self, key: jax.Array):
        vals = jax.random.uniform(key, (4,), minval=-0.05, maxval=0.05)
        state = CartPoleState(vals[0], vals[1], vals[2], vals[3], jnp.zeros((), jnp.int32))
        return state, self._obs(state)

    def step(self, state: CartPoleState, action: jnp.ndarray, key: jax.Array):
        force = jnp.where(action == 1, self.FORCE_MAG, -self.FORCE_MAG)
        costheta = jnp.cos(state.theta)
        sintheta = jnp.sin(state.theta)
        temp = (
            force + self.POLEMASS_LENGTH * state.theta_dot**2 * sintheta
        ) / self.TOTAL_MASS
        thetaacc = (self.GRAVITY * sintheta - costheta * temp) / (
            self.LENGTH * (4.0 / 3.0 - self.MASSPOLE * costheta**2 / self.TOTAL_MASS)
        )
        xacc = temp - self.POLEMASS_LENGTH * thetaacc * costheta / self.TOTAL_MASS

        x = state.x + self.TAU * state.x_dot
        x_dot = state.x_dot + self.TAU * xacc
        theta = state.theta + self.TAU * state.theta_dot
        theta_dot = state.theta_dot + self.TAU * thetaacc
        t = state.t + 1

        terminated = (
            (jnp.abs(x) > self.X_LIMIT) | (jnp.abs(theta) > self.THETA_LIMIT)
        )
        truncated = t >= self.max_steps
        done = terminated | truncated

        stepped = CartPoleState(x, x_dot, theta, theta_dot, t)
        reset_state, reset_obs = self.reset(key)
        # auto-reset: where done, return the freshly-reset state/obs
        new_state = jax.tree_util.tree_map(
            lambda a, b: jnp.where(done, a, b), reset_state, stepped
        )
        obs = jnp.where(done, reset_obs, self._obs(stepped))
        return new_state, obs, jnp.ones((), jnp.float32), done
