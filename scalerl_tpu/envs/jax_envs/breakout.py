"""Device-native Breakout: the flagship pixel-control task.

MinAtar-style brick-breaking (Young & Tian 2019's reduction of ALE
Breakout), re-designed pure-JAX on the ``envs/jax_envs/base.py`` protocol:
a paddle slides along the bottom row, a ball bounces off walls/ceiling/
paddle with diagonal unit velocity, and three rows of bricks pay +1 each
when struck; losing the ball ends the episode, clearing the wall respawns
it (so score is unbounded and tracks skill).

Why it exists: BASELINE.md's primary metric is wall-clock-to-score on
ALE Pong, but ALE ROMs are absent from this image (VERDICT r3 missing #3).
This is the strongest available stand-in: a *striking* game — multi-object
pixel state, ball interception under control, long-horizon credit for each
brick — not a diagnostic env.  The real ``ALE/Pong-v5`` recipe stays
gated behind a ROM-presence check (``examples/curves/``) so it runs the
moment ROMs exist.

Mechanics (one step):
1. paddle moves left/stay/right, clipped to the field;
2. the ball advances one cell diagonally; side walls and the ceiling
   reflect it in-cell (velocity components are always ±1);
3. entering a brick cell consumes the brick, pays +1, and reflects the
   vertical velocity (the ball re-occupies its previous row);
4. reaching the paddle row: if the paddle is under the ball (3-wide),
   the ball reflects up; otherwise the episode ends (auto-reset);
5. an emptied wall immediately respawns full (play continues);
6. episodes truncate at ``max_steps`` (done, like every env here — the
   fused loops have no separate truncation channel).

Observations are ``[size, size, stack]`` uint8 frames: bricks at 128,
ball and paddle at 255, black field — the standard Atari conv torso
applies unchanged.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from scalerl_tpu.envs.jax_envs.base import JaxEnv


class BreakoutState(NamedTuple):
    ball_x: jnp.ndarray  # int32 col
    ball_y: jnp.ndarray  # int32 row, 0 = top
    dx: jnp.ndarray  # int32 +-1
    dy: jnp.ndarray  # int32 +-1
    paddle_x: jnp.ndarray  # int32 col (center of 3-wide paddle)
    bricks: jnp.ndarray  # [brick_rows, size] bool
    t: jnp.ndarray  # int32 step counter


class JaxBreakout(JaxEnv):
    """``size`` x ``size`` Breakout with ``brick_rows`` rows of bricks."""

    def __init__(
        self,
        size: int = 10,
        stack: int = 1,
        brick_rows: int = 3,
        brick_top: int = 2,
        max_steps: int = 500,
        render_size: int | None = None,
    ) -> None:
        """``render_size``: render observations upscaled (nearest-neighbor)
        to ``render_size`` x ``render_size`` — identical game DYNAMICS at
        ALE's 84x84 observation scale, so the wall-clock-to-score protocol
        prices the conv torso at the north-star shape (VERDICT r4 #6).
        ALE Breakout is itself a small machine state rendered big; this is
        the same separation."""
        if brick_top + brick_rows >= size - 2:
            raise ValueError("brick wall must leave room above the paddle row")
        if render_size is not None and render_size < size:
            raise ValueError("render_size must be >= the logical grid size")
        self.size = size
        self.stack = stack
        self.brick_rows = brick_rows
        self.brick_top = brick_top
        self.max_steps = max_steps
        self.render_size = render_size

    @property
    def observation_shape(self) -> Tuple[int, ...]:
        side = self.render_size or self.size
        return (side, side, self.stack)

    @property
    def observation_dtype(self):
        return jnp.uint8

    @property
    def num_actions(self) -> int:
        return 3  # left / stay / right

    # ------------------------------------------------------------------
    def _render(self, state: BreakoutState) -> jnp.ndarray:
        rows = jnp.arange(self.size)[:, None]
        cols = jnp.arange(self.size)[None, :]
        frame = jnp.zeros((self.size, self.size), jnp.uint8)
        # brick band at half intensity
        brick_plane = jnp.zeros((self.size, self.size), bool)
        brick_plane = jax.lax.dynamic_update_slice(
            brick_plane, state.bricks, (self.brick_top, 0)
        )
        frame = jnp.where(brick_plane, jnp.uint8(128), frame)
        ball = (rows == state.ball_y) & (cols == state.ball_x)
        paddle = (rows == self.size - 1) & (jnp.abs(cols - state.paddle_x) <= 1)
        frame = jnp.where(ball | paddle, jnp.uint8(255), frame)
        if self.render_size is not None:
            # nearest-neighbor upscale: gather rows/cols by the index map
            # (pure gathers — XLA fuses this into the consumer)
            idx = (jnp.arange(self.render_size) * self.size) // self.render_size
            frame = frame[idx][:, idx]
        return jnp.broadcast_to(frame[:, :, None], self.observation_shape)

    def _spawn(self, key: jax.Array) -> BreakoutState:
        k_x, k_dx = jax.random.split(key)
        return BreakoutState(
            ball_x=jax.random.randint(k_x, (), 0, self.size),
            ball_y=jnp.asarray(self.brick_top + self.brick_rows, jnp.int32),
            dx=jnp.where(jax.random.bernoulli(k_dx), 1, -1).astype(jnp.int32),
            dy=jnp.ones((), jnp.int32),  # heading down toward the paddle
            paddle_x=jnp.asarray(self.size // 2, jnp.int32),
            bricks=jnp.ones((self.brick_rows, self.size), bool),
            t=jnp.zeros((), jnp.int32),
        )

    def reset(self, key: jax.Array):
        state = self._spawn(key)
        return state, self._render(state)

    # ------------------------------------------------------------------
    def step(self, state: BreakoutState, action: jnp.ndarray, key: jax.Array):
        W = self.size
        move = action.astype(jnp.int32) - 1  # 0/1/2 -> -1/0/+1
        paddle = jnp.clip(state.paddle_x + move, 1, W - 2)  # 3-wide stays on field

        # ball advance + side-wall / ceiling reflection (unit velocity makes
        # in-cell reflection exact: the clipped cell is the reflected cell)
        nx = state.ball_x + state.dx
        dx = jnp.where((nx < 0) | (nx >= W), -state.dx, state.dx)
        nx = jnp.clip(nx, 0, W - 1)
        ny = state.ball_y + state.dy
        hit_ceiling = ny < 0
        dy = jnp.where(hit_ceiling, 1, state.dy)
        ny = jnp.where(hit_ceiling, 1, ny)

        # brick collision at the entered cell
        brow = ny - self.brick_top
        in_band = (brow >= 0) & (brow < self.brick_rows)
        brow_c = jnp.clip(brow, 0, self.brick_rows - 1)
        hit_brick = in_band & state.bricks[brow_c, nx]
        bricks = state.bricks.at[brow_c, nx].set(
            jnp.where(hit_brick, False, state.bricks[brow_c, nx])
        )
        reward = hit_brick.astype(jnp.float32)
        # reflect: ball bounces back to its previous row
        ny = jnp.where(hit_brick, state.ball_y, ny)
        dy = jnp.where(hit_brick, -dy, dy)

        # paddle row
        at_bottom = ny >= W - 1
        caught = at_bottom & (jnp.abs(nx - paddle) <= 1)
        ny = jnp.where(caught, W - 2, ny)
        dy = jnp.where(caught, -1, dy)
        missed = at_bottom & ~caught

        # cleared wall respawns full (score keeps climbing with skill)
        cleared = ~jnp.any(bricks)
        bricks = jnp.where(cleared, jnp.ones_like(bricks), bricks)

        t = state.t + 1
        done = missed | (t >= self.max_steps)

        next_state = BreakoutState(
            ball_x=nx, ball_y=ny, dx=dx, dy=dy,
            paddle_x=paddle, bricks=bricks, t=t,
        )
        respawn = self._spawn(key)
        new_state = jax.tree_util.tree_map(
            lambda r, n: jnp.where(done, r, n), respawn, next_state
        )
        return new_state, self._render(new_state), reward, done
