"""Synthetic pixel environment with Atari-shaped observations.

Stands in for ALE (unavailable in this image) to drive the full IMPALA
pipeline — conv net, LSTM, V-trace — at real frame shapes for throughput
benchmarking and pipeline tests.  Dynamics: a hidden integer state walks a
ring of ``num_states`` cells; each cell renders a deterministic [84, 84, 4]
uint8 pattern; one distinguished action advances the walk (reward 1), the
rest regress it (reward 0); episodes end after ``episode_length`` steps.
A policy can therefore *learn* here (the optimal action is obs-dependent),
which makes it useful as a learning smoke test, not just a data pump.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from scalerl_tpu.envs.jax_envs.base import JaxEnv


class SyntheticState(NamedTuple):
    cell: jnp.ndarray  # int32 ring position
    t: jnp.ndarray  # int32 step counter


class SyntheticPixelEnv(JaxEnv):
    def __init__(
        self,
        size: int = 84,
        stack: int = 4,
        num_actions: int = 6,
        num_states: int = 16,
        episode_length: int = 128,
    ) -> None:
        self.size = size
        self.stack = stack
        self._num_actions = num_actions
        self.num_states = num_states
        self.episode_length = episode_length

    @property
    def observation_shape(self) -> Tuple[int, ...]:
        return (self.size, self.size, self.stack)

    @property
    def observation_dtype(self):
        return jnp.uint8

    @property
    def num_actions(self) -> int:
        return self._num_actions

    def _render(self, cell: jnp.ndarray) -> jnp.ndarray:
        """Deterministic per-cell pattern: banded gradient keyed by the cell."""
        rows = jnp.arange(self.size)[:, None, None]
        cols = jnp.arange(self.size)[None, :, None]
        chans = jnp.arange(self.stack)[None, None, :]
        pattern = (rows * (cell + 1) + cols * 3 + chans * 17) % 256
        return pattern.astype(jnp.uint8)

    def _correct_action(self, cell: jnp.ndarray) -> jnp.ndarray:
        return (cell * 2 + 1) % self._num_actions

    def reset(self, key: jax.Array):
        cell = jax.random.randint(key, (), 0, self.num_states)
        state = SyntheticState(cell, jnp.zeros((), jnp.int32))
        return state, self._render(cell)

    def step(self, state: SyntheticState, action: jnp.ndarray, key: jax.Array):
        correct = action == self._correct_action(state.cell)
        reward = correct.astype(jnp.float32)
        cell = jnp.where(correct, (state.cell + 1) % self.num_states, (state.cell - 1) % self.num_states)
        t = state.t + 1
        done = t >= self.episode_length

        reset_cell = jax.random.randint(key, (), 0, self.num_states)
        new_cell = jnp.where(done, reset_cell, cell)
        new_state = SyntheticState(new_cell, jnp.where(done, 0, t))
        return new_state, self._render(new_cell), reward, done
