"""Synthetic pixel environment with Atari-shaped observations.

Stands in for ALE (unavailable in this image) to drive the full IMPALA
pipeline — conv net, LSTM, V-trace — at real frame shapes for throughput
benchmarking and pipeline tests.  Dynamics: a hidden integer state walks a
ring of ``num_states`` cells; each cell renders a deterministic [84, 84, 4]
uint8 pattern; one distinguished action advances the walk (reward 1), the
rest teleport it to a uniformly random cell (reward 0); episodes end after
``episode_length`` steps.  A policy can therefore *learn* here (the optimal
action is obs-dependent), which makes it useful as a learning smoke test,
not just a data pump.

Design notes for learnability: the correct-action map ``cell % num_actions``
hits *every* action whenever ``num_states >= num_actions`` (an earlier
``(2*cell + 1)`` map only ever used odd actions, and ``(3*cell + 1)`` missed
actions whenever ``gcd(3, num_actions) > 1`` — e.g. the default 6 actions),
and a wrong action *teleports* rather than stepping back
— a step-back rule lets any constant-action policy oscillate between a
correct cell and its neighbour, collecting reward every other step, i.e. a
50%-of-optimal attractor no gradient signal needs to escape.  With teleport,
a constant policy earns ~1/num_actions of optimal and every extra
distinguished state strictly increases return, so "return_mean ->
episode_length" is real evidence the conv torso learned the obs->action map.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from scalerl_tpu.envs.jax_envs.base import JaxEnv


class SyntheticState(NamedTuple):
    cell: jnp.ndarray  # int32 ring position
    t: jnp.ndarray  # int32 step counter
    last_action: jnp.ndarray  # int32 previous *executed* action (sticky)


class SyntheticPixelEnv(JaxEnv):
    def __init__(
        self,
        size: int = 84,
        stack: int = 4,
        num_actions: int = 6,
        num_states: int = 16,
        episode_length: int = 128,
        sticky_prob: float = 0.0,
    ) -> None:
        """``sticky_prob``: ALE-style sticky actions (Machado et al. 2018)
        — with this probability the env *repeats the previously executed
        action* instead of the agent's choice.  Makes the dynamics
        stochastic at the north-star 84x84x4 learning shape (VERDICT r2
        #7) the way real Atari evaluation is, so a policy cannot exploit
        determinism; 0.0 (default) executes the agent's action verbatim
        (the original deterministic-dynamics benchmark env)."""
        if num_states > size:
            # each cell needs a distinct stripe column block; more states
            # than columns would alias cells >= size into identical frames
            raise ValueError(
                f"num_states ({num_states}) must be <= size ({size}) so every "
                "cell renders a distinct observation"
            )
        self.size = size
        self.stack = stack
        self._num_actions = num_actions
        self.num_states = num_states
        self.episode_length = episode_length
        self.sticky_prob = float(sticky_prob)

    @property
    def observation_shape(self) -> Tuple[int, ...]:
        return (self.size, self.size, self.stack)

    @property
    def observation_dtype(self):
        return jnp.uint8

    @property
    def num_actions(self) -> int:
        return self._num_actions

    def _render(self, cell: jnp.ndarray) -> jnp.ndarray:
        """Deterministic per-cell pattern: a bright vertical stripe at a
        cell-indexed column over a fixed dim texture.

        The stripe makes the state *spatially* encoded — the conv torso must
        localize it, which is a real (but quickly learnable) vision task.  An
        earlier render varied only the row-gradient slope per cell; after the
        stride-4 conv that discrimination was so aliased that IMPALA sat at
        the random-policy return for hundreds of thousands of frames, which
        made the env useless as a learning smoke test.
        """
        rows = jnp.arange(self.size)[:, None, None]
        cols = jnp.arange(self.size)[None, :, None]
        chans = jnp.arange(self.stack)[None, None, :]
        texture = (rows * 2 + cols * 5 + chans * 17) % 128
        stripe_w = max(self.size // self.num_states, 1)
        in_stripe = (cols // stripe_w) == cell
        pattern = jnp.where(in_stripe, 255, texture)
        return pattern.astype(jnp.uint8)

    def _correct_action(self, cell: jnp.ndarray) -> jnp.ndarray:
        return cell % self._num_actions

    def reset(self, key: jax.Array):
        cell = jax.random.randint(key, (), 0, self.num_states)
        state = SyntheticState(
            cell, jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32)
        )
        return state, self._render(cell)

    def step(self, state: SyntheticState, action: jnp.ndarray, key: jax.Array):
        k_teleport, k_reset, k_sticky = jax.random.split(key, 3)
        if self.sticky_prob > 0.0:
            sticky = jax.random.bernoulli(k_sticky, self.sticky_prob)
            executed = jnp.where(sticky, state.last_action, action).astype(
                action.dtype
            )
        else:
            executed = action
        correct = executed == self._correct_action(state.cell)
        reward = correct.astype(jnp.float32)
        teleport = jax.random.randint(k_teleport, (), 0, self.num_states)
        cell = jnp.where(correct, (state.cell + 1) % self.num_states, teleport)
        t = state.t + 1
        done = t >= self.episode_length

        reset_cell = jax.random.randint(k_reset, (), 0, self.num_states)
        new_cell = jnp.where(done, reset_cell, cell)
        new_state = SyntheticState(
            new_cell,
            jnp.where(done, 0, t),
            # sticky carry resets with the episode (fresh episodes have no
            # previous action to repeat)
            jnp.where(done, 0, executed.astype(jnp.int32)),
        )
        return new_state, self._render(new_cell), reward, done
