from scalerl_tpu.envs.jax_envs.base import JaxEnv, JaxVecEnv, make_jax_vec_env  # noqa: F401
from scalerl_tpu.envs.jax_envs.cartpole import JaxCartPole  # noqa: F401
from scalerl_tpu.envs.jax_envs.breakout import JaxBreakout  # noqa: F401
from scalerl_tpu.envs.jax_envs.catch import JaxCatch  # noqa: F401
from scalerl_tpu.envs.jax_envs.recall import JaxRecall  # noqa: F401
from scalerl_tpu.envs.jax_envs.synthetic import SyntheticPixelEnv  # noqa: F401
