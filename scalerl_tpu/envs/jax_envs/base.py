"""Device-native environments: pure-functional, vmappable, jittable.

The reference has no analog — its envs are CPU subprocesses feeding a GPU
learner.  On TPU, simple env dynamics can run *on device*, fusing the whole
act->step->learn loop into one XLA program with zero host round-trips; this
is how the synthetic throughput benches drive the learner at full speed and
how CartPole-class tasks train end-to-end on-chip.

Protocol (gymnax-flavored, deliberately minimal):

- ``env.reset(key) -> (state, obs)``
- ``env.step(state, action, key) -> (state, obs, reward, done)`` with
  **auto-reset**: when an episode ends, the returned state/obs are already
  reset (done flags the boundary), so fixed-shape rollouts never branch.

``JaxVecEnv`` lifts a single env over a batch axis with ``vmap`` and manages
keys; everything stays pure so it nests under jit/pjit/scan.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp

State = Any


class JaxEnv:
    """Interface for device-native envs (subclass and implement the pure fns)."""

    @property
    def observation_shape(self) -> Tuple[int, ...]:
        raise NotImplementedError

    @property
    def observation_dtype(self):
        return jnp.float32

    @property
    def num_actions(self) -> int:
        raise NotImplementedError

    def reset(self, key: jax.Array) -> Tuple[State, jnp.ndarray]:
        raise NotImplementedError

    def step(
        self, state: State, action: jnp.ndarray, key: jax.Array
    ) -> Tuple[State, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        raise NotImplementedError


class JaxVecEnv:
    """vmap-lifted batch of one ``JaxEnv``; still pure (state is explicit)."""

    def __init__(self, env: JaxEnv, num_envs: int) -> None:
        self.env = env
        self.num_envs = num_envs
        self._reset = jax.vmap(env.reset)
        self._step = jax.vmap(env.step)

    @property
    def observation_shape(self) -> Tuple[int, ...]:
        return self.env.observation_shape

    @property
    def num_actions(self) -> int:
        return self.env.num_actions

    def reset(self, key: jax.Array):
        keys = jax.random.split(key, self.num_envs)
        return self._reset(keys)

    def step(self, state, action: jnp.ndarray, key: jax.Array):
        # split by the *actual* batch of this call, not self.num_envs: under
        # shard_map (multi-device fused loop) each shard steps its local
        # slice of the lanes
        keys = jax.random.split(key, action.shape[0])
        return self._step(state, action, keys)


def make_jax_vec_env(env_id: str, num_envs: int, **kwargs) -> JaxVecEnv:
    from scalerl_tpu.envs.jax_envs.breakout import JaxBreakout
    from scalerl_tpu.envs.jax_envs.cartpole import JaxCartPole
    from scalerl_tpu.envs.jax_envs.catch import JaxCatch
    from scalerl_tpu.envs.jax_envs.recall import JaxRecall
    from scalerl_tpu.envs.jax_envs.synthetic import SyntheticPixelEnv

    registry = {
        "CartPole-v1": lambda: JaxCartPole(max_steps=500),
        "CartPole-v0": lambda: JaxCartPole(max_steps=200),
        "SyntheticPixel-v0": lambda: SyntheticPixelEnv(**kwargs),
        "Catch-v0": lambda: JaxCatch(**kwargs),
        "Recall-v0": lambda: JaxRecall(**kwargs),
        "Breakout-v0": lambda: JaxBreakout(**kwargs),
    }
    if env_id not in registry:
        raise KeyError(
            f"unknown jax env {env_id!r}; available: {sorted(registry)} "
            "(use env_backend='gym' for host envs)"
        )
    return JaxVecEnv(registry[env_id](), num_envs)
