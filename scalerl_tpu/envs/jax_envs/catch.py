"""Device-native Catch: the classic falling-ball pixel-control task.

A ball falls one row per step from a random column; the agent slides a
paddle along the bottom row (left / stay / right) and is rewarded +1 for
catching the ball, -1 for missing, at the episode's final step (the
DeepMind bsuite Catch task, re-implemented pure-JAX on the
``envs/jax_envs/base.py`` protocol).

Why it exists (beyond the reference, which has no device-native envs):
``SyntheticPixelEnv`` validates obs->action *pattern lookup*; Catch demands
spatio-temporal *control* — the policy must read two object positions from
pixels and steer one toward the other over many steps before the single
delayed reward lands.  That is the smallest task shaped like Pong
(BASELINE.md's north star needs ALE ROMs this image lacks), so it is the
flagship learning-evidence env for the fused device loop.

Observations are ``[size, size, stack]`` uint8 frames (bright ball + paddle
over a black field, duplicated across the channel stack so the standard
Atari conv torso applies unchanged).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from scalerl_tpu.envs.jax_envs.base import JaxEnv


class CatchState(NamedTuple):
    ball_row: jnp.ndarray  # int32, 0 = top
    ball_col: jnp.ndarray  # int32
    paddle_col: jnp.ndarray  # int32
    t: jnp.ndarray  # int32 step counter


class JaxCatch(JaxEnv):
    """rows x cols Catch; episode length == rows (ball reaches the bottom)."""

    def __init__(self, size: int = 24, stack: int = 1, paddle_width: int = 3) -> None:
        if paddle_width % 2 != 1:
            raise ValueError("paddle_width must be odd (centered on paddle_col)")
        self.size = size
        self.stack = stack
        self.paddle_width = paddle_width

    @property
    def observation_shape(self) -> Tuple[int, ...]:
        return (self.size, self.size, self.stack)

    @property
    def observation_dtype(self):
        return jnp.uint8

    @property
    def num_actions(self) -> int:
        return 3  # left / stay / right

    def _render(self, state: CatchState) -> jnp.ndarray:
        rows = jnp.arange(self.size)[:, None]
        cols = jnp.arange(self.size)[None, :]
        ball = (rows == state.ball_row) & (cols == state.ball_col)
        half = self.paddle_width // 2
        paddle = (rows == self.size - 1) & (
            jnp.abs(cols - state.paddle_col) <= half
        )
        frame = jnp.where(ball | paddle, 255, 0).astype(jnp.uint8)
        return jnp.broadcast_to(frame[:, :, None], (self.size, self.size, self.stack))

    def _spawn(self, key: jax.Array) -> CatchState:
        ball_col = jax.random.randint(key, (), 0, self.size)
        return CatchState(
            ball_row=jnp.zeros((), jnp.int32),
            ball_col=ball_col,
            paddle_col=jnp.asarray(self.size // 2, jnp.int32),
            t=jnp.zeros((), jnp.int32),
        )

    def reset(self, key: jax.Array):
        state = self._spawn(key)
        return state, self._render(state)

    def step(self, state: CatchState, action: jnp.ndarray, key: jax.Array):
        move = action.astype(jnp.int32) - 1  # 0/1/2 -> -1/0/+1
        paddle = jnp.clip(state.paddle_col + move, 0, self.size - 1)
        ball_row = state.ball_row + 1
        t = state.t + 1
        done = ball_row >= self.size - 1
        half = self.paddle_width // 2
        caught = jnp.abs(state.ball_col - paddle) <= half
        reward = jnp.where(
            done, jnp.where(caught, 1.0, -1.0), 0.0
        ).astype(jnp.float32)

        next_state = CatchState(ball_row, state.ball_col, paddle, t)
        respawn = self._spawn(key)
        new_state = jax.tree_util.tree_map(
            lambda r, n: jnp.where(done, r, n), respawn, next_state
        )
        return new_state, self._render(new_state), reward, done
