"""Device-native delayed-recall task: the recurrent-learning litmus test.

A cue (one of ``num_actions`` quadrant patterns) flashes in the FIRST frame
only; ``delay`` blank frames follow; at the final step the agent must output
the action matching the cue (+1 correct, -1 wrong).  Expected return of any
memoryless policy is ``2/num_actions - 1`` (−0.5 at 4 actions), so crossing
a high threshold *requires* the policy to carry the cue through the blank
frames — this is the to-convergence evidence for the done-masked LSTM carry
(``models/atari.py`` ``_LSTMCore``) inside the fused device loop, which the
Catch/Synthetic curves (feed-forward torsos) cannot provide.

Same protocol as the other ``envs/jax_envs`` tasks (reset/step pure fns,
auto-reset on done); observations are ``[size, size, 1]`` uint8 frames so
the standard Atari conv torso applies unchanged.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from scalerl_tpu.envs.jax_envs.base import JaxEnv


class RecallState(NamedTuple):
    cue: jnp.ndarray  # int32 in [0, num_actions)
    t: jnp.ndarray  # int32 step counter


class JaxRecall(JaxEnv):
    """Flash a quadrant cue, wait ``delay`` blank steps, demand recall."""

    def __init__(self, size: int = 16, delay: int = 6, num_cues: int = 4) -> None:
        if num_cues not in (2, 4):
            raise ValueError("num_cues must be 2 or 4 (quadrant patterns)")
        self.size = size
        self.delay = delay
        self.num_cues = num_cues

    @property
    def observation_shape(self) -> Tuple[int, ...]:
        return (self.size, self.size, 1)

    @property
    def observation_dtype(self):
        return jnp.uint8

    @property
    def num_actions(self) -> int:
        return self.num_cues

    def _render(self, state: RecallState) -> jnp.ndarray:
        half = self.size // 2
        rows = jnp.arange(self.size)[:, None]
        cols = jnp.arange(self.size)[None, :]
        # quadrant q: (row half, col half) = (q // 2, q % 2); with 2 cues the
        # pattern uses left/right halves only
        if self.num_cues == 4:
            in_q = ((rows >= half) == (state.cue // 2)) & (
                (cols >= half) == (state.cue % 2)
            )
        else:
            # broadcast against rows explicitly: the half-plane formula
            # alone yields a [1, size] mask and a wrong-shaped frame
            in_q = jnp.broadcast_to(
                (cols >= half) == (state.cue % 2), (self.size, self.size)
            )
        frame = jnp.where((state.t == 0) & in_q, 255, 0).astype(jnp.uint8)
        return frame[:, :, None]

    def _spawn(self, key: jax.Array) -> RecallState:
        return RecallState(
            cue=jax.random.randint(key, (), 0, self.num_cues),
            t=jnp.zeros((), jnp.int32),
        )

    def reset(self, key: jax.Array):
        state = self._spawn(key)
        return state, self._render(state)

    def step(self, state: RecallState, action: jnp.ndarray, key: jax.Array):
        t = state.t + 1
        done = t > self.delay  # episode = 1 cue frame + delay blanks
        reward = jnp.where(
            done,
            jnp.where(action.astype(jnp.int32) == state.cue, 1.0, -1.0),
            0.0,
        ).astype(jnp.float32)
        next_state = RecallState(state.cue, t)
        respawn = self._spawn(key)
        new_state = jax.tree_util.tree_map(
            lambda r, n: jnp.where(done, r, n), respawn, next_state
        )
        return new_state, self._render(new_state), reward, done
