"""Gym-API synthetic benchmark envs (numpy twins of ``envs/jax_envs``).

``PixelRingEnv`` pre-renders its ``[84, 84, 4]`` uint8 frames with a pure
numpy twin of the ``SyntheticPixelEnv`` renderer (bit-equality asserted in
``tests/test_envs.py``), so ``step`` costs an index lookup and — crucially
— constructing it never imports jax: spawned actor processes
(``trainer/process_actor_learner.py``) build it by id string and must stay
free of the multi-second jax import + backend init.  Registered with
gymnasium as ``PixelRing-v0`` via :func:`register_synthetic_envs`.

Parity context: the reference benchmarks env stacks only
(``examples/test_env_throughput.py:16-606``); a synthetic pixel env at the
Atari north-star shape is what lets the pipeline be measured end to end
without ALE ROMs (absent from this image — see docs/LEARNING_CURVES.md).
"""

from __future__ import annotations

import gymnasium as gym
import numpy as np


def render_ring_frame(
    cell: int, size: int, stack: int, num_states: int
) -> np.ndarray:
    """Numpy twin of ``SyntheticPixelEnv._render`` — MUST stay formula-
    identical (bright stripe at the cell-indexed column block over the
    fixed dim texture); ``tests/test_envs.py`` asserts bit-equality
    against the jax renderer so the two cannot drift."""
    rows = np.arange(size)[:, None, None]
    cols = np.arange(size)[None, :, None]
    chans = np.arange(stack)[None, None, :]
    texture = (rows * 2 + cols * 5 + chans * 17) % 128
    stripe_w = max(size // num_states, 1)
    in_stripe = (cols // stripe_w) == cell
    return np.where(in_stripe, 255, texture).astype(np.uint8)


class PixelRingEnv(gym.Env):
    """Deterministic-dynamics pixel env: N pre-rendered ring cells; the
    "correct" action advances the ring, anything else teleports randomly.

    A real ``gym.Env`` subclass: ``gym.make("PixelRing-v0")`` type-checks
    the inheritance, and spawned actor processes build it by id string.
    """

    metadata: dict = {"render_modes": []}

    def __init__(self, size: int = 84, stack: int = 4, num_actions: int = 6,
                 num_states: int = 16, episode_length: int = 128,
                 render_mode=None) -> None:
        # gym.make forwards render_mode to the ctor even when None
        self.render_mode = render_mode
        self.observation_space = gym.spaces.Box(0, 255, (size, size, stack), np.uint8)
        self.action_space = gym.spaces.Discrete(num_actions)
        self.num_states = num_states
        self.num_actions = num_actions
        self.episode_length = episode_length
        self._frames = np.stack(
            [render_ring_frame(c, size, stack, num_states) for c in range(num_states)]
        )
        self._rng = np.random.default_rng(0)
        self._cell = 0
        self._t = 0

    def reset(self, *, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._cell = int(self._rng.integers(self.num_states))
        self._t = 0
        return self._frames[self._cell], {}

    def step(self, action):
        correct = int(action) == (self._cell % self.num_actions)
        reward = float(correct)
        if correct:
            self._cell = (self._cell + 1) % self.num_states
        else:
            self._cell = int(self._rng.integers(self.num_states))
        self._t += 1
        done = self._t >= self.episode_length
        if done:
            self._cell = int(self._rng.integers(self.num_states))
            self._t = 0
        return self._frames[self._cell], reward, done, False, {}

    def close(self):
        pass


class RecallGymEnv(gym.Env):
    """Numpy/gym twin of ``envs/jax_envs/recall.py:JaxRecall`` — flash a
    quadrant cue, wait ``delay`` blank steps, demand recall (+1 / -1 at
    the final step).  A memoryless policy is pinned at expected return
    ``(2 - num_cues) / num_cues``; any positive mean return is proof of
    recurrent memory.  Used by the R2D2 host-plane memory proof."""

    metadata: dict = {"render_modes": []}

    def __init__(self, size: int = 16, delay: int = 6, num_cues: int = 4,
                 render_mode=None) -> None:
        if num_cues not in (2, 4):
            raise ValueError("num_cues must be 2 or 4 (quadrant patterns)")
        self.render_mode = render_mode
        self.size = size
        self.delay = delay
        self.num_cues = num_cues
        self.observation_space = gym.spaces.Box(0, 255, (size, size, 1), np.uint8)
        self.action_space = gym.spaces.Discrete(num_cues)
        self._rng = np.random.default_rng(0)
        self._cue = 0
        self._t = 0

    def _render_frame(self) -> np.ndarray:
        # formula-identical to JaxRecall._render (cue visible only at t=0)
        half = self.size // 2
        rows = np.arange(self.size)[:, None]
        cols = np.arange(self.size)[None, :]
        if self.num_cues == 4:
            in_q = ((rows >= half) == (self._cue // 2)) & (
                (cols >= half) == (self._cue % 2)
            )
        else:
            # broadcast against rows explicitly (same fix as JaxRecall:
            # the half-plane mask alone is [1, size])
            in_q = np.broadcast_to(
                (cols >= half) == (self._cue % 2), (self.size, self.size)
            )
        frame = np.where((self._t == 0) & in_q, 255, 0).astype(np.uint8)
        return frame[:, :, None]

    def reset(self, *, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._cue = int(self._rng.integers(self.num_cues))
        self._t = 0
        return self._render_frame(), {}

    def step(self, action):
        self._t += 1
        done = self._t > self.delay
        reward = (
            (1.0 if int(action) == self._cue else -1.0) if done else 0.0
        )
        if done:
            self._cue = int(self._rng.integers(self.num_cues))
            self._t = 0
        return self._render_frame(), reward, done, False, {}

    def close(self):
        pass


class BreakoutGymEnv(gym.Env):
    """Numpy/gym twin of ``envs/jax_envs/breakout.py:JaxBreakout`` — the
    flagship pixel-control task for the HOST actor plane (CPU envs feeding
    central batched inference), dynamics formula-identical to the device
    env: diagonal unit-velocity ball, 3-wide paddle, +1 per brick, miss
    terminates, cleared wall respawns, time cap truncates."""

    metadata: dict = {"render_modes": []}

    def __init__(
        self,
        size: int = 10,
        stack: int = 1,
        brick_rows: int = 3,
        brick_top: int = 2,
        max_steps: int = 500,
        render_mode=None,
    ) -> None:
        self.render_mode = render_mode
        self.size = size
        self.stack = stack
        self.brick_rows = brick_rows
        self.brick_top = brick_top
        self.max_steps = max_steps
        self.observation_space = gym.spaces.Box(0, 255, (size, size, stack), np.uint8)
        self.action_space = gym.spaces.Discrete(3)
        self._rng = np.random.default_rng(0)
        self._spawn()

    def _spawn(self) -> None:
        self._ball_x = int(self._rng.integers(self.size))
        self._ball_y = self.brick_top + self.brick_rows
        self._dx = 1 if self._rng.random() < 0.5 else -1
        self._dy = 1
        self._paddle_x = self.size // 2
        self._bricks = np.ones((self.brick_rows, self.size), bool)
        self._t = 0

    def _render_frame(self) -> np.ndarray:
        frame = np.zeros((self.size, self.size), np.uint8)
        band = slice(self.brick_top, self.brick_top + self.brick_rows)
        frame[band][self._bricks] = 128
        frame[self.size - 1, max(self._paddle_x - 1, 0) : self._paddle_x + 2] = 255
        frame[self._ball_y, self._ball_x] = 255
        return np.broadcast_to(
            frame[:, :, None], (self.size, self.size, self.stack)
        ).copy()

    def reset(self, *, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._spawn()
        return self._render_frame(), {}

    def step(self, action):
        W = self.size
        self._paddle_x = int(np.clip(self._paddle_x + int(action) - 1, 1, W - 2))

        nx = self._ball_x + self._dx
        if nx < 0 or nx >= W:
            self._dx = -self._dx
            nx = int(np.clip(nx, 0, W - 1))
        ny = self._ball_y + self._dy
        if ny < 0:
            self._dy = 1
            ny = 1

        reward = 0.0
        brow = ny - self.brick_top
        if 0 <= brow < self.brick_rows and self._bricks[brow, nx]:
            self._bricks[brow, nx] = False
            reward = 1.0
            ny = self._ball_y  # reflect back to the previous row
            self._dy = -self._dy

        term = False
        if ny >= W - 1:
            if abs(nx - self._paddle_x) <= 1:
                ny = W - 2
                self._dy = -1
            else:
                term = True
        if not self._bricks.any():
            self._bricks[:] = True

        self._ball_x, self._ball_y = nx, ny
        self._t += 1
        trunc = not term and self._t >= self.max_steps
        if term or trunc:
            self._spawn()
        return self._render_frame(), reward, term, trunc, {}

    def close(self):
        pass


def register_synthetic_envs() -> None:
    """Idempotently register the synthetic envs with gymnasium."""
    import gymnasium as gym

    if "PixelRing-v0" not in gym.registry:
        gym.register(
            id="PixelRing-v0",
            entry_point="scalerl_tpu.envs.synthetic_gym:PixelRingEnv",
            disable_env_checker=True,
        )
    if "RecallGym-v0" not in gym.registry:
        gym.register(
            id="RecallGym-v0",
            entry_point="scalerl_tpu.envs.synthetic_gym:RecallGymEnv",
            disable_env_checker=True,
        )
    if "BreakoutGym-v0" not in gym.registry:
        gym.register(
            id="BreakoutGym-v0",
            entry_point="scalerl_tpu.envs.synthetic_gym:BreakoutGymEnv",
            disable_env_checker=True,
        )
