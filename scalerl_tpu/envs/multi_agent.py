"""Multi-agent parallel-env protocol, adapters, and wrappers.

The protocol is PettingZoo's *parallel* API (``possible_agents``, dict-keyed
``reset``/``step``, per-agent spaces) — real PettingZoo envs plug into
``AsyncMultiAgentVecEnv`` unchanged, without this package importing
pettingzoo.

Parity targets: ``PettingZooAutoResetParallelWrapper``
(``scalerl/envs/pettingzoo_wrappers.py:9-64``) and the single-agent
generalization of the reference's vec-env design called for by SURVEY.md §7
(the shared-memory plane is the learner-host infeed buffer for *all* env
families, not just multi-agent ones).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np


class AutoResetParallelWrapper:
    """Auto-reset a parallel multi-agent env when every agent is done."""

    def __init__(self, env: Any) -> None:
        self.env = env

    @property
    def possible_agents(self) -> Sequence[str]:
        return self.env.possible_agents

    def observation_space(self, agent: str):
        return self.env.observation_space(agent)

    def action_space(self, agent: str):
        return self.env.action_space(agent)

    def reset(self, seed: Optional[int] = None, options=None):
        return self.env.reset(seed=seed, options=options)

    def step(self, actions: Dict[str, Any]):
        obs, rew, term, trunc, infos = self.env.step(actions)
        agents = self.possible_agents
        if all(term.get(a, True) or trunc.get(a, False) for a in agents):
            obs, _reset_infos = self.env.reset()
        return obs, rew, term, trunc, infos

    def close(self) -> None:
        close = getattr(self.env, "close", None)
        if close:
            close()

    def __getattr__(self, name: str):
        return getattr(self.env, name)


class SingleAgentAdapter:
    """Expose a gymnasium env through the parallel multi-agent protocol.

    Makes ``AsyncMultiAgentVecEnv`` double as a shared-memory single-agent
    vector env: one agent named ``agent_0``.
    """

    AGENT = "agent_0"

    def __init__(self, env: Any) -> None:
        self.env = env
        self.possible_agents = [self.AGENT]

    def observation_space(self, agent: str):
        return self.env.observation_space

    def action_space(self, agent: str):
        return self.env.action_space

    def reset(self, seed: Optional[int] = None, options=None):
        obs, info = self.env.reset(seed=seed, options=options)
        return {self.AGENT: obs}, {self.AGENT: info}

    def step(self, actions: Dict[str, Any]):
        obs, reward, terminated, truncated, info = self.env.step(
            actions[self.AGENT]
        )
        a = self.AGENT
        return (
            {a: obs},
            {a: float(reward)},
            {a: bool(terminated)},
            {a: bool(truncated)},
            {a: info},
        )

    def close(self) -> None:
        self.env.close()


class _Box:
    """Minimal space descriptor (shape + dtype), gymnasium-free."""

    def __init__(self, shape: Tuple[int, ...], dtype) -> None:
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)


class _Discrete:
    def __init__(self, n: int) -> None:
        self.n = n
        self.shape = ()
        self.dtype = np.dtype(np.int64)


class PursuitToyEnv:
    """Tiny built-in 2-agent pursuit on a 1-D ring: the chaser scores when
    it lands on the runner.  Used by tests, examples, and the env
    throughput benchmark — no external deps, fully deterministic."""

    SIZE = 8

    def __init__(self, episode_limit: int = 32) -> None:
        self.possible_agents = ["chaser", "runner"]
        self.episode_limit = episode_limit
        self._rng = np.random.default_rng(0)
        self._t = 0
        self._pos = np.zeros(2, np.int64)

    def observation_space(self, agent: str):
        return _Box((4,), np.float32)

    def action_space(self, agent: str):
        return _Discrete(3)  # left / stay / right

    def _obs(self) -> Dict[str, np.ndarray]:
        c, r = self._pos
        base = np.array(
            [c / self.SIZE, r / self.SIZE, (r - c) % self.SIZE / self.SIZE,
             self._t / self.episode_limit],
            np.float32,
        )
        return {"chaser": base, "runner": -base}

    def reset(self, seed: Optional[int] = None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._pos = self._rng.integers(0, self.SIZE, size=2)
        self._t = 0
        return self._obs(), {a: {} for a in self.possible_agents}

    def step(self, actions: Dict[str, int]):
        self._t += 1
        for i, agent in enumerate(self.possible_agents):
            self._pos[i] = (self._pos[i] + int(actions[agent]) - 1) % self.SIZE
        caught = self._pos[0] == self._pos[1]
        reward = {"chaser": 1.0 if caught else -0.01,
                  "runner": -1.0 if caught else 0.01}
        done = bool(caught)
        trunc = self._t >= self.episode_limit
        term = {a: done for a in self.possible_agents}
        truncs = {a: trunc and not done for a in self.possible_agents}
        return self._obs(), reward, term, truncs, {a: {} for a in
                                                   self.possible_agents}

    def close(self) -> None:
        pass


def make_multi_agent_vec_env(
    env_fn, num_envs: int, autoreset: bool = True, **kwargs
):
    """Vectorize a parallel multi-agent env over subprocesses with the
    shared-memory plane (parity: ``make_multi_agent_vect_envs``,
    ``scalerl/envs/env_utils.py:97-120``)."""
    from scalerl_tpu.envs.vector import AsyncMultiAgentVecEnv

    return AsyncMultiAgentVecEnv(
        [env_fn for _ in range(num_envs)], autoreset=autoreset, **kwargs
    )


class _SingleAgentFactory:
    """Picklable env factory so spawn/forkserver contexts work (lambdas
    would restrict the vec env to fork, which is unsafe after JAX has
    started backend threads in the parent)."""

    def __init__(self, env_fn) -> None:
        self.env_fn = env_fn

    def __call__(self):
        return SingleAgentAdapter(self.env_fn())


def make_shared_vec_envs(env_fn, num_envs: int, **kwargs):
    """Single-agent gym envs over the shared-memory vec env."""
    from scalerl_tpu.envs.vector import AsyncMultiAgentVecEnv

    return AsyncMultiAgentVecEnv(
        [_SingleAgentFactory(env_fn) for _ in range(num_envs)], **kwargs
    )
