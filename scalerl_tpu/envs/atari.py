"""DeepMind-style Atari preprocessing stack (gymnasium 5-tuple API).

Parity target: ``scalerl/envs/atari_wrapper.py:19-311`` (NoopReset(30),
MaxAndSkip(4), EpisodicLife, FireReset, WarpFrame 84x84 gray, ScaledFloat,
ClipReward(sign), FrameStack(4)) and the A3C 42x42 variant
(``scalerl/algorithms/a3c/utils/atari_env.py:9-122``), folded into one
module (SURVEY.md §2.2 prescribes merging the two preprocessing stacks).

TPU note: the default output is **channel-last uint8** ``[H, W, stack]``
(not the reference's float CHW) so the host->device infeed moves 4x fewer
bytes and matches XLA's preferred NHWC conv layout; scaling to [0, 1]
happens on device inside the model (``models/atari.py``).  Requires ale_py
for actual Atari ROMs — absent here, the stack is still exercised via
synthetic envs in tests.
"""

from __future__ import annotations

from collections import deque
from typing import Optional

import gymnasium as gym
import numpy as np

try:
    import cv2

    cv2.ocl.setUseOpenCL(False)
except ImportError:  # pragma: no cover
    cv2 = None


class NoopResetEnv(gym.Wrapper):
    """Sample 1..noop_max no-op steps at reset (``atari_wrapper.py:19-49``)."""

    def __init__(self, env: gym.Env, noop_max: int = 30) -> None:
        super().__init__(env)
        self.noop_max = noop_max
        self.noop_action = 0
        assert env.unwrapped.get_action_meanings()[0] == "NOOP"

    def reset(self, **kwargs):
        obs, info = self.env.reset(**kwargs)
        noops = self.unwrapped.np_random.integers(1, self.noop_max + 1)
        for _ in range(noops):
            obs, _, terminated, truncated, info = self.env.step(self.noop_action)
            if terminated or truncated:
                obs, info = self.env.reset(**kwargs)
        return obs, info


class MaxAndSkipEnv(gym.Wrapper):
    """Repeat action ``skip`` times; observe max of last two frames."""

    def __init__(self, env: gym.Env, skip: int = 4) -> None:
        super().__init__(env)
        self._obs_buffer = np.zeros((2,) + env.observation_space.shape, dtype=np.uint8)
        self._skip = skip

    def step(self, action):
        total_reward = 0.0
        terminated = truncated = False
        info = {}
        for i in range(self._skip):
            obs, reward, terminated, truncated, info = self.env.step(action)
            if i == self._skip - 2:
                self._obs_buffer[0] = obs
            if i == self._skip - 1:
                self._obs_buffer[1] = obs
            total_reward += float(reward)
            if terminated or truncated:
                break
        max_frame = self._obs_buffer.max(axis=0)
        return max_frame, total_reward, terminated, truncated, info


class EpisodicLifeEnv(gym.Wrapper):
    """End episode on life loss; only truly reset when the game is over."""

    def __init__(self, env: gym.Env) -> None:
        super().__init__(env)
        self.lives = 0
        self.was_real_done = True

    def step(self, action):
        obs, reward, terminated, truncated, info = self.env.step(action)
        self.was_real_done = terminated or truncated
        lives = self.env.unwrapped.ale.lives()
        if 0 < lives < self.lives:
            terminated = True
        self.lives = lives
        return obs, reward, terminated, truncated, info

    def reset(self, **kwargs):
        if self.was_real_done:
            obs, info = self.env.reset(**kwargs)
        else:
            obs, _, terminated, truncated, info = self.env.step(0)
            if terminated or truncated:
                obs, info = self.env.reset(**kwargs)
        self.lives = self.env.unwrapped.ale.lives()
        return obs, info


class FireResetEnv(gym.Wrapper):
    """Press FIRE at reset for envs that need it to start."""

    def __init__(self, env: gym.Env) -> None:
        super().__init__(env)
        assert env.unwrapped.get_action_meanings()[1] == "FIRE"
        assert len(env.unwrapped.get_action_meanings()) >= 3

    def reset(self, **kwargs):
        self.env.reset(**kwargs)
        obs, _, terminated, truncated, _ = self.env.step(1)
        if terminated or truncated:
            self.env.reset(**kwargs)
        obs, _, terminated, truncated, _ = self.env.step(2)
        if terminated or truncated:
            self.env.reset(**kwargs)
        return obs, {}


class WarpFrame(gym.ObservationWrapper):
    """Grayscale + resize to ``size`` x ``size`` (84 DeepMind / 42 A3C)."""

    def __init__(self, env: gym.Env, size: int = 84) -> None:
        super().__init__(env)
        if cv2 is None:  # pragma: no cover
            raise ImportError("WarpFrame requires opencv-python")
        self.size = size
        self.observation_space = gym.spaces.Box(
            low=0, high=255, shape=(size, size, 1), dtype=np.uint8
        )

    def observation(self, frame):
        frame = cv2.cvtColor(frame, cv2.COLOR_RGB2GRAY)
        frame = cv2.resize(frame, (self.size, self.size), interpolation=cv2.INTER_AREA)
        return frame[:, :, None]


class ScaledFloatFrame(gym.ObservationWrapper):
    """uint8 -> [0,1] float32.  NOT in the default stack: scaling happens on
    device (``models/atari.py``) to keep infeed uint8."""

    def __init__(self, env: gym.Env) -> None:
        super().__init__(env)
        self.observation_space = gym.spaces.Box(
            low=0.0, high=1.0, shape=env.observation_space.shape, dtype=np.float32
        )

    def observation(self, obs):
        return np.asarray(obs, dtype=np.float32) / 255.0


class ClipRewardEnv(gym.RewardWrapper):
    """Reward -> sign(reward)."""

    def reward(self, reward):
        return float(np.sign(reward))


class FrameStack(gym.Wrapper):
    """Stack the last ``k`` frames along the channel axis (channel-last)."""

    def __init__(self, env: gym.Env, k: int = 4) -> None:
        super().__init__(env)
        self.k = k
        self.frames: deque = deque([], maxlen=k)
        shp = env.observation_space.shape
        assert len(shp) == 3, "FrameStack expects [H, W, C] observations"
        self.observation_space = gym.spaces.Box(
            low=0, high=255, shape=(shp[0], shp[1], shp[2] * k), dtype=env.observation_space.dtype
        )

    def reset(self, **kwargs):
        obs, info = self.env.reset(**kwargs)
        for _ in range(self.k):
            self.frames.append(obs)
        return self._get_obs(), info

    def step(self, action):
        obs, reward, terminated, truncated, info = self.env.step(action)
        self.frames.append(obs)
        return self._get_obs(), reward, terminated, truncated, info

    def _get_obs(self):
        assert len(self.frames) == self.k
        return np.concatenate(list(self.frames), axis=-1)


class NormalizedEnv(gym.ObservationWrapper):
    """Running mean/std observation normalization with EMA bias correction.

    Parity: the A3C Atari variant's ``NormalizedEnv``
    (``scalerl/algorithms/a3c/utils/atari_env.py:87-122``): scalar running
    mean and std over whole observations, decay ``alpha``, divided by
    ``1 - alpha^t`` to unbias early steps.
    """

    def __init__(self, env: gym.Env, alpha: float = 0.9999) -> None:
        super().__init__(env)
        self.alpha = alpha
        self.state_mean = 0.0
        self.state_std = 0.0
        self.num_steps = 0
        self.observation_space = gym.spaces.Box(
            low=-np.inf, high=np.inf, shape=env.observation_space.shape,
            dtype=np.float32,
        )

    def observation(self, observation):
        obs = np.asarray(observation, np.float32)
        self.num_steps += 1
        self.state_mean = self.alpha * self.state_mean + (1 - self.alpha) * obs.mean()
        self.state_std = self.alpha * self.state_std + (1 - self.alpha) * obs.std()
        correction = 1 - self.alpha**self.num_steps
        unbiased_mean = self.state_mean / correction
        unbiased_std = self.state_std / correction
        return (obs - unbiased_mean) / (unbiased_std + 1e-8)


def create_atari_env(
    env_id: str,
    seed: int = 42,
    warp_size: int = 42,
    normalize: bool = True,
) -> gym.Env:
    """The A3C 42x42 Atari variant: rescale + running-norm (parity:
    ``create_atari_env``, ``a3c/utils/atari_env.py:9-30``)."""
    env = gym.make(env_id)
    env = wrap_deepmind(
        env,
        episode_life=False,
        clip_rewards=False,
        frame_stack=1,
        warp_size=warp_size,
    )
    if normalize:
        env = NormalizedEnv(env)
    env.action_space.seed(seed)
    return env


def wrap_deepmind(
    env: gym.Env,
    episode_life: bool = True,
    clip_rewards: bool = True,
    frame_stack: int = 4,
    scale: bool = False,
    warp_size: int = 84,
    noop_max: int = 30,
    skip: int = 4,
) -> gym.Env:
    """The full DeepMind stack (``atari_wrapper.py:277-311`` parity)."""
    env = NoopResetEnv(env, noop_max=noop_max)
    env = MaxAndSkipEnv(env, skip=skip)
    if episode_life:
        env = EpisodicLifeEnv(env)
    if "FIRE" in env.unwrapped.get_action_meanings():
        env = FireResetEnv(env)
    env = WarpFrame(env, size=warp_size)
    if scale:
        env = ScaledFloatFrame(env)
    if clip_rewards:
        env = ClipRewardEnv(env)
    if frame_stack > 1:
        env = FrameStack(env, frame_stack)
    return env


def make_atari_env(env_id: str, seed: int = 42, **wrap_kwargs) -> gym.Env:
    """gym.make + full DeepMind preprocessing (requires ale_py)."""
    env = gym.make(env_id)
    env = wrap_deepmind(env, **wrap_kwargs)
    env.action_space.seed(seed)
    return env
