"""Native (C++) runtime components, built lazily with the system toolchain.

The reference's "native muscle" was all third-party (NCCL/CUDA via torch —
SURVEY.md §2 intro); this package is the TPU build's own native layer:
a lock-free shared-memory rollout ring (``csrc/shm_ring.cpp``) used by the
actor->learner hot path.  Everything degrades gracefully: if no compiler is
available the callers fall back to pure-Python implementations.
"""

from scalerl_tpu.native.build import load_ring_lib, native_available  # noqa: F401
