"""Lazy g++ build + ctypes loader for the native runtime library."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional

from scalerl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_CSRC = Path(__file__).resolve().parents[2] / "csrc"
_BUILD_DIR = Path(__file__).resolve().parent / "_build"
_LIB_PATH = _BUILD_DIR / "libsrl_ring.so"
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _build() -> Optional[Path]:
    src = _CSRC / "shm_ring.cpp"
    if not src.exists():
        return None
    if _LIB_PATH.exists() and _LIB_PATH.stat().st_mtime >= src.stat().st_mtime:
        return _LIB_PATH
    _BUILD_DIR.mkdir(parents=True, exist_ok=True)
    # cross-process safety: serialize concurrent builds with a file lock and
    # publish via atomic rename so no process can dlopen a half-written .so
    import fcntl

    lock_path = _BUILD_DIR / ".build.lock"
    with open(lock_path, "w") as lock_f:
        fcntl.flock(lock_f, fcntl.LOCK_EX)
        try:
            if (
                _LIB_PATH.exists()
                and _LIB_PATH.stat().st_mtime >= src.stat().st_mtime
            ):
                return _LIB_PATH  # another process built it while we waited
            tmp = _BUILD_DIR / f"libsrl_ring.{os.getpid()}.tmp.so"
            cmd = [
                "g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                "-o", str(tmp), str(src), "-lpthread",
            ]
            try:
                subprocess.run(
                    cmd, check=True, capture_output=True, text=True, timeout=120
                )
                os.replace(tmp, _LIB_PATH)
            except (OSError, subprocess.SubprocessError) as e:
                detail = getattr(e, "stderr", "") or str(e)
                logger.warning(
                    "native build failed, using Python fallback: %s", detail
                )
                tmp.unlink(missing_ok=True)
                return None
            return _LIB_PATH
        finally:
            fcntl.flock(lock_f, fcntl.LOCK_UN)


def _annotate(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.srl_ring_bytes.argtypes = [ctypes.c_uint32]
    lib.srl_ring_bytes.restype = ctypes.c_uint64
    lib.srl_ring_init.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.srl_ring_init.restype = ctypes.c_int
    lib.srl_ring_check.argtypes = [ctypes.c_void_p]
    lib.srl_ring_check.restype = ctypes.c_int
    lib.srl_ring_acquire.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.srl_ring_acquire.restype = ctypes.c_int32
    lib.srl_ring_commit.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.srl_ring_commit.restype = ctypes.c_int
    lib.srl_ring_pop_full.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.srl_ring_pop_full.restype = ctypes.c_int32
    lib.srl_ring_release.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.srl_ring_release.restype = ctypes.c_int
    lib.srl_ring_close.argtypes = [ctypes.c_void_p]
    lib.srl_ring_close.restype = None
    lib.srl_ring_closed.argtypes = [ctypes.c_void_p]
    lib.srl_ring_closed.restype = ctypes.c_int
    lib.srl_gather_batch.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_char_p),
        ctypes.c_uint32,
        ctypes.c_uint64,
    ]
    lib.srl_gather_batch.restype = None
    return lib


def load_ring_lib() -> Optional[ctypes.CDLL]:
    """Build (once) and load the native ring library; None if unavailable."""
    global _LIB, _TRIED
    with _LOCK:
        if _TRIED:
            return _LIB
        _TRIED = True
        if os.environ.get("SCALERL_TPU_NO_NATIVE"):
            return None
        path = _build()
        if path is None:
            return None
        try:
            _LIB = _annotate(ctypes.CDLL(str(path)))
        except OSError as e:
            logger.warning("could not load native lib: %s", e)
            _LIB = None
        return _LIB


def native_available() -> bool:
    return load_ring_lib() is not None
