"""Orbax checkpointing of train-state pytrees.

Parity target: per-agent ``save_checkpoint``/``load_checkpoint``
(``scalerl/algorithms/dqn/dqn_agent.py:210-233``, interface
``algorithms/base.py:102-116``) and IMPALA's periodic checkpoints
(``impala_atari.py:496-515``), upgraded to Orbax: atomic directory writes,
async-friendly, and shard-aware for multi-host meshes (the reference's
``torch.save`` has none of these).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp


def save_checkpoint(path: str, state: Any) -> str:
    """Save a pytree to ``path`` (write-new-then-swap). Returns the path.

    The full save lands in a ``.tmp`` sibling first, so a crash mid-save
    never destroys the previous checkpoint — the only unprotected window is
    the final rmtree+rename metadata swap.
    """
    import shutil

    path = os.path.abspath(path)
    tmp = path + ".tmp"
    checkpointer = ocp.StandardCheckpointer()
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    checkpointer.save(tmp, state)
    checkpointer.wait_until_finished()
    if os.path.exists(path):
        shutil.rmtree(path)
    os.rename(tmp, path)
    return path


def load_checkpoint(path: str, target: Optional[Any] = None) -> Any:
    """Restore a pytree from ``path``; ``target`` provides structure/dtypes."""
    path = os.path.abspath(path)
    checkpointer = ocp.StandardCheckpointer()
    if target is not None:
        abstract = jax.tree_util.tree_map(ocp.utils.to_shape_dtype_struct, target)
        return checkpointer.restore(path, abstract)
    return checkpointer.restore(path)
