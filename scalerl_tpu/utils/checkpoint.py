"""Orbax checkpointing of train-state pytrees.

Parity target: per-agent ``save_checkpoint``/``load_checkpoint``
(``scalerl/algorithms/dqn/dqn_agent.py:210-233``, interface
``algorithms/base.py:102-116``) and IMPALA's periodic checkpoints
(``impala_atari.py:496-515``), upgraded to Orbax: atomic directory writes,
async-friendly, and shard-aware for multi-host meshes (the reference's
``torch.save`` has none of these).

Crash-safety contract (the supervision layer leans on this):

- a save NEVER has a window where no complete checkpoint exists on disk:
  the new state lands in ``path.tmp`` first, the previous checkpoint is
  *retained* as ``path.prev`` (…``path.prevK`` up to ``keep_last``) while the
  new one swaps in — never deleted before the swap;
- a restore that finds the latest dir corrupt/partial (a preemption mid-swap,
  a torn filesystem) falls back through the retained ``.prev`` chain instead
  of failing the run.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
from typing import Any, Dict, List, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from scalerl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# per-leaf digest manifest written INSIDE every checkpoint dir; orbax
# ignores foreign files, and the manifest travels with the dir through the
# .prev rotation for free
MANIFEST_NAME = "integrity_manifest.json"


class CheckpointIntegrityError(RuntimeError):
    """Restored leaves do not match the manifest digests (silent corruption
    orbax cannot see — a flipped bit in a data file still parses)."""


def _leaf_digest(leaf: Any) -> str:
    arr = np.ascontiguousarray(np.asarray(jax.device_get(leaf)))
    h = hashlib.sha256()
    h.update(str((arr.dtype.str, arr.shape)).encode())
    h.update(arr.tobytes())
    return h.hexdigest()


def _tree_digests(state: Any) -> List[Dict[str, str]]:
    """Per-leaf sha256 digests, with save-time key paths for diagnostics.

    Verification compares the digest MULTISET, not the paths: a restore
    without a ``target`` materializes container types (dicts) different
    from the saved dataclasses, which reorders/renames paths while the leaf
    bytes — the thing integrity is about — are unchanged.
    """
    out = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        out.append({"path": jax.tree_util.keystr(path), "sha256": _leaf_digest(leaf)})
    return out


def write_manifest(path: str, state: Any) -> str:
    manifest = {"format": 1, "leaves": _tree_digests(state)}
    target = os.path.join(path, MANIFEST_NAME)
    with open(target, "w") as f:
        json.dump(manifest, f, indent=1)
    return target


def verify_manifest(path: str, restored: Any) -> None:
    """Raise :class:`CheckpointIntegrityError` if ``restored`` does not
    reproduce the digests recorded at save time.  Checkpoints predating the
    manifest (no file) pass — upgrade compatibility."""
    mpath = os.path.join(path, MANIFEST_NAME)
    if not os.path.exists(mpath):
        return
    try:
        with open(mpath) as f:
            manifest = json.load(f)
        expected = sorted(leaf["sha256"] for leaf in manifest["leaves"])
    except (ValueError, KeyError, TypeError) as e:
        raise CheckpointIntegrityError(
            f"unreadable integrity manifest at {mpath}: {e}"
        ) from e
    actual = sorted(d["sha256"] for d in _tree_digests(restored))
    if expected != actual:
        bad = len(set(expected).symmetric_difference(actual))
        raise CheckpointIntegrityError(
            f"checkpoint {path} failed digest verification: "
            f"{bad} leaf digest(s) differ from the save-time manifest"
        )


def _prev_path(path: str, k: int) -> str:
    """k-th displaced checkpoint: ``path.prev``, ``path.prev2``, ..."""
    return path + (".prev" if k == 1 else f".prev{k}")


def checkpoint_fallbacks(path: str) -> List[str]:
    """Existing retained predecessors of ``path``, newest first."""
    out: List[str] = []
    k = 1
    while True:
        p = _prev_path(path, k)
        if not os.path.exists(p):
            break
        out.append(p)
        k += 1
    return out


def save_checkpoint(path: str, state: Any, keep_last: int = 1) -> str:
    """Save a pytree to ``path`` (write-new-then-rotate). Returns the path.

    The full save lands in a ``.tmp`` sibling first; the previous checkpoint
    is then ROTATED to ``path.prev`` (not deleted) before the atomic
    ``rename(tmp, path)``, so every instant of the sequence has at least one
    complete checkpoint on disk — a preemption mid-save costs nothing, and a
    corrupt latest restores from ``.prev`` (``load_checkpoint`` falls back
    automatically).

    ``keep_last``: how many displaced checkpoints to retain
    (``path.prev`` … ``path.prevN``); 0 deletes the predecessor after the
    new checkpoint has landed (still no unprotected window — the delete
    happens strictly after the rename).
    """
    path = os.path.abspath(path)
    tmp = path + ".tmp"
    checkpointer = ocp.StandardCheckpointer()
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    checkpointer.save(tmp, state)
    checkpointer.wait_until_finished()
    # per-leaf digest manifest INSIDE the dir (before the atomic rename, so
    # a checkpoint is never visible without its manifest): load_checkpoint
    # verifies restored bytes against it and falls back through .prev on a
    # mismatch — deterministic corruption detection, not "hope orbax raises"
    write_manifest(tmp, state)
    # rotate the retention chain oldest-first so each rename target is free
    if os.path.exists(path):
        oldest = _prev_path(path, max(keep_last, 1))
        if os.path.exists(oldest):
            shutil.rmtree(oldest)
        for k in range(max(keep_last, 1) - 1, 0, -1):
            src = _prev_path(path, k)
            if os.path.exists(src):
                os.rename(src, _prev_path(path, k + 1))
        os.rename(path, _prev_path(path, 1))
    os.rename(tmp, path)
    if keep_last <= 0:
        prev = _prev_path(path, 1)
        if os.path.exists(prev):
            shutil.rmtree(prev)
    inj = _chaos_active()
    if inj is not None:
        # chaos: leave the freshly-landed checkpoint partial (a preemption
        # mid-flush) — restores must fall back through the .prev chain
        inj.corrupt_checkpoint(path)
    _telemetry().record_event("checkpoint_save", path=path)
    _telemetry().get_registry().counter("checkpoint.saves").inc()
    return path


def load_checkpoint(
    path: str, target: Optional[Any] = None, fallback: bool = True
) -> Any:
    """Restore a pytree from ``path``; ``target`` provides structure/dtypes.

    ``fallback``: when the latest checkpoint is corrupt or partial (restore
    raises), fall back through the retained ``path.prev`` chain — the
    preemption-safety contract of ``save_checkpoint``.  The original error
    is chained if every candidate fails.
    """
    path = os.path.abspath(path)
    candidates = [path] + (checkpoint_fallbacks(path) if fallback else [])
    first_err: Optional[Exception] = None
    for cand in candidates:
        try:
            restored = _restore(cand, target)
            _telemetry().record_event(
                "checkpoint_restore", path=cand, fallback=cand != path
            )
            _telemetry().get_registry().counter("checkpoint.restores").inc()
            return restored
        except Exception as e:  # noqa: BLE001 — try the retained predecessor
            if first_err is None:
                first_err = e
            if fallback and cand != candidates[-1]:
                _telemetry().record_event(
                    "checkpoint_fallback", path=cand, error=repr(e)
                )
                _telemetry().get_registry().counter("checkpoint.fallbacks").inc()
                logger.warning(
                    "checkpoint %s failed to restore (%r); falling back to %s",
                    cand, e, candidates[candidates.index(cand) + 1],
                )
    assert first_err is not None
    raise first_err


def _restore(path: str, target: Optional[Any]) -> Any:
    checkpointer = ocp.StandardCheckpointer()
    if target is not None:
        abstract = jax.tree_util.tree_map(ocp.utils.to_shape_dtype_struct, target)
        restored = checkpointer.restore(path, abstract)
    else:
        restored = checkpointer.restore(path)
    verify_manifest(path, restored)
    return restored


def _chaos_active():
    from scalerl_tpu.runtime import chaos

    return chaos.active()


def _telemetry():
    # lazy: keep jax-free importers of runtime.telemetry from paying for
    # orbax, and this module from importing telemetry at module load
    from scalerl_tpu.runtime import telemetry

    return telemetry
