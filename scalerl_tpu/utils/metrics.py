"""Vectorized episode accounting for env pools.

Parity target: ``EpisodeMetrics`` (``scalerl/envs/env_utils.py:10-82``) and
``calculate_vectorized_scores`` (``:123-164``) / ``calculate_mean``
(``scalerl/utils/utils.py``).  Pure numpy on the host — episode boundaries are
data-dependent and belong outside jit.
"""

from __future__ import annotations

from typing import Dict, List, Mapping, Sequence

import numpy as np


class EpisodeMetrics:
    """Track per-env running return/length and report completed episodes."""

    def __init__(self, num_envs: int) -> None:
        self.num_envs = num_envs
        self._returns = np.zeros(num_envs, dtype=np.float64)
        self._lengths = np.zeros(num_envs, dtype=np.int64)
        self.episode_returns: List[float] = []
        self.episode_lengths: List[int] = []

    def step(self, rewards: np.ndarray, dones: np.ndarray, lane0: int = 0) -> int:
        """Accumulate one vector step. Returns number of episodes completed.

        ``lane0`` lets a sub-fleet (e.g. one Ape-X actor's env slab) update
        only its own contiguous lane block; different actors touch disjoint
        lanes, so concurrent threaded updates stay well-defined.
        """
        rewards = np.asarray(rewards, dtype=np.float64).ravel()
        width = rewards.shape[0]
        dones = np.asarray(dones).reshape(width).astype(bool)
        lanes = slice(lane0, lane0 + width)
        self._returns[lanes] += rewards
        self._lengths[lanes] += 1
        finished = int(dones.sum())
        if finished:
            for i in np.nonzero(dones)[0]:
                self.episode_returns.append(float(self._returns[lane0 + i]))
                self.episode_lengths.append(int(self._lengths[lane0 + i]))
            ret_block = self._returns[lanes]
            len_block = self._lengths[lanes]
            ret_block[dones] = 0.0
            len_block[dones] = 0
        return finished

    @property
    def num_episodes(self) -> int:
        return len(self.episode_returns)

    def summary(self, window: int = 100) -> Dict[str, float]:
        rets = self.episode_returns[-window:]
        lens = self.episode_lengths[-window:]
        if not rets:
            return {"episodes": 0}
        return {
            "episodes": float(len(self.episode_returns)),
            "return_mean": float(np.mean(rets)),
            "return_std": float(np.std(rets)),
            "return_max": float(np.max(rets)),
            "return_min": float(np.min(rets)),
            "length_mean": float(np.mean(lens)),
        }


def calculate_vectorized_scores(
    rewards: np.ndarray,
    dones: np.ndarray,
    include_unterminated: bool = False,
) -> List[float]:
    """Split ``[T, N]`` reward/done arrays into completed-episode returns."""
    rewards = np.asarray(rewards, dtype=np.float64)
    dones = np.asarray(dones).astype(bool)
    if rewards.ndim == 1:
        rewards = rewards[:, None]
        dones = dones[:, None]
    T, N = rewards.shape
    scores: List[float] = []
    for env in range(N):
        acc = 0.0
        steps = 0
        for t in range(T):
            acc += rewards[t, env]
            steps += 1
            if dones[t, env]:
                scores.append(acc)
                acc = 0.0
                steps = 0
        if include_unterminated and steps > 0:
            scores.append(acc)
    return scores


def calculate_mean(dicts: Sequence[Mapping[str, float]]) -> Dict[str, float]:
    """Average a list of metric dicts key-wise (keys may be ragged)."""
    out: Dict[str, List[float]] = {}
    for d in dicts:
        for k, v in d.items():
            out.setdefault(k, []).append(float(v))
    return {k: float(np.mean(v)) for k, v in out.items()}
