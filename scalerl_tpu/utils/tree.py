"""Pytree parameter utilities: target-network updates, counting.

Parity target: ``hard_target_update`` / ``soft_target_update``
(``scalerl/utils/model_utils.py:4-32``) — reimagined as pure functions over
Flax parameter pytrees so they can live inside a jitted train step (the
reference mutates ``nn.Module`` state dicts on the host).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

Params = Any


def hard_target_update(online: Params, target: Params) -> Params:
    """target <- online (pure; returns a distinct-buffer copy, so donation of
    a state holding both never sees aliased buffers)."""
    del target
    return jax.tree_util.tree_map(jnp.copy, online)


def soft_target_update(online: Params, target: Params, tau: float) -> Params:
    """Polyak update: target <- tau * online + (1 - tau) * target."""
    return jax.tree_util.tree_map(
        lambda o, t: tau * o + (1.0 - tau) * t, online, target
    )


def periodic_target_update(
    online: Params, target: Params, steps: jnp.ndarray, period: int
) -> Params:
    """Hard-update target every ``period`` steps; identity otherwise (jittable)."""
    return jax.tree_util.tree_map(
        lambda o, t: jnp.where(steps % period == 0, o, t), online, target
    )


def param_count(params: Params) -> int:
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(params))


def tree_norm(tree: Params) -> jnp.ndarray:
    """Global L2 norm of a pytree."""
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.sqrt(sum(jnp.sum(jnp.square(x)) for x in leaves))
