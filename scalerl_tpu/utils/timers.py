"""Lightweight host-side profiling timers.

Parity targets: ``Timings`` online mean/variance event profiler
(``scalerl/utils/profile.py:10-65``, MonoBeast-derived design) and
``Timer`` (``scalerl/utils/timer.py:12-118``).  For device-side tracing use
``jax.profiler.trace`` — these timers cover the host runtime (env stepping,
queue waits, infeed) where ``jax.profiler`` has no visibility.

All clocks are ``time.monotonic()``: these are interval timers, and a
wall-clock jump (NTP step, suspend/resume, a container migration) under
``time.time()`` would feed a negative or multi-hour "elapsed" sample
straight into the Welford accumulators, permanently corrupting the
mean/variance stats the stall reports and telemetry lean on.
"""

from __future__ import annotations

import collections
import time
from typing import Dict


class Timings:
    """Per-event online mean/variance timers (Welford update).

    Usage::

        t = Timings()
        ... step env ...
        t.time("step")
        ... write buffer ...
        t.time("write")
    """

    def __init__(self) -> None:
        # plain dicts: reads must never insert keys (the old defaultdicts
        # grew phantom zero-entries on every speculative lookup)
        self._means: Dict[str, float] = {}
        self._vars: Dict[str, float] = {}
        self._counts: Dict[str, int] = {}
        self.reset()

    def reset(self) -> None:
        self.last_time = time.monotonic()

    def time(self, name: str) -> None:
        """Record the elapsed time since the last ``time``/``reset`` call."""
        now = time.monotonic()
        x = now - self.last_time
        self.last_time = now
        n = self._counts.get(name, 0) + 1
        mean = self._means.get(name, 0.0)
        delta = x - mean
        mean += delta / n
        delta2 = x - mean
        self._means[name] = mean
        self._vars[name] = self._vars.get(name, 0.0) + delta * delta2
        self._counts[name] = n

    def means(self) -> Dict[str, float]:
        return dict(self._means)

    def stds(self) -> Dict[str, float]:
        """Per-event std-devs; lookups of never-recorded keys return 0.0
        (a defaultdict view) instead of raising — summary consumers probe
        speculative keys like ``dequeue`` that only some topologies emit."""
        return collections.defaultdict(
            float,
            {
                k: (self._vars.get(k, 0.0) / max(self._counts.get(k, 1), 1)) ** 0.5
                for k in self._counts
            },
        )

    def summary(self, prefix: str = "") -> str:
        means = self.means()
        stds = self.stds()
        total = sum(means.values()) or 1.0
        rows = [
            f"  {k}: {1000.0 * means[k]:.2f}ms +- {1000.0 * stds[k]:.2f}ms "
            f"({100.0 * means[k] / total:.1f}%)"
            for k in sorted(means, key=means.get, reverse=True)  # type: ignore[arg-type]
        ]
        return f"{prefix}total: {1000.0 * total:.2f}ms\n" + "\n".join(rows)


class Timer:
    """Context-manager stopwatch with a running check interval."""

    def __init__(self) -> None:
        self._start = time.monotonic()
        self._last_check = self._start
        self._running = True

    def __enter__(self) -> "Timer":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self._running = False

    def start(self) -> None:
        self._start = time.monotonic()
        self._last_check = self._start
        self._running = True

    def since_start(self) -> float:
        return time.monotonic() - self._start

    def since_last_check(self) -> float:
        now = time.monotonic()
        dur = now - self._last_check
        self._last_check = now
        return dur

    def check_time(self, interval: float) -> bool:
        """True (and reset the check clock) if ``interval`` seconds elapsed."""
        now = time.monotonic()
        if now - self._last_check >= interval:
            self._last_check = now
            return True
        return False
