"""Platform selection honoring ``RLArguments.platform``.

Under the axon TPU tunnel the ``JAX_PLATFORMS`` env var is ignored (the
plugin registers regardless), so ``--platform cpu`` must go through
``jax.config.update('jax_platforms', ...)`` *before* first backend use.
"""

from __future__ import annotations


def setup_platform(platform: str = "auto") -> str:
    """Pin the JAX backend. Call before any jax array/computation is created.

    ``auto`` keeps JAX's default (TPU when present).  Returns the backend
    actually in use.
    """
    import jax

    if platform and platform != "auto":
        jax.config.update("jax_platforms", platform)
    return jax.default_backend()
