"""Platform selection honoring ``RLArguments.platform``.

Under the axon TPU tunnel the ``JAX_PLATFORMS`` env var is ignored (the
plugin registers regardless), so ``--platform cpu`` must go through
``jax.config.update('jax_platforms', ...)`` *before* first backend use.
"""

from __future__ import annotations

import os
import sys
from typing import Optional


def jax_runtime_initialized() -> bool:
    """True iff a JAX backend has been created in this process.

    Passive: never imports jax or triggers backend init itself (backend
    init can hang for minutes under the axon tunnel).  Used to decide the
    multiprocessing start method — forking after XLA has started its
    thread pools clones held mutexes into the child, which can deadlock
    (the reference never hits this: torch tolerates fork; JAX does not).
    """
    if "jax" not in sys.modules:
        return False
    try:
        from jax._src import xla_bridge as xb

        return bool(xb._backends)
    except Exception:  # noqa: BLE001 — jax-internals drift: assume not init
        return False


def safe_mp_context(requested: Optional[str] = None) -> Optional[str]:
    """Resolve a multiprocessing start-method name.

    Explicit ``requested`` always wins.  Otherwise: ``"spawn"`` when a JAX
    backend already lives in this process (fork would be unsafe — see
    ``jax_runtime_initialized``), else ``None`` (the platform default,
    fork on Linux, which is cheapest when no runtime is at risk).
    Call sites must keep worker targets/runners picklable so the spawn
    path works when it triggers.
    """
    if requested is not None:
        return requested
    return "spawn" if jax_runtime_initialized() else None


def setup_platform(
    platform: str = "auto", compilation_cache: bool = True
) -> str:
    """Pin the JAX backend. Call before any jax array/computation is created.

    ``auto`` keeps JAX's default (TPU when present).  Returns the backend
    actually in use.

    ``compilation_cache`` enables JAX's persistent compilation cache
    (``~/.cache/scalerl_tpu_xla`` unless ``JAX_COMPILATION_CACHE_DIR`` is
    set) on accelerator backends: TPU first-compiles of the fused loop run
    20-40 s, and every entry script re-traces the same programs — the cache
    turns relaunch compiles into disk reads.  CPU is deliberately excluded:
    XLA:CPU caches AOT machine code whose recorded target features can
    mismatch the loading host (the loader warns about possible SIGILL).
    Disable with ``compilation_cache=False`` or
    ``SCALERL_NO_COMPILATION_CACHE=1``.
    """
    import jax

    if platform and platform != "auto":
        jax.config.update("jax_platforms", platform)
    backend = jax.default_backend()
    if (
        compilation_cache
        and backend in ("tpu", "gpu")
        and not os.environ.get("SCALERL_NO_COMPILATION_CACHE")
    ):
        cache_dir = os.environ.get(
            "JAX_COMPILATION_CACHE_DIR",
            os.path.join(os.path.expanduser("~"), ".cache", "scalerl_tpu_xla"),
        )
        try:
            # jax's default min-compile-time threshold (~1 s) stays: the
            # expensive fused-loop compiles clear it, and trivial programs
            # don't bloat the cache dir
            jax.config.update("jax_compilation_cache_dir", cache_dir)
        except Exception as e:  # noqa: BLE001 — cache is best-effort
            import warnings

            warnings.warn(
                f"persistent compilation cache unavailable ({e}); "
                "relaunches will pay full XLA compile times"
            )
    return backend
