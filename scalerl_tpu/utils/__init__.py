"""Shared utilities: logging, metrics, schedulers, profiling, pytree ops.

Exports resolve lazily (PEP 562): ``profiling`` and ``tree`` import jax at
module level, but the jax-free planes (fleet shells, the chaos injector,
the disagg generation hosts, telemetry) import ``utils.logging`` and
friends from worker processes that must not pay the multi-second jax
import — the package itself therefore stays import-light.
"""

from typing import Any

_EXPORTS = {
    "get_logger": "scalerl_tpu.utils.logging",
    "EpisodeMetrics": "scalerl_tpu.utils.metrics",
    "calculate_mean": "scalerl_tpu.utils.metrics",
    "calculate_vectorized_scores": "scalerl_tpu.utils.metrics",
    "LinearDecayScheduler": "scalerl_tpu.utils.schedulers",
    "MultiStepScheduler": "scalerl_tpu.utils.schedulers",
    "PiecewiseScheduler": "scalerl_tpu.utils.schedulers",
    "annotate": "scalerl_tpu.utils.profiling",
    "maybe_trace": "scalerl_tpu.utils.profiling",
    "step_marker": "scalerl_tpu.utils.profiling",
    "trace": "scalerl_tpu.utils.profiling",
    "Timer": "scalerl_tpu.utils.timers",
    "Timings": "scalerl_tpu.utils.timers",
    "hard_target_update": "scalerl_tpu.utils.tree",
    "param_count": "scalerl_tpu.utils.tree",
    "soft_target_update": "scalerl_tpu.utils.tree",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str) -> Any:
    module_name = _EXPORTS.get(name)
    if module_name is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(module_name), name)


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
