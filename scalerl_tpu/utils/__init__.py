from scalerl_tpu.utils.logging import get_logger  # noqa: F401
from scalerl_tpu.utils.metrics import (  # noqa: F401
    EpisodeMetrics,
    calculate_mean,
    calculate_vectorized_scores,
)
from scalerl_tpu.utils.schedulers import (  # noqa: F401
    LinearDecayScheduler,
    MultiStepScheduler,
    PiecewiseScheduler,
)
from scalerl_tpu.utils.profiling import (  # noqa: F401
    annotate,
    maybe_trace,
    step_marker,
    trace,
)
from scalerl_tpu.utils.timers import Timer, Timings  # noqa: F401
from scalerl_tpu.utils.tree import (  # noqa: F401
    hard_target_update,
    param_count,
    soft_target_update,
)
