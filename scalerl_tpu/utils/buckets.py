"""The power-of-two bucket ladder: one shape-stability util, many planes.

Every dynamic-arrival plane in the codebase pads ragged sizes up a fixed
ladder so its jitted programs compile once per bucket and never retrace on
arrival patterns (graftlint JG003 designed out rather than linted out):

- the serving plane buckets *batch lanes* (``serving/batcher.py``);
- the generation engines bucket *prompt/response lengths* on the time axis
  (``genrl/engine.py``, ``genrl/continuous.py``) and the continuous
  engine additionally buckets *admitted-prefill batch sizes*;
- the page allocator sizes page tables off the largest bucket pair.

Extracted here (ISSUE 11) so the ladder has ONE definition and direct unit
tests; ``serving.batcher`` re-exports both names for compatibility.
jax-free by design.
"""

from __future__ import annotations

from typing import List, Tuple


def default_buckets(max_size: int) -> Tuple[int, ...]:
    """Power-of-two ladder up to (and always including) ``max_size``."""
    buckets: List[int] = []
    b = 1
    while b < max_size:
        buckets.append(b)
        b *= 2
    buckets.append(max_size)
    return tuple(buckets)


def bucket_for(size: int, buckets: Tuple[int, ...]) -> int:
    """Smallest bucket >= size; oversize requests get their own
    next-power-of-two bucket (a rare extra trace, never an error)."""
    for b in buckets:
        if size <= b:
            return b
    b = buckets[-1] if buckets else 1
    while b < size:
        b *= 2
    return b
