"""Interval-gated scalar loggers: TensorBoard, W&B, or silent.

Parity targets: ``BaseLogger``/``LazyLogger`` (``scalerl/utils/logger/base.py:
12-146``), ``TensorboardLogger`` incl. resume via event replay
(``scalerl/utils/logger/tensorboard.py:41-82``), and ``WandbLogger``
(``scalerl/utils/logger/wandb.py:104-160``, gated on wandb being installed).
"""

from __future__ import annotations

import itertools
import os
from abc import ABC, abstractmethod
from numbers import Number
from typing import Callable, Dict, Optional, Tuple

WRITE_TYPE = Tuple[str, int, Dict[str, float]]

# tensorboardX names event files events.out.tfevents.<second>.<hostname>:
# two writers on one dir within the same second SILENTLY OVERWRITE each
# other — exactly the resume path (restore_data constructs a fresh writer
# over the old run dir).  A per-process sequence + pid suffix makes every
# writer's file unique.
_WRITER_SEQ = itertools.count()


class BaseLogger(ABC):
    """Scalar logger with per-namespace interval gating."""

    def __init__(
        self,
        train_interval: int = 1000,
        test_interval: int = 1,
        update_interval: int = 1000,
    ) -> None:
        self.train_interval = train_interval
        self.test_interval = test_interval
        self.update_interval = update_interval
        self.last_log_train_step = -1
        self.last_log_test_step = -1
        self.last_log_update_step = -1

    @abstractmethod
    def write(self, step_type: str, step: int, data: Dict[str, float]) -> None:
        ...

    def log_train_data(self, data: Dict[str, float], step: int) -> None:
        if step - self.last_log_train_step >= self.train_interval:
            self.write("train/env_step", step, {f"train/{k}": v for k, v in data.items()})
            self.last_log_train_step = step

    def log_test_data(self, data: Dict[str, float], step: int) -> None:
        if step - self.last_log_test_step >= self.test_interval:
            self.write("test/env_step", step, {f"test/{k}": v for k, v in data.items()})
            self.last_log_test_step = step

    def log_update_data(self, data: Dict[str, float], step: int) -> None:
        if step - self.last_log_update_step >= self.update_interval:
            self.write("update/gradient_step", step, {f"update/{k}": v for k, v in data.items()})
            self.last_log_update_step = step

    def log_registry(
        self,
        step: int,
        step_type: str = "train",
        registry=None,
        include_prefixes: Optional[Tuple[str, ...]] = None,
        extra: Optional[Dict[str, float]] = None,
    ) -> None:
        """Registry-backed write path: flatten the telemetry registry's
        scalars and route them through the existing interval gating.

        Trainers populate the process registry (gauges/meters/counters) and
        call this instead of hand-assembling a metric dict; every backend
        (TensorBoard/W&B/none) then reads from the same plane.  Dots become
        slashes so instruments group in TensorBoard (``train.fps`` →
        ``train/fps``).  ``include_prefixes`` narrows the write to matching
        instrument names; ``extra`` rides along (already-host floats only).
        """
        from scalerl_tpu.runtime.telemetry import get_registry

        reg = registry if registry is not None else get_registry()
        scalars = reg.scalars()
        if include_prefixes is not None:
            scalars = {
                k: v
                for k, v in scalars.items()
                if k.startswith(include_prefixes)
            }
        # the gating methods prefix with their namespace; drop a redundant
        # leading instrument namespace (train.fps → train/fps, not
        # train/train/fps)
        ns = step_type + "."
        data = {
            (k[len(ns):] if k.startswith(ns) else k).replace(".", "/"): v
            for k, v in scalars.items()
        }
        if extra:
            data.update(extra)
        if step_type == "train":
            self.log_train_data(data, step)
        elif step_type == "test":
            self.log_test_data(data, step)
        elif step_type == "update":
            self.log_update_data(data, step)
        else:
            raise ValueError(
                f"unknown step_type {step_type!r}; expected train|test|update"
            )

    def save_data(
        self,
        epoch: int,
        env_step: int,
        gradient_step: int,
        checkpoint_fn: Optional[Callable[[int, int, int], str]] = None,
    ) -> None:
        pass

    def restore_data(self) -> Tuple[int, int, int]:
        return 0, 0, 0

    def close(self) -> None:
        pass


class LazyLogger(BaseLogger):
    """A no-op logger (``scalerl/utils/logger/base.py:133-146``)."""

    def __init__(self) -> None:
        super().__init__()

    def write(self, step_type: str, step: int, data: Dict[str, float]) -> None:
        pass


class TensorboardLogger(BaseLogger):
    """TensorBoard scalar logger with resume via event-file replay."""

    SAVE_KEYS = ("save/epoch", "save/env_step", "save/gradient_step")

    def __init__(
        self,
        log_dir: str,
        train_interval: int = 1000,
        test_interval: int = 1,
        update_interval: int = 1000,
    ) -> None:
        super().__init__(train_interval, test_interval, update_interval)
        # tensorboardX keeps this framework torch-free (torch's SummaryWriter
        # would drag in a multi-GB dependency for event-file writing)
        from tensorboardX import SummaryWriter

        os.makedirs(log_dir, exist_ok=True)
        self.log_dir = log_dir
        self.writer = SummaryWriter(
            log_dir, filename_suffix=f".{os.getpid()}.{next(_WRITER_SEQ)}"
        )

    def write(self, step_type: str, step: int, data: Dict[str, float]) -> None:
        for k, v in data.items():
            if isinstance(v, Number) or getattr(v, "ndim", None) == 0:
                self.writer.add_scalar(k, float(v), global_step=step)
        self.writer.flush()

    def save_data(
        self,
        epoch: int,
        env_step: int,
        gradient_step: int,
        checkpoint_fn: Optional[Callable[[int, int, int], str]] = None,
    ) -> None:
        if checkpoint_fn is not None:
            checkpoint_fn(epoch, env_step, gradient_step)
        self.write("save/epoch", epoch, {"save/epoch": epoch})
        self.write("save/env_step", env_step, {"save/env_step": env_step})
        self.write(
            "save/gradient_step", gradient_step, {"save/gradient_step": gradient_step}
        )

    def restore_data(self) -> Tuple[int, int, int]:
        """Replay event files to recover save/{epoch,env_step,gradient_step}."""
        from tensorboard.backend.event_processing import event_accumulator

        ea = event_accumulator.EventAccumulator(self.log_dir)
        ea.Reload()
        out = []
        for key in self.SAVE_KEYS:
            try:
                out.append(int(ea.Scalars(key)[-1].step))
            except KeyError:
                out.append(0)
        epoch, env_step, gradient_step = out
        self.last_log_train_step = env_step
        self.last_log_update_step = gradient_step
        return epoch, env_step, gradient_step

    def close(self) -> None:
        self.writer.close()


class WandbLogger(BaseLogger):
    """Weights & Biases logger (requires ``wandb``; raises a clear error if absent)."""

    def __init__(
        self,
        project: str,
        name: Optional[str] = None,
        config: Optional[dict] = None,
        train_interval: int = 1000,
        test_interval: int = 1,
        update_interval: int = 1000,
    ) -> None:
        super().__init__(train_interval, test_interval, update_interval)
        try:
            import wandb
        except ImportError as e:  # pragma: no cover - wandb not in image
            raise ImportError(
                "WandbLogger requires `wandb`; install it or use "
                "logger_backend='tensorboard'"
            ) from e
        self.wandb = wandb
        self.run = wandb.init(project=project, name=name, config=config, resume="allow")

    def write(self, step_type: str, step: int, data: Dict[str, float]) -> None:
        # Record the gating step as a field instead of wandb's monotonic
        # ``step=`` axis: train logs are gated on env_step while update logs
        # are gated on gradient_step, and interleaving those on one axis makes
        # wandb drop out-of-order rows.
        self.wandb.log({**data, step_type: step})

    def save_data(
        self,
        epoch: int,
        env_step: int,
        gradient_step: int,
        checkpoint_fn: Optional[Callable[[int, int, int], str]] = None,
    ) -> None:
        if checkpoint_fn is not None:
            path = checkpoint_fn(epoch, env_step, gradient_step)
            artifact = self.wandb.Artifact("run_checkpoint", type="model")
            if path and os.path.exists(path):
                artifact.add_dir(path) if os.path.isdir(path) else artifact.add_file(path)
            self.run.log_artifact(artifact)
        self.wandb.log(
            {
                "save/epoch": epoch,
                "save/env_step": env_step,
                "save/gradient_step": gradient_step,
            },
            step=env_step,
        )

    def close(self) -> None:
        self.run.finish()


def make_logger(
    backend: str,
    log_dir: str,
    project: str = "scalerl_tpu",
    name: Optional[str] = None,
    config: Optional[dict] = None,
    **intervals: int,
) -> BaseLogger:
    if backend == "tensorboard":
        return TensorboardLogger(log_dir, **intervals)
    if backend == "wandb":
        return WandbLogger(project=project, name=name, config=config, **intervals)
    if backend in ("none", "lazy"):
        return LazyLogger()
    raise ValueError(
        f"unknown logger backend {backend!r}; expected "
        "'tensorboard' | 'wandb' | 'none'"
    )
