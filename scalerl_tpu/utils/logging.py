"""Process-rank-aware colored logging.

Capability parity with the reference's OpenMMLab-derived logger
(``scalerl/utils/logger/logging.py:30-110``, duplicated at
``scalerl/utils/logger_utils.py:29-110`` — the duplication is not carried
over): colored stream output, rank-0-only file handlers, and non-zero ranks
silenced to ERROR.  Rank here is the JAX process index (multi-host DCN), not a
torch.distributed rank.
"""

from __future__ import annotations

import logging
import os
import sys
from typing import Dict, Optional

_initialized_loggers: Dict[str, logging.Logger] = {}

_COLORS = {
    logging.DEBUG: "\x1b[36m",  # cyan
    logging.INFO: "\x1b[32m",  # green
    logging.WARNING: "\x1b[33m",  # yellow
    logging.ERROR: "\x1b[31m",  # red
    logging.CRITICAL: "\x1b[35m",  # magenta
}
_RESET = "\x1b[0m"


class _ColorFormatter(logging.Formatter):
    def __init__(self, use_color: bool = True) -> None:
        super().__init__("%(asctime)s - %(name)s - %(levelname)s - %(message)s")
        self.use_color = use_color

    def format(self, record: logging.LogRecord) -> str:
        msg = super().format(record)
        if self.use_color:
            color = _COLORS.get(record.levelno, "")
            if color:
                msg = f"{color}{msg}{_RESET}"
        return msg


def process_index() -> int:
    """Current distributed process index (0 on single-host).

    Deliberately does NOT force JAX backend initialization:
    ``get_logger`` runs at module-import time all over the package, and
    ``jax.process_index()`` would spin up the device runtime (on the axon
    TPU tunnel this can block for minutes while another process holds the
    chip).  If no backend exists yet, the multihost process id — when
    ``jax.distributed`` was initialized — or the env override decides.
    """
    # env override wins (also the escape hatch if the private-API probes
    # below break on a jax upgrade — they are each isolated so a rename
    # degrades to the next probe, never to an exception)
    env = os.environ.get("SCALERL_PROCESS_INDEX")
    if env is not None:
        return int(env)
    if "jax" not in sys.modules:
        # jax was never imported, so neither jax.distributed nor a backend
        # can be initialized — and importing jax here would charge every
        # jax-free fleet/disagg child the multi-second package import just
        # to learn the answer is 0
        return 0
    try:  # multihost: jax.distributed.initialize() recorded a process id
        from jax._src import distributed

        pid = getattr(distributed.global_state, "process_id", None)
        if pid:  # 0 is also the uninitialized default -> fall through
            return int(pid)
    except Exception:  # pragma: no cover - private-API drift
        pass
    try:  # backend already up -> querying it is cheap and safe
        import jax
        from jax._src import xla_bridge

        if getattr(xla_bridge, "_backends", None):
            return jax.process_index()
    except Exception:  # pragma: no cover - private-API drift
        pass
    return 0


def get_logger(
    name: str = "scalerl_tpu",
    log_file: Optional[str] = None,
    log_level: int = logging.INFO,
) -> logging.Logger:
    """Return a logger writing colored stream output; file output on rank 0 only.

    Non-zero ranks are raised to ERROR so a multi-host run logs once
    (reference behavior: ``logger/logging.py:95-102``).
    """
    logger = logging.getLogger(name)
    if name in _initialized_loggers:
        return logger
    logger.propagate = False

    stream = logging.StreamHandler(sys.stderr)
    stream.setFormatter(_ColorFormatter(use_color=sys.stderr.isatty()))
    handlers: list[logging.Handler] = [stream]

    rank = process_index()
    if rank == 0 and log_file is not None:
        os.makedirs(os.path.dirname(log_file) or ".", exist_ok=True)
        fh = logging.FileHandler(log_file, "a")
        fh.setFormatter(_ColorFormatter(use_color=False))
        handlers.append(fh)

    level = log_level if rank == 0 else logging.ERROR
    for h in handlers:
        h.setLevel(level)
        logger.addHandler(h)
    logger.setLevel(level)
    _initialized_loggers[name] = logger
    return logger
