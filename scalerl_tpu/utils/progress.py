"""Terminal progress bar + task-mapping helpers.

Parity target: mmcv-style ``ProgressBar`` / ``track_progress`` /
``track_parallel_progress`` (``scalerl/utils/progress_bar.py:16-247``).
"""

from __future__ import annotations

import sys
import time
from multiprocessing import Pool
from shutil import get_terminal_size
from typing import Any, Callable, Iterable, List, Optional, Sequence


class ProgressBar:
    def __init__(self, task_num: int = 0, bar_width: int = 50, start: bool = True, file=sys.stdout) -> None:
        self.task_num = task_num
        self.bar_width = bar_width
        self.completed = 0
        self.file = file
        if start:
            self.start()

    @property
    def terminal_width(self) -> int:
        return get_terminal_size().columns

    def start(self) -> None:
        if self.task_num > 0:
            self.file.write(f"[{' ' * self.bar_width}] 0/{self.task_num}, elapsed: 0s, ETA:")
        else:
            self.file.write("completed: 0, elapsed: 0s")
        self.file.flush()
        self.start_time = time.time()

    def update(self, num_tasks: int = 1) -> None:
        self.completed += num_tasks
        elapsed = time.time() - self.start_time or 1e-8
        fps = self.completed / elapsed
        if self.task_num > 0:
            pct = self.completed / float(self.task_num)
            eta = int(elapsed * (1 - pct) / max(pct, 1e-8) + 0.5)
            msg = (
                f"\r[{{}}] {self.completed}/{self.task_num}, {fps:.1f} task/s, "
                f"elapsed: {int(elapsed + 0.5)}s, ETA: {eta:5}s"
            )
            bar_width = min(self.bar_width, int(self.terminal_width - len(msg)) + 2, int(self.terminal_width * 0.6))
            bar_width = max(2, bar_width)
            mark_width = int(bar_width * pct)
            bar_chars = ">" * mark_width + " " * (bar_width - mark_width)
            self.file.write(msg.format(bar_chars))
        else:
            self.file.write(
                f"completed: {self.completed}, elapsed: {int(elapsed + 0.5)}s, {fps:.1f} tasks/s"
            )
        self.file.flush()


def track_progress(func: Callable, tasks: Sequence[Any], bar_width: int = 50, file=sys.stdout, **kwargs) -> List[Any]:
    """Map ``func`` over ``tasks`` with a progress bar."""
    prog_bar = ProgressBar(len(tasks), bar_width, file=file)
    results = []
    for task in tasks:
        results.append(func(task, **kwargs))
        prog_bar.update()
    file.write("\n")
    return results


def track_iter_progress(tasks: Sequence[Any], bar_width: int = 50, file=sys.stdout) -> Iterable[Any]:
    prog_bar = ProgressBar(len(tasks), bar_width, file=file)
    for task in tasks:
        yield task
        prog_bar.update()
    file.write("\n")


def track_parallel_progress(
    func: Callable,
    tasks: Sequence[Any],
    nproc: int,
    initializer: Optional[Callable] = None,
    initargs: tuple = (),
    bar_width: int = 50,
    chunksize: int = 1,
    keep_order: bool = True,
    file=sys.stdout,
) -> List[Any]:
    """Parallel map with a progress bar (process pool)."""
    pool = Pool(nproc, initializer, initargs)
    prog_bar = ProgressBar(len(tasks), bar_width, file=file)
    results = []
    gen = pool.imap(func, tasks, chunksize) if keep_order else pool.imap_unordered(func, tasks, chunksize)
    for result in gen:
        results.append(result)
        prog_bar.update()
    file.write("\n")
    pool.close()
    pool.join()
    return results
