"""Host-side hyperparameter schedulers (epsilon, LR, PER beta).

Parity target: ``scalerl/utils/lr_scheduler.py:7-117`` (``PiecewiseScheduler``,
``LinearDecayScheduler``, ``MultiStepScheduler``).  These run on the host and
feed scalar values into jitted steps; device-side LR schedules can instead use
``optax`` schedules directly (see ``scalerl_tpu.agents``).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple


class PiecewiseScheduler:
    """Piecewise-constant schedule over step boundaries."""

    def __init__(self, endpoints: Sequence[Tuple[int, float]]) -> None:
        if not endpoints:
            raise ValueError("endpoints must be non-empty")
        steps = [s for s, _ in endpoints]
        if steps != sorted(steps):
            raise ValueError(f"endpoints must be sorted by step, got {steps}")
        self.endpoints = list(endpoints)
        self.cur_step = 0

    def value(self, step: int) -> float:
        out = self.endpoints[0][1]
        for boundary, v in self.endpoints:
            if step >= boundary:
                out = v
            else:
                break
        return out

    def step(self, num: int = 1) -> float:
        self.cur_step += num
        return self.value(self.cur_step)


class LinearDecayScheduler:
    """Linear interpolation from start to end over ``total_steps``."""

    def __init__(self, start_value: float, end_value: float, total_steps: int) -> None:
        if total_steps <= 0:
            raise ValueError(f"total_steps must be positive, got {total_steps}")
        self.start_value = float(start_value)
        self.end_value = float(end_value)
        self.total_steps = int(total_steps)
        self.cur_step = 0

    def value(self, step: int) -> float:
        frac = min(max(step / self.total_steps, 0.0), 1.0)
        return self.start_value + frac * (self.end_value - self.start_value)

    def step(self, num: int = 1) -> float:
        self.cur_step += num
        return self.value(self.cur_step)


class MultiStepScheduler:
    """Multiply the value by ``gamma`` at each milestone."""

    def __init__(
        self,
        start_value: float,
        milestones: Sequence[int],
        gamma: float = 0.1,
    ) -> None:
        ms: List[int] = list(milestones)
        if ms != sorted(ms):
            raise ValueError(f"milestones must be sorted, got {ms}")
        self.start_value = float(start_value)
        self.milestones = ms
        self.gamma = float(gamma)
        self.cur_step = 0

    def value(self, step: int) -> float:
        v = self.start_value
        for m in self.milestones:
            if step >= m:
                v *= self.gamma
        return v

    def step(self, num: int = 1) -> float:
        self.cur_step += num
        return self.value(self.cur_step)
