"""Device-side tracing: jax.profiler integration.

The TPU half of the observability story (SURVEY.md §5): the reference had
only host timers (``scalerl/utils/profile.py``) — ported as
``utils.timers`` — with no device tracing at all.  Here ``trace()`` wraps
``jax.profiler.trace`` (XPlane/perfetto output for TensorBoard's profile
plugin) and ``annotate()`` names host regions so queue waits and env
stepping line up against device streams in the trace viewer.
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional

import jax


@contextlib.contextmanager
def trace(log_dir: str, create_perfetto_link: bool = False) -> Iterator[None]:
    """Capture a device+host profile into ``log_dir``.

    View with TensorBoard's profile plugin, or pass
    ``create_perfetto_link=True`` for a perfetto URL (blocks at exit).
    """
    jax.profiler.start_trace(log_dir, create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str) -> "jax.profiler.TraceAnnotation":
    """Name a host-side region so it shows up in the captured trace:

        with annotate("drain_rollout_queue"):
            batch, idxs = queue.get_batch(...)
    """
    return jax.profiler.TraceAnnotation(name)


def step_marker(step: int) -> "jax.profiler.StepTraceAnnotation":
    """Mark one train step (enables per-step breakdowns in the viewer)."""
    return jax.profiler.StepTraceAnnotation("train", step_num=step)


@contextlib.contextmanager
def maybe_trace(log_dir: Optional[str]) -> Iterator[None]:
    """``trace`` when a directory is configured, no-op otherwise — lets
    trainers accept a ``--profile-dir`` flag unconditionally."""
    if log_dir:
        with trace(log_dir):
            yield
    else:
        yield
