"""Fully-fused on-device actor-learner loop (the flagship throughput path).

Replaces the reference's process zoo — actor processes doing per-step CPU
inference + queue hand-off + learner batching (``impala_atari.py:153-268``)
— with ONE XLA program per training iteration: env step, policy forward,
action sample, trajectory collection (``lax.scan`` over the unroll), V-trace
learner update.  Multiple iterations are themselves ``lax.scan``-ed so the
host dispatches once per ``iters_per_call`` updates — essential under the
axon tunnel where each host->device dispatch costs ~50-100 ms, and the reason
this path reaches orders of magnitude more env-frames/sec than the
reference's architecture on the same chip count.

Works with any ``JaxVecEnv`` (device-native env) and any model implementing
the recurrent-policy signature (``models/policy.py``).  Within a fused
iteration the behavior policy equals the target policy (V-trace rhos = 1,
the on-policy special case); the *host* actor plane
(``trainer/actor_learner.py``) exercises true off-policy lag.
"""

from __future__ import annotations

from contextlib import nullcontext
from functools import partial
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from scalerl_tpu.agents.impala import ImpalaTrainState
from scalerl_tpu.data.trajectory import Trajectory
from scalerl_tpu.envs.jax_envs.base import JaxVecEnv
from scalerl_tpu.runtime import dispatch, telemetry
from scalerl_tpu.runtime.dispatch import MetricsPipeline, get_metrics
from scalerl_tpu.utils.profiling import step_marker


class ActorCarry(NamedTuple):
    """Per-env actor state threaded across rollout chunks.

    Every leaf keeps the env/batch axis leading (the accumulators are
    per-env vectors, not scalars), so the whole carry shards uniformly
    over a ``dp`` mesh axis in the multi-device fused loop.
    """

    env_state: Any
    obs: jnp.ndarray  # [B, ...]
    last_action: jnp.ndarray  # [B]
    reward: jnp.ndarray  # [B]
    done: jnp.ndarray  # [B]
    core_state: Any  # model recurrent state
    episode_return: jnp.ndarray  # [B] running return accumulator
    return_sum: jnp.ndarray  # [B] per-env sum of completed-episode returns
    episode_count: jnp.ndarray  # [B] per-env completed-episode count


def resolve_iter_mode(iter_mode: str = "auto") -> str:
    """Resolve the fused loop's iteration-fusion strategy.

    ``"scan"`` wraps the per-iteration (rollout + learn) body in
    ``lax.scan`` — compile time stays flat in ``iters_per_call`` and the
    program is small; this is the right choice on TPU/GPU.  ``"unroll"``
    expands the iterations as a Python loop inside the one jitted program —
    identical math, but no ``while`` wrapper in the HLO.

    Why the knob exists (the r05 bench regression verdict,
    docs/PERFORMANCE.md): XLA:CPU lowers convolution *gradient* ops inside
    a while-loop body through a non-Eigen path that is catastrophically
    slow — the fused IMPALA chunk measured **23.2 s wrapped in a length-1
    ``lax.scan`` vs 0.42 s with the same body unrolled** (~55x) on this
    repo's bench shape.  ``"auto"`` therefore picks ``"unroll"`` on the CPU
    backend and ``"scan"`` everywhere else.  ``SCALERL_ITER_MODE`` overrides
    what ``auto`` resolves to (escape hatch, same pattern as
    ``SCALERL_PER_METHOD``)."""
    import os

    modes = ("scan", "unroll")
    if iter_mode != "auto":
        if iter_mode not in modes:
            raise ValueError(
                f"iter_mode must be one of {('auto',) + modes}, got {iter_mode!r}"
            )
        return iter_mode
    forced = os.environ.get("SCALERL_ITER_MODE")
    if forced:
        if forced not in modes:
            raise ValueError(
                f"SCALERL_ITER_MODE={forced!r} is not one of {modes}"
            )
        return forced
    return "unroll" if jax.default_backend() == "cpu" else "scan"


class DeviceActorLearnerLoop:
    def __init__(
        self,
        model,
        venv: JaxVecEnv,
        learn_fn: Callable[[ImpalaTrainState, Trajectory], Tuple[ImpalaTrainState, Dict]],
        unroll_length: int,
        iters_per_call: int = 10,
        mesh=None,
        axis_name: str = "dp",
        iter_mode: str = "auto",
    ) -> None:
        """``mesh``: shard the fused loop data-parallel over a mesh — env
        lanes and actor carry split along ``axis_name``, params replicated,
        gradients ``psum``-ed inside the learn step (pass a ``learn_fn``
        built with ``grad_axis=axis_name``).  This is the Podracer "Anakin"
        architecture; ``venv.num_envs`` must divide by the axis size.

        ``iter_mode``: how iterations fuse into the chunk program —
        ``"scan"`` (lax.scan body, TPU/GPU), ``"unroll"`` (Python-unrolled
        body; recovers XLA:CPU's ~55x conv-grad-in-while-loop slowdown), or
        ``"auto"`` (backend-resolved, see :func:`resolve_iter_mode`)."""
        self.model = model
        self.venv = venv
        self.learn_fn = learn_fn
        self.unroll_length = unroll_length
        self.iters_per_call = iters_per_call
        self.mesh = mesh
        self.axis_name = axis_name
        self.iter_mode = resolve_iter_mode(iter_mode)
        # superchunk executables keyed by num_chunks (the Anakin whole-run
        # fusion: one dispatch covers N chunks of rollout+learn)
        self._superchunks: Dict[int, Callable] = {}
        self._superchunk_warm: set = set()
        if mesh is None:
            self._train_many = jax.jit(
                partial(self._train_many_impl), donate_argnums=(0, 1)
            )
        else:
            n = mesh.shape[axis_name]
            if venv.num_envs % n != 0:
                raise ValueError(
                    f"num_envs ({venv.num_envs}) must divide by mesh axis "
                    f"{axis_name!r} size ({n})"
                )
            self._sharded_fn = None  # built on first call (needs pytree structure)
            self._train_many = self._sharded_train_many

    # ------------------------------------------------------------------
    def _sharded_train_many(self, state, carry, key):
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        if self._sharded_fn is None:
            axis = self.axis_name

            def leaf_spec(x):
                if getattr(x, "ndim", 0) >= 1:
                    return P(axis, *([None] * (x.ndim - 1)))
                return P()

            state_spec = jax.tree_util.tree_map(lambda x: P(), state)
            carry_spec = jax.tree_util.tree_map(leaf_spec, carry)

            def inner(state, carry, key):
                # distinct randomness per shard: fold the device's ring index
                key = jax.random.fold_in(
                    key, jax.lax.axis_index(self.axis_name)
                )
                return self._train_many_impl(state, carry, key)

            def inner_synced(state, carry, key):
                state, carry, metrics = inner(state, carry, key)
                # monitoring sums fused into the step (a host-side jnp.sum
                # per chunk would cost an extra dispatch each)
                metrics["episode_return_sum"] = jax.lax.psum(
                    jnp.sum(carry.return_sum), axis
                )
                metrics["episode_count_sum"] = jax.lax.psum(
                    jnp.sum(carry.episode_count), axis
                )
                return state, carry, metrics

            fn = shard_map(
                inner_synced,
                mesh=self.mesh,
                in_specs=(state_spec, carry_spec, P()),
                # metrics leave the learn step replicated (sum-convention
                # losses psum-ed, mean_* pmean-ed — impala_loss contract)
                out_specs=(state_spec, carry_spec, P()),
                check_rep=False,
            )
            # check_rep=False disables the replication check, so a learn_fn
            # built WITHOUT grad_axis would silently train each shard on its
            # own grads; verify the traced program psums over our axis.
            # Trace `inner` (pre-monitoring) so the check is independent of
            # how many monitoring psums `inner_synced` adds, and cache only
            # after the check passes — a caller that catches the error and
            # retries must not get an unsynced cached fn.
            probe = shard_map(
                inner,
                mesh=self.mesh,
                in_specs=(state_spec, carry_spec, P()),
                out_specs=(state_spec, carry_spec, P()),
                check_rep=False,
            )
            self._assert_grad_synced(probe, state, carry, key)
            self._sharded_fn = jax.jit(fn, donate_argnums=(0, 1))
        return self._sharded_fn(state, carry, key)

    def _assert_grad_synced(self, fn, state, carry, key) -> None:
        """Fail fast if the sharded step has no *gradient-sized* psum over
        ``axis_name``.  ``fn`` must be the pre-monitoring program — the
        caller passes a probe without the monitoring psums.  Heuristic:
        gradient syncs psum *arrays* (param leaves: kernels, biases), while
        metric/counter psums carry scalars — so require at least one psum
        over the axis with an operand of rank >= 1.  A learn_fn that psums
        only scalar metrics still fails the check.  Best-effort:
        jax-internals changes skip the check rather than break the loop."""
        try:
            jaxpr = jax.make_jaxpr(fn)(state, carry, key)

            def count_array_psums(jxp) -> int:
                n = 0
                for eqn in jxp.eqns:
                    if (
                        eqn.primitive.name == "psum"
                        and self.axis_name in (eqn.params.get("axes") or ())
                        and any(
                            getattr(v.aval, "ndim", 0) >= 1 for v in eqn.invars
                        )
                    ):
                        n += 1
                    for v in eqn.params.values():
                        inner_jaxpr = getattr(v, "jaxpr", v)
                        if hasattr(inner_jaxpr, "eqns"):
                            n += count_array_psums(inner_jaxpr)
                return n

            n_psums = count_array_psums(jaxpr.jaxpr)
        except Exception:  # noqa: BLE001 — introspection only
            return
        if n_psums == 0:
            raise ValueError(
                "mesh mode needs a gradient-synchronized learn_fn: build it "
                f"with grad_axis={self.axis_name!r} (e.g. "
                "agent.make_learn_fn(grad_axis=...)); the traced step "
                "contains no array-valued (gradient-sized) psum over the "
                "mesh axis, so each device would train on its own shard only"
            )

    # ------------------------------------------------------------------
    def init_carry(self, key: jax.Array) -> ActorCarry:
        B = self.venv.num_envs
        env_state, obs = self.venv.reset(key)
        return ActorCarry(
            env_state=env_state,
            obs=obs,
            last_action=jnp.zeros(B, jnp.int32),
            reward=jnp.zeros(B, jnp.float32),
            done=jnp.ones(B, jnp.bool_),
            core_state=self.model.initial_state(B),
            episode_return=jnp.zeros(B, jnp.float32),
            return_sum=jnp.zeros(B, jnp.float32),
            episode_count=jnp.zeros(B, jnp.float32),
        )

    # ------------------------------------------------------------------
    def _unroll(self, params, carry: ActorCarry, key: jax.Array):
        """Collect one [T+1, B] trajectory chunk; row T's logits are unused
        by the learner (behavior_logits[:-1]) and left zero."""
        core0 = carry.core_state

        def step(c: ActorCarry, k):
            out, new_core = self.model.apply(
                params, c.obs[None], c.last_action[None], c.reward[None],
                c.done[None], c.core_state,
            )
            logits = out.policy_logits[0]
            k_act, k_env = jax.random.split(k)
            action = jax.random.categorical(k_act, logits, axis=-1)
            env_state, next_obs, reward, done = self.venv.step(
                c.env_state, action, k_env
            )
            row = (c.obs, c.last_action, c.reward, c.done, logits)
            ep_ret = c.episode_return + reward
            new_c = ActorCarry(
                env_state=env_state,
                obs=next_obs,
                last_action=action,
                reward=reward,
                done=done,
                core_state=new_core,
                episode_return=jnp.where(done, 0.0, ep_ret),
                return_sum=c.return_sum + jnp.where(done, ep_ret, 0.0),
                episode_count=c.episode_count + done.astype(jnp.float32),
            )
            return new_c, row

        keys = jax.random.split(key, self.unroll_length)
        carry, rows = jax.lax.scan(step, carry, keys)
        obs_rows, la_rows, rew_rows, done_rows, logit_rows = rows

        # final row T from the post-scan carry (logits zero: unused)
        traj = Trajectory(
            obs=jnp.concatenate([obs_rows, carry.obs[None]], axis=0),
            action=jnp.concatenate([la_rows, carry.last_action[None]], axis=0),
            reward=jnp.concatenate([rew_rows, carry.reward[None]], axis=0),
            done=jnp.concatenate([done_rows, carry.done[None]], axis=0),
            logits=jnp.concatenate(
                [logit_rows, jnp.zeros_like(logit_rows[:1])], axis=0
            ),
            core_state=core0,
        )
        return carry, traj

    # ------------------------------------------------------------------
    def _train_many_impl(self, state: ImpalaTrainState, carry: ActorCarry, key):
        def one_iter(sc, k):
            state, carry = sc
            k_roll, _ = jax.random.split(k)
            carry, traj = self._unroll(state.params, carry, k_roll)
            state, metrics = self.learn_fn(state, traj)
            return (state, carry), metrics

        keys = jax.random.split(key, self.iters_per_call)
        if self.iter_mode == "scan":
            (state, carry), metrics = jax.lax.scan(one_iter, (state, carry), keys)
        else:
            # "unroll": same iteration body, Python-expanded — no while
            # wrapper in the HLO, so XLA:CPU's slow conv-grad-in-loop
            # lowering is never hit (the r05 bench regression; the stacked
            # metrics keep the scan path's exact reduction order)
            per_iter = []
            sc = (state, carry)
            for i in range(self.iters_per_call):
                sc, m = one_iter(sc, keys[i])
                per_iter.append(m)
            state, carry = sc
            metrics = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *per_iter
            )
        mean_metrics = {k: jnp.mean(v) for k, v in metrics.items()}
        # monitoring sums ride the fused program (shard-local here; the mesh
        # wrapper overwrites them with the psum-ed globals)
        mean_metrics["episode_return_sum"] = jnp.sum(carry.return_sum)
        mean_metrics["episode_count_sum"] = jnp.sum(carry.episode_count)
        return state, carry, mean_metrics

    # ------------------------------------------------------------------
    def _superchunk_impl(self, state, carry, key, num_chunks: int):
        """The Anakin whole-run fusion: ``num_chunks`` chunks of
        (rollout + V-trace learn) in ONE program.

        The per-chunk key schedule replicates ``run``'s host loop exactly
        (``key, sub = split(key)`` each chunk), so the final state and the
        per-chunk metric stream are bitwise-comparable with the chunked
        driver — the parity contract ``tests/test_dispatch.py`` asserts.
        Per-chunk metric dicts come back stacked ``[num_chunks]`` and are
        materialized by the caller with ONE batched transfer for the whole
        super-chunk.
        """

        def one_chunk(sc, _):
            state, carry, key = sc
            key, sub = jax.random.split(key)
            state, carry, m = self._train_many_impl(state, carry, sub)
            return (state, carry, key), m

        if self.iter_mode == "scan":
            (state, carry, key), stacked = jax.lax.scan(
                one_chunk, (state, carry, key), None, length=num_chunks
            )
        else:
            per_chunk = []
            sc = (state, carry, key)
            for _ in range(num_chunks):
                sc, m = one_chunk(sc, None)
                per_chunk.append(m)
            state, carry, key = sc
            stacked = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *per_chunk
            )
        return state, carry, stacked

    def train_superchunk(
        self, state, carry, key, num_chunks: int
    ) -> Tuple[ImpalaTrainState, ActorCarry, Dict]:
        """One host dispatch covering ``num_chunks`` fused chunks (Anakin).

        Metrics are returned as DEVICE arrays stacked ``[num_chunks]`` per
        key — read them back with one ``dispatch.get_metrics`` call.
        Inputs are donated, like :meth:`train_chunk`.
        """
        if self.mesh is not None:
            raise NotImplementedError(
                "train_superchunk composes with the single-device fused "
                "loop; the mesh path already fuses per-chunk via shard_map "
                "(drive it through run())"
            )
        fn = self._superchunks.get(num_chunks)
        if fn is None:
            fn = jax.jit(
                partial(self._superchunk_impl, num_chunks=num_chunks),
                donate_argnums=(0, 1),
            )
            self._superchunks[num_chunks] = fn
        return fn(state, carry, key)

    def run_anakin(
        self,
        state: ImpalaTrainState,
        carry: ActorCarry,
        key: jax.Array,
        num_calls: int,
        on_metrics: Optional[Callable[[int, Dict[str, float]], None]] = None,
        progress=None,
        instrument: bool = True,
    ) -> Tuple[ImpalaTrainState, ActorCarry, Dict[str, float]]:
        """Drive ``num_calls`` chunks as ONE fused dispatch (Anakin mode).

        Where :meth:`run` dispatches once per chunk and pipelines the metric
        reads, this path dispatches once per *run*: a single jitted
        ``lax.scan`` (or unrolled body, per ``iter_mode``) over (env step ->
        policy -> V-trace learn) covers every chunk, and ONE batched
        device->host transfer materializes the whole stacked metric history
        afterwards.  Steady state (every ``run_anakin`` call after the first
        for a given ``num_calls``) runs under the armed transfer guard.
        ``on_metrics(i, metrics)`` fires per chunk, in order, after the
        read — the metric stream matches :meth:`run`'s exactly.
        """
        guard_ctx = (
            dispatch.steady_state_guard()
            if num_calls in self._superchunk_warm
            else nullcontext()
        )
        with guard_ctx:
            with step_marker(0):
                state, carry, stacked = self.train_superchunk(
                    state, carry, key, num_calls
                )
            if progress is not None:
                progress.bump()
            host = get_metrics(stacked)  # ONE batched transfer, all chunks
        self._superchunk_warm.add(num_calls)
        frames_per_call = (
            self.unroll_length * self.venv.num_envs * self.iters_per_call
        )
        reg = telemetry.get_registry() if instrument else None
        metrics: Dict[str, float] = {}
        nonfinite_chunks = 0
        for i in range(num_calls):
            m = {k: float(v[i]) for k, v in host.items()}
            if reg is not None:
                telemetry.observe_train_metrics(m)
            if m.get("skipped_steps", 0.0) > 0.0:
                nonfinite_chunks += 1
            m["episodes"] = m.pop("episode_count_sum")
            m["return_mean"] = m.pop("episode_return_sum") / max(
                m["episodes"], 1.0
            )
            metrics = m
            if on_metrics is not None:
                on_metrics(i, m)
        if reg is not None:
            # per-superchunk instrument write (chunk-amortized by design)
            reg.meter("rates.chunks_per_s").mark(num_calls)
            reg.meter("rates.fps").mark(frames_per_call * num_calls)
        metrics["chunks_done"] = float(num_calls)
        metrics["nonfinite_chunks"] = float(nonfinite_chunks)
        return state, carry, metrics

    # ------------------------------------------------------------------
    def train_chunk(
        self, state: ImpalaTrainState, carry: ActorCarry, key: jax.Array
    ) -> Tuple[ImpalaTrainState, ActorCarry, Dict]:
        """One fused dispatch (``iters_per_call`` env-unroll+update iterations).

        The public single-dispatch entry point; ``run``/``run_until`` are
        loops over this.  Inputs are donated — do not reuse ``state``/``carry``
        after the call.
        """
        return self._train_many(state, carry, key)

    def run_until(
        self,
        state: ImpalaTrainState,
        carry: ActorCarry,
        key: jax.Array,
        threshold: float,
        max_calls: int,
        on_metrics: Optional[Callable[[int, float, Dict[str, float]], None]] = None,
        chunks_in_flight: int = 2,
        progress=None,
        should_stop: Optional[Callable[[], bool]] = None,
        instrument: bool = True,
    ) -> Tuple[ImpalaTrainState, ActorCarry, Dict[str, float]]:
        """Drive fused chunks until the *windowed* mean episode return (over
        episodes completed since the previous chunk) reaches ``threshold``,
        or ``max_calls`` chunks elapse.

        ``progress``: a supervisor ``ProgressCounter`` bumped once per
        dispatched chunk (stall-watchdog liveness for the host driver).
        ``should_stop``: polled before each dispatch; True stops cleanly
        with in-flight chunks drained and counted — the preemption-guard
        safe point for the fused path.

        ``chunks_in_flight`` chunks stay dispatched ahead of the host's
        metric reads (one batched device->host transfer per chunk), so the
        threshold check and ``on_metrics`` lag the device by
        ``chunks_in_flight - 1`` chunks instead of stalling it; a hit stops
        further dispatch but the chunks already in flight still land (they
        are counted in ``frames`` and folded into the returned state).  The
        metric STREAM — chunk order, values, and the frame counts passed to
        ``on_metrics(frames, windowed_return, chunk_metrics)`` — is
        identical for every ``chunks_in_flight``; 1 is fully synchronous.
        Returns ``(state, carry, summary)`` with summary keys
        ``windowed_return`` / ``frames`` / ``hit``.
        """
        frames_per_call = self.unroll_length * self.venv.num_envs * self.iters_per_call
        init = get_metrics(
            {"s": jnp.sum(carry.return_sum), "c": jnp.sum(carry.episode_count)}
        )
        prev_sum, prev_cnt = init["s"], init["c"]
        windowed = float("nan")
        frames = 0
        hit = False
        nonfinite_chunks = 0
        pipe = MetricsPipeline(depth=chunks_in_flight)
        # instrument=False (args.telemetry_interval_s <= 0) compiles the
        # per-chunk registry feed out of the driver entirely — no meter
        # objects, no observe calls, not even a skipped branch per chunk
        reg = telemetry.get_registry() if instrument else None
        _chunk_meter = reg.meter("rates.chunks_per_s") if instrument else None
        _fps_meter = reg.meter("rates.fps") if instrument else None

        def consume(ready) -> None:
            nonlocal windowed, prev_sum, prev_cnt, hit, nonfinite_chunks
            for i, m in ready:
                # host-side registry feed (m is already host floats via the
                # pipeline's one batched transfer — no extra device traffic)
                if instrument:
                    telemetry.observe_train_metrics(m)
                    _chunk_meter.mark()
                    _fps_meter.mark(frames_per_call)
                if m.get("skipped_steps", 0.0) > 0.0:
                    # guarded learn skipped >= 1 non-finite update this chunk
                    nonfinite_chunks += 1
                s = m["episode_return_sum"]
                c = m["episode_count_sum"]
                if c > prev_cnt:
                    windowed = (s - prev_sum) / (c - prev_cnt)
                    prev_sum, prev_cnt = s, c
                if on_metrics is not None:
                    on_metrics((i + 1) * frames_per_call, windowed, dict(m))
                if windowed >= threshold:
                    hit = True

        for i in range(max_calls):
            if should_stop is not None and should_stop():
                break
            # steady state (chunk 1+) runs under the transfer guard: the
            # only host transfer allowed per chunk is get_metrics' explicit
            # batched device_get; a stray implicit sync raises at its line.
            # Chunk 0 is exempt — tracing/compilation may place constants.
            with dispatch.steady_state_guard() if i > 0 else nullcontext():
                # step_marker: per-chunk device-trace alignment (a cheap
                # profiler annotation — a no-op unless a trace is active)
                with step_marker(i):
                    key, sub = jax.random.split(key)
                    state, carry, m = self.train_chunk(state, carry, sub)
                frames += frames_per_call
                if progress is not None:
                    progress.bump()
                # the sums ride the fused metrics — no extra host dispatches
                consume(pipe.push(i, m))
            if hit:
                break
        consume(pipe.drain())
        summary = {
            "windowed_return": windowed,
            "frames": float(frames),
            "hit": hit,
            "nonfinite_chunks": float(nonfinite_chunks),
        }
        return state, carry, summary

    # ------------------------------------------------------------------
    def run(
        self,
        state: ImpalaTrainState,
        carry: ActorCarry,
        key: jax.Array,
        num_calls: int,
        on_metrics: Optional[Callable[[int, Dict[str, float]], None]] = None,
        chunks_in_flight: int = 2,
        progress=None,
        should_stop: Optional[Callable[[], bool]] = None,
        instrument: bool = True,
    ) -> Tuple[ImpalaTrainState, ActorCarry, Dict[str, float]]:
        """Drive ``num_calls`` fused mega-steps; one host dispatch each.

        Each chunk's metric dict is read back with ONE batched transfer,
        lagging dispatch by ``chunks_in_flight - 1`` chunks so the device
        never idles waiting on the host (``chunks_in_flight=1`` restores
        the synchronous read-after-every-chunk path).  ``on_metrics(i,
        metrics)`` still fires once per chunk, in order.

        ``progress``/``should_stop``: supervision hooks (see ``run_until``).
        The returned metrics carry ``chunks_done`` — with an early
        ``should_stop`` the frame count is ``chunks_done *
        frames_per_call``, which the preemption checkpoint must record
        instead of the requested budget.
        """
        metrics: Dict[str, float] = {}
        nonfinite_chunks = 0
        pipe = MetricsPipeline(depth=chunks_in_flight)
        frames_per_call = self.unroll_length * self.venv.num_envs * self.iters_per_call
        # see run_until: instrument=False compiles the registry feed out
        reg = telemetry.get_registry() if instrument else None
        _chunk_meter = reg.meter("rates.chunks_per_s") if instrument else None
        _fps_meter = reg.meter("rates.fps") if instrument else None

        def consume(ready) -> None:
            nonlocal metrics, nonfinite_chunks
            for i, host_m in ready:
                m = dict(host_m)
                if instrument:
                    telemetry.observe_train_metrics(m)
                    _chunk_meter.mark()
                    _fps_meter.mark(frames_per_call)
                if m.get("skipped_steps", 0.0) > 0.0:
                    nonfinite_chunks += 1
                m["episodes"] = m.pop("episode_count_sum")
                m["return_mean"] = m.pop("episode_return_sum") / max(
                    m["episodes"], 1.0
                )
                metrics = m
                if on_metrics is not None:
                    on_metrics(i, m)

        chunks_done = 0
        for i in range(num_calls):
            if should_stop is not None and should_stop():
                break
            # steady-state transfer guard (see run_until): implicit host
            # syncs raise; get_metrics' one explicit batched get passes
            with dispatch.steady_state_guard() if i > 0 else nullcontext():
                # per-chunk trace step (no-op without an active trace)
                with step_marker(i):
                    key, sub = jax.random.split(key)
                    state, carry, dev_metrics = self.train_chunk(state, carry, sub)
                chunks_done += 1
                if progress is not None:
                    progress.bump()
                consume(pipe.push(i, dev_metrics))
        consume(pipe.drain())
        jax.block_until_ready(state.params)
        metrics["chunks_done"] = float(chunks_done)
        metrics["nonfinite_chunks"] = float(nonfinite_chunks)
        return state, carry, metrics
