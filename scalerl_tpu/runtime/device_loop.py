"""Fully-fused on-device actor-learner loop (the flagship throughput path).

Replaces the reference's process zoo — actor processes doing per-step CPU
inference + queue hand-off + learner batching (``impala_atari.py:153-268``)
— with ONE XLA program per training iteration: env step, policy forward,
action sample, trajectory collection (``lax.scan`` over the unroll), V-trace
learner update.  Multiple iterations are themselves ``lax.scan``-ed so the
host dispatches once per ``iters_per_call`` updates — essential under the
axon tunnel where each host->device dispatch costs ~50-100 ms, and the reason
this path reaches orders of magnitude more env-frames/sec than the
reference's architecture on the same chip count.

Works with any ``JaxVecEnv`` (device-native env) and any model implementing
the recurrent-policy signature (``models/policy.py``).  Within a fused
iteration the behavior policy equals the target policy (V-trace rhos = 1,
the on-policy special case); the *host* actor plane
(``trainer/actor_learner.py``) exercises true off-policy lag.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from scalerl_tpu.agents.impala import ImpalaTrainState
from scalerl_tpu.data.trajectory import Trajectory
from scalerl_tpu.envs.jax_envs.base import JaxVecEnv


class ActorCarry(NamedTuple):
    """Per-env actor state threaded across rollout chunks."""

    env_state: Any
    obs: jnp.ndarray  # [B, ...]
    last_action: jnp.ndarray  # [B]
    reward: jnp.ndarray  # [B]
    done: jnp.ndarray  # [B]
    core_state: Any  # model recurrent state
    episode_return: jnp.ndarray  # [B] running return accumulator
    return_sum: jnp.ndarray  # scalar: sum of completed-episode returns
    episode_count: jnp.ndarray  # scalar: completed episodes


class DeviceActorLearnerLoop:
    def __init__(
        self,
        model,
        venv: JaxVecEnv,
        learn_fn: Callable[[ImpalaTrainState, Trajectory], Tuple[ImpalaTrainState, Dict]],
        unroll_length: int,
        iters_per_call: int = 10,
    ) -> None:
        self.model = model
        self.venv = venv
        self.learn_fn = learn_fn
        self.unroll_length = unroll_length
        self.iters_per_call = iters_per_call
        self._train_many = jax.jit(
            partial(self._train_many_impl), donate_argnums=(0, 1)
        )

    # ------------------------------------------------------------------
    def init_carry(self, key: jax.Array) -> ActorCarry:
        B = self.venv.num_envs
        env_state, obs = self.venv.reset(key)
        return ActorCarry(
            env_state=env_state,
            obs=obs,
            last_action=jnp.zeros(B, jnp.int32),
            reward=jnp.zeros(B, jnp.float32),
            done=jnp.ones(B, jnp.bool_),
            core_state=self.model.initial_state(B),
            episode_return=jnp.zeros(B, jnp.float32),
            return_sum=jnp.zeros((), jnp.float32),
            episode_count=jnp.zeros((), jnp.float32),
        )

    # ------------------------------------------------------------------
    def _unroll(self, params, carry: ActorCarry, key: jax.Array):
        """Collect one [T+1, B] trajectory chunk; row T's logits are unused
        by the learner (behavior_logits[:-1]) and left zero."""
        core0 = carry.core_state

        def step(c: ActorCarry, k):
            out, new_core = self.model.apply(
                params, c.obs[None], c.last_action[None], c.reward[None],
                c.done[None], c.core_state,
            )
            logits = out.policy_logits[0]
            k_act, k_env = jax.random.split(k)
            action = jax.random.categorical(k_act, logits, axis=-1)
            env_state, next_obs, reward, done = self.venv.step(
                c.env_state, action, k_env
            )
            row = (c.obs, c.last_action, c.reward, c.done, logits)
            ep_ret = c.episode_return + reward
            new_c = ActorCarry(
                env_state=env_state,
                obs=next_obs,
                last_action=action,
                reward=reward,
                done=done,
                core_state=new_core,
                episode_return=jnp.where(done, 0.0, ep_ret),
                return_sum=c.return_sum + jnp.sum(jnp.where(done, ep_ret, 0.0)),
                episode_count=c.episode_count + jnp.sum(done),
            )
            return new_c, row

        keys = jax.random.split(key, self.unroll_length)
        carry, rows = jax.lax.scan(step, carry, keys)
        obs_rows, la_rows, rew_rows, done_rows, logit_rows = rows

        # final row T from the post-scan carry (logits zero: unused)
        traj = Trajectory(
            obs=jnp.concatenate([obs_rows, carry.obs[None]], axis=0),
            action=jnp.concatenate([la_rows, carry.last_action[None]], axis=0),
            reward=jnp.concatenate([rew_rows, carry.reward[None]], axis=0),
            done=jnp.concatenate([done_rows, carry.done[None]], axis=0),
            logits=jnp.concatenate(
                [logit_rows, jnp.zeros_like(logit_rows[:1])], axis=0
            ),
            core_state=core0,
        )
        return carry, traj

    # ------------------------------------------------------------------
    def _train_many_impl(self, state: ImpalaTrainState, carry: ActorCarry, key):
        def one_iter(sc, k):
            state, carry = sc
            k_roll, _ = jax.random.split(k)
            carry, traj = self._unroll(state.params, carry, k_roll)
            state, metrics = self.learn_fn(state, traj)
            return (state, carry), metrics

        (state, carry), metrics = jax.lax.scan(
            one_iter, (state, carry), jax.random.split(key, self.iters_per_call)
        )
        mean_metrics = {k: jnp.mean(v) for k, v in metrics.items()}
        return state, carry, mean_metrics

    # ------------------------------------------------------------------
    def train_chunk(
        self, state: ImpalaTrainState, carry: ActorCarry, key: jax.Array
    ) -> Tuple[ImpalaTrainState, ActorCarry, Dict]:
        """One fused dispatch (``iters_per_call`` env-unroll+update iterations).

        The public single-dispatch entry point; ``run``/``run_until`` are
        loops over this.  Inputs are donated — do not reuse ``state``/``carry``
        after the call.
        """
        return self._train_many(state, carry, key)

    def run_until(
        self,
        state: ImpalaTrainState,
        carry: ActorCarry,
        key: jax.Array,
        threshold: float,
        max_calls: int,
        on_metrics: Optional[Callable[[int, float, Dict[str, float]], None]] = None,
    ) -> Tuple[ImpalaTrainState, ActorCarry, Dict[str, float]]:
        """Drive fused chunks until the *windowed* mean episode return (over
        episodes completed since the previous chunk) reaches ``threshold``,
        or ``max_calls`` chunks elapse.

        ``on_metrics(frames, windowed_return, device_metrics)`` fires after
        every chunk.  Returns ``(state, carry, summary)`` with summary keys
        ``windowed_return`` / ``frames`` / ``hit``.
        """
        frames_per_call = self.unroll_length * self.venv.num_envs * self.iters_per_call
        prev_sum = float(carry.return_sum)
        prev_cnt = float(carry.episode_count)
        windowed = float("nan")
        frames = 0
        hit = False
        for _ in range(max_calls):
            key, sub = jax.random.split(key)
            state, carry, m = self.train_chunk(state, carry, sub)
            frames += frames_per_call
            s, c = float(carry.return_sum), float(carry.episode_count)
            if c > prev_cnt:
                windowed = (s - prev_sum) / (c - prev_cnt)
                prev_sum, prev_cnt = s, c
            if on_metrics is not None:
                on_metrics(frames, windowed, {k: float(v) for k, v in m.items()})
            if windowed >= threshold:
                hit = True
                break
        summary = {"windowed_return": windowed, "frames": float(frames), "hit": hit}
        return state, carry, summary

    # ------------------------------------------------------------------
    def run(
        self,
        state: ImpalaTrainState,
        carry: ActorCarry,
        key: jax.Array,
        num_calls: int,
        on_metrics: Optional[Callable[[int, Dict[str, float]], None]] = None,
    ) -> Tuple[ImpalaTrainState, ActorCarry, Dict[str, float]]:
        """Drive ``num_calls`` fused mega-steps; one host dispatch each."""
        metrics: Dict[str, float] = {}
        for i in range(num_calls):
            key, sub = jax.random.split(key)
            state, carry, dev_metrics = self.train_chunk(state, carry, sub)
            if on_metrics is not None:
                metrics = {k: float(v) for k, v in dev_metrics.items()}
                metrics["episodes"] = float(carry.episode_count)
                metrics["return_mean"] = float(
                    carry.return_sum / jnp.maximum(carry.episode_count, 1.0)
                )
                on_metrics(i, metrics)
        jax.block_until_ready(state.params)
        if not metrics:
            metrics = {
                "episodes": float(carry.episode_count),
                "return_mean": float(carry.return_sum / max(float(carry.episode_count), 1.0)),
            }
        return state, carry, metrics
