"""Pipelined host dispatch: batched metric transfer + K chunks in flight.

The fused drivers (``runtime/device_loop.py``, ``trainer/r2d2_device.py``)
and the host-plane learners all end each chunk with a metric dict of device
scalars.  Consuming it with per-key ``float(v)`` reads costs one blocking
device->host round trip PER KEY (~10 per chunk) — under the axon tunnel's
~50-100 ms round-trip latency that serializes the host against the device
and defeats JAX's async dispatch.  Two primitives fix both halves:

- :func:`get_metrics` — materialize a whole metric pytree with ONE batched
  device->host transfer (scalar leaves are stacked into a single device
  vector first, so even the tunnel pays exactly one round trip).
- :class:`MetricsPipeline` — a bounded deque of pending metric payloads so
  the driver dispatches chunk ``i+1`` (or ``i+K-1``) BEFORE reading chunk
  ``i``'s metrics.  Reading a K-chunks-old payload never stalls the device:
  by the time the host blocks on it, the device finished it long ago and
  is already executing the chunks dispatched after it.  ``depth=1`` is the
  fully synchronous path (read-after-every-dispatch), so callers expose
  one ``chunks_in_flight`` knob covering both.

Metric payloads are loop OUTPUTS (never donated), so holding device
references to K of them while later chunks run is safe by construction.
"""

from __future__ import annotations

import os
from collections import deque
from contextlib import contextmanager, nullcontext
from typing import Any, Callable, Deque, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Module-level seam: tests monkeypatch this to count host transfers.
_device_get = jax.device_get


@contextmanager
def _host_boundary_disallow():
    # both directions of the HOST boundary; device->device stays allowed
    # (resharding a scalar argument onto a mesh is legitimate and free of
    # host involvement)
    with jax.transfer_guard_host_to_device("disallow"), \
            jax.transfer_guard_device_to_host("disallow"):
        yield


def steady_state_guard():
    """Transfer-guard context for the fused drivers' steady state.

    Arms ``transfer_guard("disallow")`` on both directions of the *host
    boundary* around a steady-state chunk (dispatch + pipelined metric
    read): *implicit* transfers — a stray ``float()``/``np.asarray()`` on a
    device value, a Python scalar or numpy array leaking into a jitted
    call — raise immediately, while the one *explicit* batched
    ``jax.device_get`` in :func:`get_metrics` is still allowed.
    Device->device traffic (e.g. replicating a scalar argument onto a
    mesh) never touches the host and stays allowed.  This is the runtime
    enforcement of graftlint's JG001: the dispatch pipeline performs
    exactly one (explicit) host transfer per chunk, and anything else is a
    bug at the line that did it.

    Backend note: the CPU backend's device buffers are host memory, so the
    device->host direction never registers as a transfer there — on CPU the
    guard catches stray host->device traffic only; on TPU/GPU it catches
    both directions.  Escape hatch: ``SCALERL_NO_TRANSFER_GUARD=1``.

    Drivers skip the guard for a branch's FIRST call: tracing/compilation
    may legitimately materialize host constants onto the device.
    """
    if os.environ.get("SCALERL_NO_TRANSFER_GUARD") == "1":
        return nullcontext()
    return _host_boundary_disallow()


def get_metrics(metrics: Any) -> Any:
    """Materialize a metric pytree with ONE batched device->host transfer.

    Scalar (``size == 1``) device leaves — the metric-dict common case —
    are stacked into one float32 device vector and fetched with a single
    ``jax.device_get``; they come back as Python floats, matching the
    ``{k: float(v)}`` idiom this replaces.  Mixed pytrees (e.g. a PER
    ``td_abs`` vector riding along) fall back to one ``device_get`` of the
    device leaves together; non-scalar leaves return as numpy arrays.
    Host-side numeric leaves pass through as floats, untouched otherwise.
    """
    leaves, treedef = jax.tree_util.tree_flatten(metrics)
    idx = [i for i, l in enumerate(leaves) if isinstance(l, jax.Array)]
    if idx:
        if all(leaves[i].size == 1 for i in idx):
            stacked = jnp.stack(
                [leaves[i].astype(jnp.float32).reshape(()) for i in idx]
            )
            host = np.asarray(_device_get(stacked))
            fetched: List[Any] = [float(host[j]) for j in range(len(idx))]
        else:
            host = _device_get([leaves[i] for i in idx])
            fetched = [
                float(v) if getattr(v, "ndim", 1) == 0 else np.asarray(v)
                for v in host
            ]
        for i, v in zip(idx, fetched):
            leaves[i] = v
    leaves = [
        float(l) if isinstance(l, (int, float, np.floating, np.integer)) else l
        for l in leaves
    ]
    return jax.tree_util.tree_unflatten(treedef, leaves)


class MetricsPipeline:
    """Bounded deque of in-flight metric payloads (one per dispatched chunk).

    ``depth`` = chunks in flight: :meth:`push` enqueues the just-dispatched
    chunk's device metrics and pops (materializing via :func:`get_metrics`,
    one batched transfer each) only once ``depth`` payloads are pending —
    so the newest ``depth - 1`` chunks are always still in flight when the
    host blocks on an older one.  ``depth=1`` reads back synchronously on
    every push.  :attr:`transfers` counts batched gets performed (the
    per-chunk-transfer invariant tests assert on).
    """

    def __init__(self, depth: int = 2) -> None:
        if depth < 1:
            raise ValueError(f"pipeline depth must be >= 1, got {depth}")
        self.depth = depth
        self.transfers = 0
        self._pending: Deque[Tuple[Any, Any]] = deque()

    def __len__(self) -> int:
        return len(self._pending)

    def _materialize(self, item: Tuple[Any, Any]) -> Tuple[Any, Any]:
        tag, payload = item
        self.transfers += 1
        # registry mirror: host-side int bump only (the transfer itself is
        # the one sanctioned batched get inside get_metrics)
        from scalerl_tpu.runtime import telemetry

        telemetry.get_registry().counter("dispatch.batched_transfers").inc()
        return tag, get_metrics(payload)

    def push(self, tag: Any, payload: Any) -> List[Tuple[Any, Any]]:
        """Enqueue a chunk's device metrics; return newly ready host ones.

        Returns ``[(tag, host_metrics), ...]`` for every payload that fell
        out of the in-flight window (oldest first) — empty while the
        pipeline is still filling.
        """
        self._pending.append((tag, payload))
        ready: List[Tuple[Any, Any]] = []
        while len(self._pending) >= self.depth:
            ready.append(self._materialize(self._pending.popleft()))
        return ready

    def drain(self) -> List[Tuple[Any, Any]]:
        """Materialize every pending payload (oldest first) and empty the
        pipeline.  Blocks until the last dispatched chunk finishes on
        device — the end-of-run synchronization point."""
        ready = [self._materialize(item) for item in self._pending]
        self._pending.clear()
        return ready


def pipelined_drive(
    dispatch: Callable[[int], Any],
    num_calls: int,
    on_ready: Optional[Callable[[int, Any], None]] = None,
    depth: int = 2,
    stop: Optional[Callable[[], bool]] = None,
) -> int:
    """Drive ``dispatch(i) -> device_metrics`` for up to ``num_calls``
    chunks with ``depth`` in flight; ``on_ready(i, host_metrics)`` fires in
    chunk order (lagging dispatch by ``depth - 1``).  ``stop()`` is checked
    after each materialization batch — when it returns True no further
    chunks are dispatched, but everything already in flight is drained (the
    state those chunks produced exists regardless).  Returns the number of
    chunks dispatched.
    """
    pipe = MetricsPipeline(depth=depth)

    def consume(ready) -> bool:
        for tag, host in ready:
            if on_ready is not None:
                on_ready(tag, host)
        return bool(stop is not None and stop())

    dispatched = 0
    for i in range(num_calls):
        payload = dispatch(i)
        dispatched += 1
        if consume(pipe.push(i, payload)):
            break
    consume(pipe.drain())
    return dispatched
