"""Cross-process rollout slot ring over shared memory.

The process-grade big brother of :class:`~scalerl_tpu.runtime.rollout_queue.
RolloutQueue` (which is thread-scoped): actor *processes* acquire fixed-size
trajectory slots, fill them through zero-copy numpy views, and commit; the
learner drains committed slots and recycles them.  Index handoff goes
through the lock-free C++ ring (``csrc/shm_ring.cpp``) when the native
toolchain is present, else through ``multiprocessing`` queues — the payload
path (shared-memory numpy slots) is identical either way.

Parity target: the reference's shared-tensor pool + SimpleQueue index cycle
(``scalerl/impala/impala_atari.py:122-151,416-437``), minus the per-handoff
pickle and with multi-producer/multi-consumer safety.
"""

from __future__ import annotations

import ctypes
import multiprocessing as mp
import struct
import time
import zlib
from multiprocessing import shared_memory
from typing import Dict, List, Mapping, Optional, Tuple

import numpy as np

from scalerl_tpu.native import load_ring_lib
from scalerl_tpu.runtime import telemetry
from scalerl_tpu.runtime.chaos import active as chaos_active
from scalerl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_ALIGN = 64
# per-slot integrity words (trailing, inside the slot stride): CRC32 of the
# payload bytes + a monotonic per-slot commit sequence number
_INTG = struct.Struct("<II")


def _aligned(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


class SlotSpec:
    """Field layout of one trajectory slot: name -> (shape, dtype)."""

    def __init__(self, fields: Mapping[str, Tuple[Tuple[int, ...], np.dtype]]):
        self.fields: Dict[str, Tuple[Tuple[int, ...], np.dtype]] = {
            k: (tuple(s), np.dtype(d)) for k, (s, d) in fields.items()
        }
        self.offsets: Dict[str, int] = {}
        off = 0
        for name, (shape, dtype) in self.fields.items():
            self.offsets[name] = off
            off += _aligned(int(np.prod(shape)) * dtype.itemsize)
        self.slot_bytes = _aligned(off)

    def views(self, buf: memoryview) -> Dict[str, np.ndarray]:
        out = {}
        for name, (shape, dtype) in self.fields.items():
            start = self.offsets[name]
            n = int(np.prod(shape)) * dtype.itemsize
            out[name] = np.frombuffer(
                buf[start:start + n], dtype=dtype
            ).reshape(shape)
        return out


class ShmRolloutRing:
    """MPMC slot ring shared by actor processes and the learner."""

    def __init__(
        self,
        spec: SlotSpec,
        num_slots: int,
        use_native: Optional[bool] = None,
        integrity: bool = True,
    ) -> None:
        """``integrity``: reserve per-slot sequence+checksum words.  The
        writer stamps a CRC32 of the payload at ``commit``; readers verify
        (``verify_slot`` / ``pop_full_verified``) so a torn write — a
        producer SIGKILLed mid-``memcpy``, a scribbler process — is
        *detected* instead of silently training on garbage."""
        if num_slots < 2:
            raise ValueError(f"num_slots must be >= 2, got {num_slots}")
        self.spec = spec
        self.num_slots = num_slots
        self.integrity = bool(integrity)
        self._slot_stride = spec.slot_bytes + (_ALIGN if self.integrity else 0)
        self.torn_reads = 0  # per-process detection counter (learner-side)
        lib = load_ring_lib() if use_native in (None, True) else None
        if use_native is True and lib is None:
            raise RuntimeError("native ring requested but unavailable")
        self.native = lib is not None
        ctrl_bytes = (
            int(lib.srl_ring_bytes(num_slots)) if self.native else 0
        )
        self._ctrl_bytes = _aligned(ctrl_bytes)
        total = self._ctrl_bytes + num_slots * self._slot_stride
        self.shm = shared_memory.SharedMemory(create=True, size=total)
        self._owner = True
        # telemetry plane: occupancy + torn_reads ride the merged snapshot
        # (snapshot-time binding — zero hot-path cost; a later ring simply
        # shadows an earlier one in the same process; weakref so the
        # registry never pins a torn-down ring's shm mapping alive)
        import weakref

        ring_ref = weakref.ref(self)

        def _ring_stats() -> Dict[str, int]:
            ring = ring_ref()
            return ring.stats() if ring is not None else {"gone": 1}

        telemetry.get_registry().bind("ring", _ring_stats)
        self._base_obj = None  # cached ctypes buffer export (see _base_ptr)
        self._base_addr: Optional[int] = None
        if self.native:
            self.shm.buf[:self._ctrl_bytes] = b"\x00" * self._ctrl_bytes
            rc = lib.srl_ring_init(self._base_ptr(), num_slots)
            assert rc == 0
            self._free = self._full = None
        else:
            # spawn context: its SemLocks may be shared with BOTH spawn
            # children (pickled) and fork children (inherited), whereas
            # fork-context SemLocks raise when pickled into a spawn child —
            # and the consumers (trainer/parallel_dqn.py) spawn
            ctx = mp.get_context("spawn")
            self._free = ctx.Queue()
            self._full = ctx.Queue()
            for i in range(num_slots):
                self._free.put(i)
            self._closed = ctx.Event()

    # -- pickling: children re-attach by shm name ----------------------
    def __getstate__(self):
        state = self.__dict__.copy()
        state["shm"] = None
        state["_shm_name"] = self.shm.name
        state["_owner"] = False
        state["_base_obj"] = None
        state["_base_addr"] = None
        return state

    def __setstate__(self, state):
        name = state.pop("_shm_name")
        self.__dict__.update(state)
        self.shm = shared_memory.SharedMemory(name=name)

    def _base_ptr(self) -> int:
        # One cached buffer export per process: creating a fresh
        # ``from_buffer`` view on every call leaks exports that keep the
        # mapping pinned ("cannot close exported pointers exist" during
        # unlink).  detach() drops the cached object before shm.close().
        if self._base_addr is None:
            self._base_obj = ctypes.c_char.from_buffer(self.shm.buf)
            self._base_addr = ctypes.addressof(self._base_obj)
        return self._base_addr

    def _lib(self):
        lib = load_ring_lib()
        assert lib is not None, "native lib vanished across processes"
        return lib

    def _fallback_get(self, q, timeout: Optional[float]) -> Optional[int]:
        """Queue get that also wakes on close() (mirrors native rc=-2)."""
        import queue as _q
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        while not self._closed.is_set():
            step = 0.1
            if deadline is not None:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    return None
                step = min(step, remaining)
            try:
                return q.get(timeout=step)
            except _q.Empty:
                continue
        return None

    # -- actor side ----------------------------------------------------
    def acquire(self, timeout: Optional[float] = None) -> Optional[int]:
        """Free slot index, or None on timeout/closed."""
        if self.native:
            us = -1 if timeout is None else int(timeout * 1e6)
            idx = int(self._lib().srl_ring_acquire(self._base_ptr(), us))
            return idx if idx >= 0 else None
        return self._fallback_get(self._free, timeout)

    def commit(self, idx: int) -> None:
        if self.integrity:
            self._stamp_slot(idx)
        if self.native:
            rc = self._lib().srl_ring_commit(self._base_ptr(), idx)
            if rc != 0:
                raise RuntimeError(f"ring commit failed rc={rc}")
        else:
            self._full.put(idx)

    # -- learner side --------------------------------------------------
    def pop_full(self, timeout: Optional[float] = None) -> Optional[int]:
        if self.native:
            us = -1 if timeout is None else int(timeout * 1e6)
            idx = int(self._lib().srl_ring_pop_full(self._base_ptr(), us))
            return idx if idx >= 0 else None
        return self._fallback_get(self._full, timeout)

    def release(self, idx: int) -> None:
        if self.native:
            rc = self._lib().srl_ring_release(self._base_ptr(), idx)
            if rc != 0:
                raise RuntimeError(f"ring release failed rc={rc}")
        else:
            self._free.put(idx)

    # -- payload -------------------------------------------------------
    def _slot_start(self, idx: int) -> int:
        if not 0 <= idx < self.num_slots:
            raise IndexError(idx)
        return self._ctrl_bytes + idx * self._slot_stride

    def slot(self, idx: int) -> Dict[str, np.ndarray]:
        """Zero-copy field views of slot ``idx`` in shared memory."""
        start = self._slot_start(idx)
        return self.spec.views(self.shm.buf[start:start + self.spec.slot_bytes])

    # -- integrity (torn-write detection) ------------------------------
    def _payload_crc(self, idx: int) -> int:
        start = self._slot_start(idx)
        mv = self.shm.buf[start:start + self.spec.slot_bytes]
        try:
            return zlib.crc32(mv)
        finally:
            mv.release()  # never leave a lingering buffer export (detach)

    def _stamp_slot(self, idx: int) -> None:
        """Write the integrity words for a filled slot (commit side)."""
        off = self._slot_start(idx) + self.spec.slot_bytes
        _crc_old, seq = _INTG.unpack_from(self.shm.buf, off)
        crc = self._payload_crc(idx)
        _INTG.pack_into(self.shm.buf, off, crc, (seq + 1) & 0xFFFFFFFF)
        inj = chaos_active()
        if inj is not None:
            # tear AFTER the stamp so the reader's verify must catch it
            start = self._slot_start(idx)
            mv = self.shm.buf[start:start + self.spec.slot_bytes]
            try:
                inj.tear_slot(mv, site="shm_ring")
            finally:
                mv.release()

    def verify_slot(self, idx: int) -> bool:
        """Recompute the payload CRC and compare against the commit stamp."""
        if not self.integrity:
            return True
        off = self._slot_start(idx) + self.spec.slot_bytes
        crc, _seq = _INTG.unpack_from(self.shm.buf, off)
        return crc == self._payload_crc(idx)

    def slot_seq(self, idx: int) -> int:
        """Commit sequence number of slot ``idx`` (0 = never committed)."""
        if not self.integrity:
            return 0
        off = self._slot_start(idx) + self.spec.slot_bytes
        return _INTG.unpack_from(self.shm.buf, off)[1]

    def pop_full_verified(
        self,
        timeout: Optional[float] = None,
        repolls: int = 3,
        repoll_delay_s: float = 0.002,
    ) -> Optional[int]:
        """``pop_full`` + checksum verification.

        A mismatching slot is re-polled ``repolls`` times (a commit-ordering
        race resolves in microseconds; a true torn write never does), then
        counted in ``torn_reads``, released back to the free pool, and the
        next full slot is tried — the learner skips the corrupt payload
        instead of training on it.  Returns None on timeout/close, exactly
        like ``pop_full``.
        """
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            t = (
                None
                if deadline is None
                else max(0.0, deadline - time.monotonic())
            )
            idx = self.pop_full(timeout=t)
            if idx is None:
                return None
            ok = self.verify_slot(idx)
            for _ in range(repolls):
                if ok:
                    break
                time.sleep(repoll_delay_s)
                ok = self.verify_slot(idx)
            if ok:
                return idx
            self.torn_reads += 1
            telemetry.get_registry().counter("ring.torn_reads").inc()
            telemetry.record_event(
                "torn_read", slot=idx, seq=self.slot_seq(idx),
                total=self.torn_reads,
            )
            logger.warning(
                "shm ring: torn/corrupt slot %d detected (seq %d); "
                "released without consuming (%d total)",
                idx, self.slot_seq(idx), self.torn_reads,
            )
            self.release(idx)
            if deadline is not None and time.monotonic() >= deadline:
                return None

    def gather_batch(
        self, idxs: List[int], out: Optional[Dict[str, np.ndarray]] = None
    ) -> Dict[str, np.ndarray]:
        """Stack slots into ``[len(idxs), ...]`` per-field batches (native
        memcpy when the C++ lib is loaded, Python copy loop otherwise)."""
        if out is None:
            out = {
                name: np.empty((len(idxs),) + shape, dtype)
                for name, (shape, dtype) in self.spec.fields.items()
            }
        if self.native and idxs:
            lib = self._lib()
            base = self._base_ptr() + self._ctrl_bytes
            n = len(idxs)
            for name, (shape, dtype) in self.spec.fields.items():
                nbytes = int(np.prod(shape)) * dtype.itemsize
                srcs = (ctypes.c_char_p * n)(
                    *(
                        base + idx * self._slot_stride + self.spec.offsets[name]
                        for idx in idxs
                    )
                )
                dst = out[name]
                assert dst.flags["C_CONTIGUOUS"]
                lib.srl_gather_batch(
                    dst.ctypes.data_as(ctypes.c_char_p), srcs, n, nbytes
                )
            return out
        for b, idx in enumerate(idxs):
            for name, view in self.slot(idx).items():
                out[name][b] = view
        return out

    def stats(self) -> Dict[str, int]:
        """Occupancy snapshot for watchdog stall reports.

        Fallback mode reports approximate free/full depths (qsize is
        advisory); the native ring exposes no depth API, so only slot count
        and the closed flag are reported there — still enough to tell "ring
        closed under us" from "producers wedged".
        """
        out = {
            "slots": self.num_slots,
            "closed": int(self.closed),
            "integrity": int(self.integrity),
            "torn_reads": self.torn_reads,
        }
        if not self.native:
            out["free"] = self._free.qsize()
            out["full"] = self._full.qsize()
        return out

    # -- lifecycle -----------------------------------------------------
    @property
    def closed(self) -> bool:
        """True once any holder called close() — lets pollers distinguish
        shutdown from a timeout (both return None from acquire/pop_full):
        ``while not ring.closed: idx = ring.pop_full(timeout=1.0) ...``"""
        if self.native:
            return bool(self._lib().srl_ring_closed(self._base_ptr()))
        return self._closed.is_set()

    def close(self) -> None:
        if self.native:
            self._lib().srl_ring_close(self._base_ptr())
        else:
            self._closed.set()

    def __del__(self):
        # drop the cached buffer export before SharedMemory.__del__ runs —
        # GC dict-clear order is unspecified, and if the mmap closes second
        # it raises "cannot close exported pointers exist"
        self._base_obj = None

    def detach(self) -> None:
        """Drop this process's mapping.  Callers must release every
        ``slot()`` view first — live views keep the buffer exported and the
        mapping cannot close (warned, not silently leaked)."""
        import gc

        self._base_obj = None  # release the cached ctypes buffer export
        self._base_addr = None
        try:
            self.shm.close()
        except BufferError:
            gc.collect()  # drop unreferenced slot views, then retry once
            try:
                self.shm.close()
            except BufferError:
                logger.warning(
                    "shm ring %s not closed: slot views still alive "
                    "(release them before detach/unlink)",
                    self.shm.name,
                )
        except OSError:
            pass

    def unlink(self) -> None:
        """Owner-side final cleanup of the shared segment."""
        self.detach()
        if self._owner:
            try:
                self.shm.unlink()
            except FileNotFoundError:
                pass
