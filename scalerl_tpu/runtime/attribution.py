"""Streaming tier-latency attribution: mergeable digests + the online
edge walk (ISSUE 20).

Two problems block "which tier is eating the p99" at traffic scale, and
this module solves both on the host side with zero new round-trips:

1. **Quantiles over unbounded request counts.**  The telemetry
   ``Histogram`` keeps a 256-sample reservoir — fine for step latencies,
   structurally biased for a front door that answers millions of
   requests (the tail is exactly what systematic thinning under-samples).
   :class:`LatencyDigest` is a DDSketch-style log-bucket sketch: fixed
   γ-spaced buckets (γ = (1+α)/(1−α) for a configured relative error α),
   integer counts, and quantiles that are ALWAYS within α of the true
   value regardless of count.  Merging two digests is exact bucket-wise
   integer addition — associative and commutative — so per-host digests
   compose across processes the same way the fleet telemetry piggyback
   composes counters.

2. **Naming the tier.**  PR 13's spans already stamp every boundary a
   request crosses (the context rides the codec-v2 ``trace`` key on
   frames that already flow); ``tools/trace_report.py`` had the exact-sum
   attribution walk, but only OFFLINE over span files.  The walk lives
   here now (:func:`attribute_edges` — trace_report imports it back), and
   :class:`TierLedger` runs it online: subscribed to the tracer's
   finished-span feed, it buffers each sampled trace's spans, decomposes
   the trace the moment its root ends, charges every interval of
   [trace start, trace end] to exactly one named tier (clip overlap, fill
   gaps — per-tier durations sum to the end-to-end latency EXACTLY), and
   feeds per-tier :class:`LatencyDigest` instruments into the telemetry
   registry under ``attr.*``.

jax-free by construction (graftlint HOT-clean: ``runtime/`` is a HOT
package and this module never imports jax) — every stamp is a host
``time.monotonic()`` the span sites already took.  See
docs/OBSERVABILITY.md "Tier attribution & traffic replay".
"""

from __future__ import annotations

import math
import threading
from collections import OrderedDict, deque
from typing import Any, Callable, Deque, Dict, List, Mapping, Optional, Tuple

import numpy as np

# ---------------------------------------------------------------------------
# the mergeable log-bucket digest

# values at or below this are "zero" latencies (clock granularity noise);
# they get their own exact bucket instead of a -inf bucket index
MIN_TRACKABLE = 1e-9


class LatencyDigest:
    """Fixed-γ log-bucket quantile sketch with exact merge.

    Bucket ``i`` covers ``(γ^(i-1), γ^i]``; a value reports back as the
    bucket midpoint-in-log-space ``2·γ^i/(γ+1)``, which is within the
    configured ``relative_error`` of the true value — for EVERY quantile,
    at ANY count.  ``merge`` is bucket-wise integer addition (associative,
    commutative, exact), so digests built on different hosts/threads
    compose without bias, unlike reservoir union.

    Bounded: when the bucket map would exceed ``max_buckets``, the LOWEST
    buckets collapse into one (DDSketch's collapsing strategy) — the upper
    tail, which is what an SLO gate reads, keeps full resolution.
    Thread-safe; ``observe`` is a dict increment under a lock.
    """

    __slots__ = ("relative_error", "gamma", "_log_gamma", "_lock", "count",
                 "sum", "min", "max", "zero_count", "_buckets",
                 "max_buckets", "_collapsed_at")

    def __init__(self, relative_error: float = 0.01,
                 max_buckets: int = 1024) -> None:
        if not 0.0 < relative_error < 1.0:
            raise ValueError(f"relative_error must be in (0, 1): {relative_error}")
        self.relative_error = float(relative_error)
        self.gamma = (1.0 + self.relative_error) / (1.0 - self.relative_error)
        self._log_gamma = math.log(self.gamma)
        self._lock = threading.Lock()
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self.zero_count = 0
        self._buckets: Dict[int, int] = {}
        self.max_buckets = max(int(max_buckets), 8)
        self._collapsed_at: Optional[int] = None  # lowest live index after a collapse

    # -- ingest ----------------------------------------------------------
    def _index(self, v: float) -> int:
        return int(math.ceil(math.log(v) / self._log_gamma - 1e-12))

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            self.min = min(self.min, v)
            self.max = max(self.max, v)
            if v <= MIN_TRACKABLE:
                self.zero_count += 1
                return
            i = self._index(v)
            if self._collapsed_at is not None and i < self._collapsed_at:
                i = self._collapsed_at
            self._buckets[i] = self._buckets.get(i, 0) + 1
            if len(self._buckets) > self.max_buckets:
                self._collapse()

    def observe_array(self, values: Any) -> None:
        """Bulk ingest via one vectorized bucketing pass — the replay
        harness and tests feed millions of samples without a Python loop
        per value."""
        arr = np.asarray(values, dtype=np.float64).ravel()
        if arr.size == 0:
            return
        pos = arr[arr > MIN_TRACKABLE]
        with self._lock:
            self.count += int(arr.size)
            self.sum += float(arr.sum())
            self.min = min(self.min, float(arr.min()))
            self.max = max(self.max, float(arr.max()))
            self.zero_count += int(arr.size - pos.size)
            if pos.size:
                idx = np.ceil(np.log(pos) / self._log_gamma - 1e-12).astype(np.int64)
                if self._collapsed_at is not None:
                    idx = np.maximum(idx, self._collapsed_at)
                uniq, counts = np.unique(idx, return_counts=True)
                for i, c in zip(uniq.tolist(), counts.tolist()):
                    self._buckets[i] = self._buckets.get(i, 0) + c
                if len(self._buckets) > self.max_buckets:
                    self._collapse()

    def _collapse(self) -> None:
        # called under the lock: fold the lowest buckets together until the
        # map fits — tail resolution is untouched
        while len(self._buckets) > self.max_buckets:
            lows = sorted(self._buckets)[:2]
            lo, nxt = lows[0], lows[1]
            self._buckets[nxt] += self._buckets.pop(lo)
            self._collapsed_at = nxt

    # -- read ------------------------------------------------------------
    def _value_of(self, i: int) -> float:
        return 2.0 * math.pow(self.gamma, i) / (self.gamma + 1.0)

    def quantile(self, q: float) -> float:
        q = min(max(float(q), 0.0), 1.0)
        with self._lock:
            if self.count == 0:
                return 0.0
            rank = q * (self.count - 1)
            seen = self.zero_count
            if rank < seen:
                return 0.0
            for i in sorted(self._buckets):
                seen += self._buckets[i]
                if rank < seen:
                    # clamp into the observed range: the bucket midpoint of
                    # the extreme buckets may overshoot min/max slightly
                    return min(max(self._value_of(i), self.min), self.max)
            return self.max

    def read(self) -> Dict[str, float]:
        with self._lock:
            if self.count == 0:
                return {"count": 0.0, "sum": 0.0, "mean": 0.0, "min": 0.0,
                        "max": 0.0, "p50": 0.0, "p95": 0.0, "p99": 0.0,
                        "p999": 0.0}
            out = {
                "count": float(self.count),
                "sum": self.sum,
                "mean": self.sum / self.count,
                "min": self.min,
                "max": self.max,
            }
        out["p50"] = self.quantile(0.50)
        out["p95"] = self.quantile(0.95)
        out["p99"] = self.quantile(0.99)
        out["p999"] = self.quantile(0.999)
        return out

    # -- compose ---------------------------------------------------------
    def merge(self, other: "LatencyDigest") -> "LatencyDigest":
        """Fold ``other`` into self (exact integer addition per bucket).
        Both digests must share γ — merging different error bounds would
        silently degrade the tighter one."""
        if abs(other.gamma - self.gamma) > 1e-12:
            raise ValueError(
                f"digest gamma mismatch: {self.gamma} vs {other.gamma}"
            )
        with other._lock:
            o_count, o_sum = other.count, other.sum
            o_min, o_max = other.min, other.max
            o_zero = other.zero_count
            o_buckets = dict(other._buckets)
        with self._lock:
            self.count += o_count
            self.sum += o_sum
            self.min = min(self.min, o_min)
            self.max = max(self.max, o_max)
            self.zero_count += o_zero
            for i, c in o_buckets.items():
                if self._collapsed_at is not None and i < self._collapsed_at:
                    i = self._collapsed_at
                self._buckets[i] = self._buckets.get(i, 0) + c
            if len(self._buckets) > self.max_buckets:
                self._collapse()
        return self

    def to_wire(self) -> Dict[str, Any]:
        """JSON-safe snapshot (string bucket keys) for the ``_telem``
        piggyback / artifact files; :meth:`from_wire` round-trips it."""
        with self._lock:
            return {
                "relerr": self.relative_error,
                "count": self.count,
                "sum": self.sum,
                "min": self.min if self.count else 0.0,
                "max": self.max if self.count else 0.0,
                "zero": self.zero_count,
                "buckets": {str(i): c for i, c in self._buckets.items()},
            }

    @classmethod
    def from_wire(cls, node: Mapping[str, Any],
                  max_buckets: int = 1024) -> "LatencyDigest":
        d = cls(relative_error=float(node.get("relerr", 0.01)),
                max_buckets=max_buckets)
        d.count = int(node.get("count", 0))
        d.sum = float(node.get("sum", 0.0))
        if d.count:
            d.min = float(node.get("min", math.inf))
            d.max = float(node.get("max", -math.inf))
        d.zero_count = int(node.get("zero", 0))
        d._buckets = {
            int(i): int(c) for i, c in (node.get("buckets") or {}).items()
        }
        return d


# ---------------------------------------------------------------------------
# the exact-sum edge walk (factored out of tools/trace_report.py so it can
# run ONLINE; trace_report imports these back for the offline path)


def build_traces(spans: List[Dict]) -> Dict[str, Dict[str, Any]]:
    """Group span records by trace id; identify each trace's root and
    orphans; stamp the [t0, t1] envelope and ``e2e``."""
    traces: Dict[str, Dict[str, Any]] = {}
    for s in spans:
        traces.setdefault(s["trace"], {"spans": []})["spans"].append(s)
    for t in traces.values():
        ids = {s["span"] for s in t["spans"]}
        t["root"] = next(
            (s for s in t["spans"] if not s.get("parent")), None
        )
        t["orphans"] = [
            s for s in t["spans"]
            if s.get("parent") and s["parent"] not in ids
        ]
        t0 = min(float(s["t0"]) for s in t["spans"])
        t1 = max(float(s["t0"]) + float(s["dur"]) for s in t["spans"])
        if t["root"] is not None:
            t0 = min(t0, float(t["root"]["t0"]))
        t["t0"], t["t1"] = t0, t1
        t["e2e"] = max(t1 - t0, 0.0)
    return traces


def _walk(
    trace: Mapping[str, Any],
    name_of: Callable[[Dict[str, Any]], str],
    gap_of: Callable[[bool, bool], str],
) -> Dict[str, float]:
    """The clip-overlap/fill-gap cursor walk behind
    :func:`attribute_edges`: charge every interval of [start, end] to
    exactly one label; the values sum to ``e2e`` by construction.
    ``gap_of(is_head, is_tail)`` names un-spanned intervals.  Sequential
    (sibling) spans decompose exactly; NESTED spans resolve to the
    earlier-starting (enclosing) one — the traffic plane's nested shape
    uses :func:`attribute_tiers`'s innermost-wins sweep instead."""
    edges: Dict[str, float] = {}
    start, end = trace["t0"], trace["t1"]
    root = trace["root"]
    children = sorted(
        (
            s for s in trace["spans"]
            if root is None or s["span"] != root["span"]
        ),
        key=lambda s: float(s["t0"]),
    )
    cursor = start
    seen_child = False
    for s in children:
        s0 = max(float(s["t0"]), cursor)
        s1 = min(float(s["t0"]) + float(s["dur"]), end)
        if s0 > cursor:
            gap = gap_of(not seen_child, False)
            edges[gap] = edges.get(gap, 0.0) + (s0 - cursor)
            cursor = s0
        if s1 > cursor:
            name = name_of(s)
            edges[name] = edges.get(name, 0.0) + (s1 - cursor)
            cursor = s1
            seen_child = True
    if end > cursor:
        gap = gap_of(not seen_child, True)
        edges[gap] = edges.get(gap, 0.0) + (end - cursor)
    return edges


def attribute_edges(trace: Mapping[str, Any]) -> Dict[str, float]:
    """Charge every interval of [trace start, trace end] to exactly one
    edge (or ``untracked``): walk the child spans in start order, clip to
    the un-attributed suffix, fill holes with ``untracked``.  The values
    sum to ``e2e`` by construction."""
    return _walk(trace, lambda s: s["name"], lambda head, tail: "untracked")


# span-name -> tier name for the traffic plane.  Traffic spans NEST —
# ``router.route`` (admit -> client-bound reply) encloses the replica's
# ``serve.*`` spans — so the tier walk is an INNERMOST-WINS sweep: at
# every instant the latest-starting covering span is the most specific
# stage the request is in.  ``router.dispatch`` therefore collects
# exactly the intervals spent inside the router but NOT inside a replica
# span: admit + routing decision + replica-link send on the way out, and
# the reply hop back through the router on the way in.
TRAFFIC_TIERS = {
    "router.route": "router.dispatch",
    "serve.queue_wait": "replica.queue",
    "serve.flush": "replica.flush",
}
TIER_HEAD_GAP = "client.dispatch"   # trace start -> first tracked edge
TIER_INTERIOR_GAP = "wire.gap"      # holes between tracked edges
TIER_TAIL_GAP = "reply.wire"        # last tracked edge -> trace end

# roots the traffic plane decomposes (bench/replay fire traffic.request;
# a plain RemotePolicyClient.act fires serve.request)
TRAFFIC_ROOTS = ("traffic.request", "serve.request")


def attribute_tiers(
    trace: Mapping[str, Any],
    tiers: Optional[Mapping[str, str]] = None,
) -> Dict[str, float]:
    """Exact-sum tier decomposition for NESTED traffic spans.

    A boundary sweep over the child spans' elementary intervals: each
    interval of [trace start, trace end] is charged to the covering span
    with the LATEST start (innermost wins — the most specific stage;
    :func:`attribute_edges`'s cursor walk would let the enclosing
    ``router.route`` swallow the replica's nested spans).  Edge names map
    through ``tiers``; uncovered intervals are named by POSITION — the
    head gap is the client's dispatch leg (fire -> router admit: client
    queueing + the request wire), interior gaps are untracked
    wire/handoff time, and the tail gap is the reply leg (last tracked
    stamp -> client wakeup).  Values sum to ``e2e`` by construction (the
    elementary intervals partition [start, end])."""
    mapping = TRAFFIC_TIERS if tiers is None else tiers
    start, end = float(trace["t0"]), float(trace["t1"])
    root = trace["root"]
    ivals: List[Tuple[float, float, Dict[str, Any]]] = []
    for s in trace["spans"]:
        if root is not None and s["span"] == root["span"]:
            continue
        s0 = max(float(s["t0"]), start)
        s1 = min(float(s["t0"]) + float(s["dur"]), end)
        if s1 > s0:
            ivals.append((s0, s1, s))
    cuts = sorted({start, end,
                   *(p for s0, s1, _ in ivals for p in (s0, s1))})
    segs: List[Tuple[float, float, Optional[str]]] = []
    for a, b in zip(cuts, cuts[1:]):
        cover = [
            (s0, s1, s) for s0, s1, s in ivals if s0 <= a and s1 >= b
        ]
        if cover:
            # innermost wins: latest start; ties break to the shorter
            # (more deeply nested) span
            _, _, s = max(cover, key=lambda c: (c[0], -(c[1] - c[0])))
            segs.append((a, b, mapping.get(s["name"], s["name"])))
        else:
            segs.append((a, b, None))
    covered = [i for i, (_, _, n) in enumerate(segs) if n is not None]
    first_cov = covered[0] if covered else None
    last_cov = covered[-1] if covered else None
    edges: Dict[str, float] = {}
    for i, (a, b, name) in enumerate(segs):
        if name is None:
            if first_cov is None or i < first_cov:
                name = TIER_HEAD_GAP
            elif i > last_cov:
                name = TIER_TAIL_GAP
            else:
                name = TIER_INTERIOR_GAP
        edges[name] = edges.get(name, 0.0) + (b - a)
    return edges


# ---------------------------------------------------------------------------
# the online ledger


class TierLedger:
    """Online per-trace tier decomposition feeding per-tier digests.

    Subscribe with :meth:`attach` (``tracing.get_tracer().add_listener``):
    every finished-span record is buffered by trace id; the moment a
    trace's ROOT ends (roots end last — the client stamps e2e), the
    buffered spans decompose via :func:`attribute_tiers` and each tier's
    duration lands in its :class:`LatencyDigest`.  Counters:

    - ``decomposed`` — roots fully attributed (the completeness numerator);
    - ``late_spans`` — spans arriving for an already-decomposed trace
      (duplicate replies after first-reply-wins dedup; never re-opened,
      never double-charged);
    - ``orphans`` — buffered traces that never saw a root (evicted at the
      ``max_pending`` cap or counted at :meth:`drain`);
    - ``max_sum_err`` — the largest |Σedges − e2e| ever observed (exactness
      is by construction; this is the float-noise witness).

    ``registry`` binding: ``reg.bind("attr", ledger.tree)`` exposes the
    per-tier quantiles + shares in every telemetry snapshot with zero
    hot-path cost.  Single-process scope: the ledger sees the spans its
    process records (the replay/bench topology records ALL tiers
    in-process); multi-host runs use ``tools/trace_report.py --traffic``
    over the merged span files instead.
    """

    def __init__(
        self,
        roots: Tuple[str, ...] = TRAFFIC_ROOTS,
        relative_error: float = 0.01,
        max_pending: int = 8192,
        tiers: Optional[Mapping[str, str]] = None,
        registry: Any = None,
        bind_as: str = "attr",
    ) -> None:
        self.roots = tuple(roots)
        self.relative_error = float(relative_error)
        self.tiers = dict(TRAFFIC_TIERS if tiers is None else tiers)
        self.max_pending = max(int(max_pending), 1)
        self._lock = threading.Lock()
        # trace id -> buffered span records (insertion-ordered for the
        # bounded evict: the stalest trace goes first)
        self._pending: "OrderedDict[str, List[Dict[str, Any]]]" = OrderedDict()
        # recently decomposed trace ids: late spans (duplicate replies) are
        # counted, never mistaken for orphans or re-decomposed
        self._done: Deque[str] = deque(maxlen=4096)
        self._done_set: set = set()
        self.digests: Dict[str, LatencyDigest] = {}
        self.totals: Dict[str, float] = {}  # exact per-tier attributed seconds
        self.decomposed = 0
        self.orphans = 0
        self.late_spans = 0
        self.max_sum_err = 0.0
        self._e2e = LatencyDigest(relative_error=self.relative_error)
        if registry is not None:
            registry.bind(bind_as, self.tree)

    # -- feed ------------------------------------------------------------
    def attach(self, tracer: Any) -> "TierLedger":
        tracer.add_listener(self.ingest)
        return self

    def detach(self, tracer: Any) -> None:
        tracer.remove_listener(self.ingest)

    def ingest(self, rec: Mapping[str, Any]) -> None:
        """One finished-span record (the tracer-listener entry point).
        Host-side dict work only — never called with device values."""
        tid = rec.get("trace")
        if not tid:
            return
        is_root = not rec.get("parent") and rec.get("name") in self.roots
        with self._lock:
            if tid in self._done_set:
                self.late_spans += 1
                return
            buf = self._pending.get(tid)
            if buf is None:
                if not is_root and rec.get("name") not in self.tiers:
                    # a span family this ledger does not track (seq.*,
                    # snapshot.*): never buffered, never an orphan
                    return
                buf = self._pending[tid] = []
                while len(self._pending) > self.max_pending:
                    # bounded: evict the stalest rootless trace as orphaned
                    self._pending.popitem(last=False)
                    self.orphans += 1
            buf.append(dict(rec))
            if not is_root:
                return
            spans = self._pending.pop(tid)
            self._done.append(tid)
            self._done_set.add(tid)
            while len(self._done_set) > self._done.maxlen:
                # deque evicted its oldest on append; mirror into the set
                self._done_set = set(self._done)
        self._decompose(tid, spans)

    def _decompose(self, tid: str, spans: List[Dict[str, Any]]) -> None:
        trace = build_traces(spans)[tid]
        edges = attribute_tiers(trace, self.tiers)
        e2e = trace["e2e"]
        err = abs(sum(edges.values()) - e2e)
        with self._lock:
            self.decomposed += 1
            self.max_sum_err = max(self.max_sum_err, err)
            for tier, dur in edges.items():
                self.totals[tier] = self.totals.get(tier, 0.0) + dur
                d = self.digests.get(tier)
                if d is None:
                    d = self.digests[tier] = LatencyDigest(
                        relative_error=self.relative_error
                    )
            # digest observes outside self._lock would race tier creation;
            # LatencyDigest has its own lock, and observe below is cheap
        for tier, dur in edges.items():
            self.digests[tier].observe(dur)
        self._e2e.observe(e2e)

    def drain(self) -> int:
        """End of run: count every still-buffered (rootless) trace as
        orphaned and clear.  Returns the number drained."""
        with self._lock:
            n = len(self._pending)
            self.orphans += n
            self._pending.clear()
        return n

    # -- read ------------------------------------------------------------
    def e2e_digest(self) -> LatencyDigest:
        return self._e2e

    def tree(self) -> Dict[str, Any]:
        """The registry binding: per-tier digest summary + exact share,
        plus the ledger counters — evaluated only at snapshot time."""
        with self._lock:
            totals = dict(self.totals)
            tiers = list(self.digests)
            pending = len(self._pending)
        grand = sum(totals.values()) or 1.0
        out: Dict[str, Any] = {
            "decomposed": self.decomposed,
            "orphans": self.orphans,
            "late_spans": self.late_spans,
            "pending": pending,
            "max_sum_err_s": self.max_sum_err,
            "e2e": self._e2e.read(),
        }
        for tier in tiers:
            row = self.digests[tier].read()
            row["share"] = totals.get(tier, 0.0) / grand
            row["total_s"] = totals.get(tier, 0.0)
            out[tier.replace(".", "_")] = row
        return out

    def bottleneck(self) -> Dict[str, Any]:
        """The verdict: the tier with the largest p95 share of the critical
        path, its digest quantiles, and the exact-sum attribution table
        (shares sum to 1 over the decomposed traces)."""
        with self._lock:
            totals = dict(self.totals)
            tiers = list(self.digests)
        grand = sum(totals.values()) or 1.0
        table: Dict[str, Dict[str, float]] = {}
        for tier in tiers:
            d = self.digests[tier]
            table[tier] = {
                "share": round(totals.get(tier, 0.0) / grand, 4),
                "total_s": round(totals.get(tier, 0.0), 6),
                "p50_ms": round(d.quantile(0.50) * 1e3, 3),
                "p95_ms": round(d.quantile(0.95) * 1e3, 3),
                "p99_ms": round(d.quantile(0.99) * 1e3, 3),
                "count": d.count,
            }
        p95_total = sum(row["p95_ms"] for row in table.values()) or 1.0
        for row in table.values():
            row["p95_share"] = round(row["p95_ms"] / p95_total, 4)
        bottleneck = max(
            table, key=lambda t: table[t]["p95_ms"], default=""
        ) if table else ""
        return {
            "bottleneck_tier": bottleneck,
            "tiers": table,
            "decomposed": self.decomposed,
            "orphans": self.orphans,
            "late_spans": self.late_spans,
            "max_sum_err_s": self.max_sum_err,
            "e2e_p50_ms": round(self._e2e.quantile(0.50) * 1e3, 3),
            "e2e_p95_ms": round(self._e2e.quantile(0.95) * 1e3, 3),
            "e2e_p99_ms": round(self._e2e.quantile(0.99) * 1e3, 3),
            "relative_error": self.relative_error,
        }
