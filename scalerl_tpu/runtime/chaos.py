"""Seeded, deterministic fault injection for the data plane.

Podracer-style TPU deployments (arxiv 2104.06272) treat corruption and
preemption as routine events to be *absorbed*; IMPALA (arxiv 1802.01561)
requires the learner to tolerate stale/duplicated actor data by
construction.  This module makes those properties testable: a
:class:`FaultInjector` wraps the fleet transport, the shm rollout ring, and
checkpoint I/O and injects the faults the integrity layer must catch —
dropped/duplicated/bit-flipped/truncated frames, a peer killed mid-frame,
torn shm slot writes, partial checkpoint directories, and NaN/Inf poisoned
training batches.

Everything is driven by a :class:`ChaosPlan` (seed + per-fault rates).
Determinism contract: every fault *kind* at every *site* draws from its own
``numpy`` PCG64 stream seeded by ``(plan.seed, kind, site)``, so the same
seed reproduces the same fault schedule at a site regardless of how other
sites interleave (connection pumps run in threads; a single shared stream
would make schedules scheduling-dependent).

Activation paths:

- tests: ``chaos.install(FaultInjector(ChaosPlan(...)))`` / ``chaos.clear()``;
- soak runs: ``SCALERL_CHAOS=<seed>:<spec>`` — read lazily on first
  :func:`active` call in ANY process (spawned fleet children inherit the
  env var, so the whole tree runs under the same plan).

Spec syntax (see docs/DISTRIBUTED.md "Data integrity & chaos testing"):
comma-separated ``kind=rate`` or ``kind=rate@max_count`` entries plus
options ``minframe=<bytes>`` (frame faults only hit frames at least this
large — scopes chaos to the rollout uplink, not the entry handshake),
``sites=<prefix>[|<prefix>...]`` (frame faults only at matching transport
sites, e.g. ``sites=sock``), ``delay=<seconds>`` (the ``frame_delay``
duration), and ``kills=<n>`` (victims per ``mass_kill`` wave; default half
the live peers).  Example::

    SCALERL_CHAOS="42:frame_bitflip=0.05@3,grad_nan=0.2@10,minframe=1024"

jax-free by design: fleet workers and spawn children import this for
pennies; the NaN *guard* (the thing chaos throws grad faults at) lives in
``parallel/train_step.py``.
"""

from __future__ import annotations

import os
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Tuple

import numpy as np

from scalerl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

ENV_VAR = "SCALERL_CHAOS"

# fault vocabulary: transport frames, shm slots, checkpoints, gradients
FRAME_KINDS = (
    "frame_drop",      # frame silently discarded (lost uplink datagram)
    "frame_dup",       # frame delivered twice (at-least-once resend)
    "frame_bitflip",   # one random bit flipped anywhere in the frame
    "frame_truncate",  # frame cut at a random byte boundary
    "frame_delay",     # frame delayed by plan.delay_s
    "peer_kill",       # half the frame sent, then the connection dies
)
KINDS = FRAME_KINDS + (
    "slot_tear",       # committed shm slot payload bytes scrambled
    "ckpt_partial",    # freshly-written checkpoint left truncated
    "grad_nan",        # NaN planted in the training batch
    "grad_inf",        # Inf planted in the training batch
    "mass_kill",       # K fleet peers SIGTERMed in one window (spot wave)
    "preempt",         # ONE peer SIGTERMed at a site (single spot reclaim)
)

_UNLIMITED = 1 << 62


@dataclass(frozen=True)
class ChaosPlan:
    """Seed + per-fault-kind rates/limits driving a :class:`FaultInjector`."""

    seed: int
    rates: Mapping[str, float] = field(default_factory=dict)
    limits: Mapping[str, int] = field(default_factory=dict)
    min_frame_bytes: int = 0
    site_prefixes: Tuple[str, ...] = ()  # empty = every site
    delay_s: float = 0.05
    # mass_kill victim count per wave (spec option ``kills=<n>``); 0 means
    # "half the live peers, rounded up" — the spot-preemption-wave default
    kill_count: int = 0

    def __post_init__(self) -> None:
        for kind in self.rates:
            if kind not in KINDS:
                raise ValueError(
                    f"unknown chaos fault kind {kind!r}; known: {sorted(KINDS)}"
                )

    @classmethod
    def parse(cls, text: str) -> "ChaosPlan":
        """Parse the ``<seed>:<spec>`` string (the SCALERL_CHAOS format)."""
        head, sep, spec = text.partition(":")
        if not sep:
            raise ValueError(
                f"chaos plan {text!r} must look like '<seed>:<kind>=<rate>,...'"
            )
        try:
            seed = int(head)
        except ValueError as e:
            raise ValueError(f"chaos plan seed {head!r} is not an integer") from e
        rates: Dict[str, float] = {}
        limits: Dict[str, int] = {}
        minframe = 0
        sites: Tuple[str, ...] = ()
        delay_s = 0.05
        kill_count = 0
        for token in filter(None, (t.strip() for t in spec.split(","))):
            key, eq, value = token.partition("=")
            if not eq:
                raise ValueError(f"chaos spec token {token!r} is not key=value")
            if key in KINDS:
                rate_s, at, max_s = value.partition("@")
                rates[key] = float(rate_s)
                if at:
                    limits[key] = int(max_s)
            elif key == "minframe":
                minframe = int(value)
            elif key == "sites":
                sites = tuple(filter(None, value.split("|")))
            elif key == "delay":
                delay_s = float(value)
            elif key == "kills":
                kill_count = int(value)
            else:
                raise ValueError(
                    f"unknown chaos spec key {key!r} (fault kinds: "
                    f"{sorted(KINDS)}; options: minframe, sites, delay, kills)"
                )
        return cls(
            seed=seed,
            rates=rates,
            limits=limits,
            min_frame_bytes=minframe,
            site_prefixes=sites,
            delay_s=delay_s,
            kill_count=kill_count,
        )

    def spec(self) -> str:
        """Round-trip back to the env-var string (for spawning soak children)."""
        parts = []
        for kind, rate in self.rates.items():
            lim = self.limits.get(kind)
            parts.append(f"{kind}={rate}" + (f"@{lim}" if lim is not None else ""))
        if self.min_frame_bytes:
            parts.append(f"minframe={self.min_frame_bytes}")
        if self.site_prefixes:
            parts.append("sites=" + "|".join(self.site_prefixes))
        if self.delay_s != 0.05:
            parts.append(f"delay={self.delay_s}")
        if self.kill_count:
            parts.append(f"kills={self.kill_count}")
        return f"{self.seed}:" + ",".join(parts)


class FaultInjector:
    """Deterministic fault scheduler over independent per-(kind, site) streams.

    Thread-safe: transport pumps, actor threads, and the learner can all
    consult the injector concurrently; each (kind, site) stream is advanced
    under the lock, so per-site schedules stay reproducible.
    """

    def __init__(self, plan: ChaosPlan) -> None:
        self.plan = plan
        self._lock = threading.Lock()
        self._gens: Dict[Tuple[str, str], np.random.Generator] = {}
        self.fired: Dict[str, int] = {k: 0 for k in KINDS}
        self.opportunities: Dict[str, int] = {k: 0 for k in KINDS}

    # -- decision streams ----------------------------------------------
    def _gen(self, kind: str, site: str) -> np.random.Generator:
        key = (kind, site)
        g = self._gens.get(key)
        if g is None:
            # crc32 of the label folds (kind, site) into the seed material
            # deterministically across processes and python hash seeds
            ss = np.random.SeedSequence(
                [self.plan.seed, zlib.crc32(f"{kind}|{site}".encode())]
            )
            g = np.random.Generator(np.random.PCG64(ss))
            self._gens[key] = g
        return g

    def decide(self, kind: str, site: str = "") -> bool:
        """One fault-or-not draw from the (kind, site) stream."""
        rate = self.plan.rates.get(kind, 0.0)
        if rate <= 0.0:
            return False
        with self._lock:
            g = self._gen(kind, site)
            self.opportunities[kind] += 1
            hit = bool(g.random() < rate)  # drawn BEFORE the limit check so
            # the stream position (and thus later decisions) is independent
            # of how many faults already landed
            if hit and self.fired[kind] >= self.plan.limits.get(kind, _UNLIMITED):
                return False
            if hit:
                self.fired[kind] += 1
        if hit:
            # telemetry outside the injector lock: counter + flight event so
            # a post-mortem can line injected faults up against detections
            from scalerl_tpu.runtime import telemetry

            telemetry.get_registry().counter(f"chaos.{kind}").inc()
            telemetry.record_event("chaos_injection", fault=kind, site=site)
        return hit

    def _draw_int(self, kind: str, site: str, n: int) -> int:
        with self._lock:
            return int(self._gen(kind, site).integers(0, n))

    # -- transport frames ----------------------------------------------
    def frame_faults(
        self, data: bytes, site: str
    ) -> Tuple[List[bytes], Optional[bytes]]:
        """Mangle one outgoing frame.

        Returns ``(frames, kill)``: the frames to actually transmit (empty =
        drop, two = duplicate, one mutated = bit-flip/truncate) and, when
        ``kill`` is not None, a *partial* frame body to transmit before the
        sender tears the connection down mid-frame (the peer-kill fault).
        At most one fault per frame, in fixed precedence order.
        """
        if self.plan.site_prefixes and not any(
            site.startswith(p) for p in self.plan.site_prefixes
        ):
            return [data], None
        if len(data) < self.plan.min_frame_bytes:
            return [data], None
        if self.decide("peer_kill", site):
            return [], data[: max(1, len(data) // 2)]
        if self.decide("frame_drop", site):
            return [], None
        if self.decide("frame_dup", site):
            return [data, data], None
        if self.decide("frame_truncate", site):
            return [data[: self._draw_int("frame_truncate", site, len(data))]], None
        if self.decide("frame_bitflip", site):
            pos = self._draw_int("frame_bitflip", site, len(data) * 8)
            mut = bytearray(data)
            mut[pos // 8] ^= 1 << (pos % 8)
            return [bytes(mut)], None
        if self.decide("frame_delay", site):
            time.sleep(self.plan.delay_s)
        return [data], None

    # -- preemption waves ------------------------------------------------
    def mass_kill_victims(self, n_peers: int, site: str = "fleet") -> List[int]:
        """One preemption-wave draw: when the ``mass_kill`` stream fires,
        return the indices (into the caller's list of ``n_peers`` live
        peers) to kill inside this window — ``plan.kill_count`` of them, or
        half the fleet rounded up when unset.  Empty list = no wave.

        The victim choice draws from the same per-(kind, site) stream as
        the fire decision, so the same seed preempts the same peers — the
        autoscaler-backfill chaos tests replay identical waves.
        """
        if n_peers <= 0 or not self.decide("mass_kill", site):
            return []
        k = self.plan.kill_count or max(1, (n_peers + 1) // 2)
        k = min(k, n_peers)
        with self._lock:
            g = self._gen("mass_kill", site)
            victims = sorted(int(i) for i in g.choice(n_peers, size=k, replace=False))
        return victims

    def preempt_victim(self, n_peers: int, site: str = "fleet") -> Optional[int]:
        """One seeded single-preemption draw: when the ``preempt`` stream
        fires, return the index (into the caller's list of ``n_peers`` live
        peers) of the ONE peer to SIGTERM; None = no preemption.

        ``mass_kill`` models a spot *wave*; ``preempt`` models the scheduler
        reclaiming a single worker — the learner, one generation host, or a
        serving replica — mid-run.  Sites distinguish the tier
        (``"learner"``, ``"disagg"``, ``"router"``), and the victim choice
        draws from the same per-(kind, site) stream as the fire decision so
        the same seed preempts the same peer.
        """
        if n_peers <= 0 or not self.decide("preempt", site):
            return None
        with self._lock:
            return int(self._gen("preempt", site).integers(0, n_peers))

    # -- shm ring slots ------------------------------------------------
    def tear_slot(self, payload, site: str = "shm_ring") -> bool:
        """Scramble bytes of a committed slot payload (a torn write).

        ``payload``: a writable buffer (the slot's shared-memory bytes,
        *after* the integrity checksum was written — so the reader's verify
        must fail).  Returns True when the tear happened.
        """
        if not self.decide("slot_tear", site):
            return False
        arr = np.frombuffer(payload, dtype=np.uint8)
        if arr.size:
            with self._lock:
                g = self._gen("slot_tear", site)
                pos = g.integers(0, arr.size, size=max(1, arr.size // 64))
            arr[pos] ^= 0xFF
        return True

    # -- checkpoints ----------------------------------------------------
    def corrupt_checkpoint(self, path: str, site: str = "ckpt") -> bool:
        """Leave the freshly-written checkpoint at ``path`` partial, the way
        a preemption landing mid-flush does: the largest data file is
        truncated to half and the top-level metadata files (the LAST thing
        a checkpointer finalizes) are removed.  Returns True when the
        corruption happened."""
        if not self.decide("ckpt_partial", site):
            return False
        candidates: List[Tuple[int, str]] = []
        for root, _dirs, files in os.walk(path):
            for name in files:
                p = os.path.join(root, name)
                try:
                    candidates.append((os.path.getsize(p), p))
                except OSError:
                    continue
        if not candidates:
            return False
        size, victim = max(candidates)
        with open(victim, "r+b") as f:
            f.truncate(size // 2)
        removed = []
        for name in ("_METADATA", "_CHECKPOINT_METADATA"):
            p = os.path.join(path, name)
            if os.path.exists(p):
                os.remove(p)
                removed.append(name)
        logger.warning(
            "chaos: left checkpoint %s partial (truncated %s %d -> %d "
            "bytes; removed %s)",
            path, victim, size, size // 2, removed or "nothing",
        )
        return True

    # -- gradients -------------------------------------------------------
    def poison_batch(self, batch, site: str = "batch") -> bool:
        """Plant a NaN/Inf in the first float leaf of a training batch.

        Works on host numpy arrays (in place) and jax arrays (functional
        ``.at[...].set`` via duck typing — no jax import here).  Poisoning
        the batch corrupts the loss and gradients downstream, which is
        exactly what the train step's non-finite guard must absorb.
        """
        if self.decide("grad_nan", site):
            value = float("nan")
        elif self.decide("grad_inf", site):
            value = float("inf")
        else:
            return False
        for key in sorted(batch):
            arr = batch[key]
            dtype = getattr(arr, "dtype", None)
            if dtype is None or not np.issubdtype(np.dtype(str(dtype)), np.floating):
                continue
            if getattr(arr, "size", 0) == 0:
                continue
            if isinstance(arr, np.ndarray):
                arr.reshape(-1)[0] = value
            else:  # jax array: functional update, still no host sync
                flat_at = arr.reshape(-1).at[0].set(value)
                batch[key] = flat_at.reshape(arr.shape)
            return True
        return False


# ---------------------------------------------------------------------------
# process-wide activation

_ACTIVE: Optional[FaultInjector] = None
_ENV_CHECKED = False
_INSTALL_LOCK = threading.Lock()


def install(injector: Optional[FaultInjector]) -> None:
    """Install (or, with None, remove) the process-wide injector."""
    global _ACTIVE, _ENV_CHECKED
    with _INSTALL_LOCK:
        _ACTIVE = injector
        _ENV_CHECKED = True  # explicit install wins over the env var


def clear() -> None:
    """Remove any injector AND forget the env-var verdict, so the next
    :func:`active` call re-reads ``SCALERL_CHAOS`` (tests toggle the var)."""
    global _ACTIVE, _ENV_CHECKED
    with _INSTALL_LOCK:
        _ACTIVE = None
        _ENV_CHECKED = False


def from_env() -> Optional[FaultInjector]:
    text = os.environ.get(ENV_VAR, "")
    if not text:
        return None
    return FaultInjector(ChaosPlan.parse(text))


def active() -> Optional[FaultInjector]:
    """The process-wide injector, or None.

    Lazily initialized from ``SCALERL_CHAOS`` exactly once per process —
    spawned fleet children inherit the env var, so a soak plan covers the
    whole process tree.  The fast path is one global read: with no chaos
    configured the data plane pays nothing.
    """
    global _ACTIVE, _ENV_CHECKED
    if _ENV_CHECKED:
        return _ACTIVE
    with _INSTALL_LOCK:
        if not _ENV_CHECKED:
            try:
                _ACTIVE = from_env()
            except ValueError:
                logger.exception("chaos: invalid %s value ignored", ENV_VAR)
                _ACTIVE = None
            _ENV_CHECKED = True
            if _ACTIVE is not None:
                logger.warning(
                    "chaos: fault injection ACTIVE (%s=%s)",
                    ENV_VAR, os.environ.get(ENV_VAR),
                )
    return _ACTIVE
