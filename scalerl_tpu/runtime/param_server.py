"""Versioned parameter distribution: the snapshot plane + the pull server.

Parity target: ``ParameterServer`` (``scalerl/hpc/parameter_server.py:4-33``)
— a push/pull weight holder — upgraded with what the reference lacked:
versioning (actors can skip a no-op pull), thread-safety (the reference had
no locking), and zero-copy host snapshots (device->host fetch happens once
per publish, not once per actor pull).  This is the "weight publication
without stalls" design of SURVEY.md §7: the learner publishes a snapshot;
actor pulls never block the train step.

Parameter distribution used to exist three times — ``ParameterServer``
push/pull, ``InferenceServer.push_params``, and the generation engines'
``push_params`` — each with its own tagging.  :class:`ParamSnapshotPlane`
is the ONE idiom all three now share (the ROADMAP snapshot-bus refactor):
a monotonic *generation* id, a device-side snapshot copy detached from the
learner's donated buffers, optional quantized storage
(``runtime/quantize.py``) with dequant-on-read cached per generation, a
``_place`` hook for sharding-aware re-placement, and a bounded
generation -> learner-step map backing the unified staleness definition
(learner steps behind the newest generation; docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import sys
import threading
from typing import Any, Dict, Optional, Tuple

import numpy as np


def jnp_copy(x):
    """Async device-side copy (new buffer, survives donation of ``x``).

    jax is referenced only if it is already loaded: fleet workers and
    spawn children publish/pull plain numpy trees and must not pay the
    multi-second jax import just to hold weights.
    """
    jax = sys.modules.get("jax")
    if jax is not None and isinstance(x, jax.Array):
        import jax.numpy as jnp

        return jnp.copy(x)
    return np.asarray(x)


def _to_host(tree):
    """Materialize a weight pytree on the host in ONE batched fetch.

    ``jax.device_get`` transfers the whole tree in one call (the per-leaf
    ``np.asarray`` alternative pays one blocking round trip per layer —
    dozens per pull under the tunnel's 50-100 ms latency).  Processes that
    never imported jax can only hold numpy trees; they keep the per-leaf
    stdlib walk, which is already host-local and free.
    """
    jax = sys.modules.get("jax")
    if jax is not None:
        return jax.device_get(tree)
    return _tree_map(np.asarray, tree)


def _tree_map(fn, tree):
    """``jax.tree_util.tree_map`` when jax is loaded; a stdlib-container
    fallback otherwise.  A process that never imported jax can only be
    holding dict/list/tuple/leaf weight trees (fleet workers), so the
    fallback is complete for them — and flax/custom pytrees always arrive
    with jax already in ``sys.modules``."""
    jax = sys.modules.get("jax")
    if jax is not None:
        return jax.tree_util.tree_map(fn, tree)
    if tree is None:
        return None  # match jax: None is empty structure, not a leaf
    if isinstance(tree, dict):
        return {k: _tree_map(fn, v) for k, v in tree.items()}
    if isinstance(tree, tuple) and hasattr(tree, "_fields"):
        # NamedTuple: positional-field constructor, not iterable-accepting
        return type(tree)(*(_tree_map(fn, v) for v in tree))
    if isinstance(tree, (list, tuple)):
        return type(tree)(_tree_map(fn, v) for v in tree)
    return fn(tree)


class ParamSnapshotPlane:
    """Generation-tagged parameter snapshots, optionally quantized.

    The shared distribution idiom (``ParameterServer``, ``InferenceServer``,
    the generation engines, the disagg learner): :meth:`push_params`
    publishes a snapshot copy with a monotonic generation bump — the copy
    detaches the snapshot from the learner's donated buffers — and
    ``_snapshot_params`` hands consumers the serve-ready tree.

    ``quantize="int8" | "bf16"`` stores the ROADMAP's compressed broadcast
    format instead (``runtime/quantize.py``: per-leaf symmetric int8 with
    f32 scales, or a bf16 cast; 1-D f32-sensitive leaves pass through) and
    dequantizes ON READ, cached per generation — so a non-learner replica
    holds the small format at rest and pays one fused dequant per publish.

    Subclasses may override :meth:`_place` (sharding-aware re-placement:
    the ``InferenceServer`` re-places snapshots into the learner's live
    mesh layout) — it is applied to full-precision pushes AND to the
    dequantized read.  ``learner_step`` on a push records the bounded
    generation -> learner-step map that :meth:`staleness_steps` reads: the
    unified staleness definition is *learner steps behind the newest
    generation* (docs/OBSERVABILITY.md), and at push-per-step the
    generation delta equals it for entries that aged out of the map.

    jax-optional by design: full-precision pushes of numpy trees work in
    processes that never imported jax (``_tree_map``/``jnp_copy`` fall back
    to stdlib walks); only ``quantize=`` requires jax.
    """

    _GEN_STEPS_CAP = 64

    def _init_param_plane(self, params: Any) -> None:
        self._param_lock = threading.Lock()
        self._params = (
            self._place(_tree_map(jnp_copy, params))
            if params is not None
            else None
        )
        self._quantized = None
        self.generation = 0
        self._gen_steps: Dict[int, int] = {0: 0}
        self._latest_learner_step = 0

    def _place(self, snapshot: Any) -> Any:
        """Placement hook: identity here; sharded consumers re-place the
        snapshot into their live layout (device-side reshard at worst)."""
        return snapshot

    def push_params(
        self,
        params: Any,
        learner_step: Optional[int] = None,
        quantize: Optional[str] = None,
    ) -> int:
        """Publish fresh params (device-side copy or quantized snapshot +
        monotonic generation bump; no host transfer).  Returns the new
        generation."""
        if quantize is None:
            snapshot, qsnap = self._place(_tree_map(jnp_copy, params)), None
        else:
            # round/clip/cast produce fresh buffers, so the quantized tree
            # is already detached from the learner's donated params
            from scalerl_tpu.runtime.quantize import quantize_tree

            snapshot, qsnap = None, quantize_tree(params, quantize)
        with self._param_lock:
            self.generation += 1
            gen = self.generation
            self._params = snapshot
            self._quantized = qsnap
            self._record_step(gen, learner_step)
            return gen

    def _record_step(self, gen: int, learner_step: Optional[int]) -> None:
        """Under the param lock: extend the bounded gen -> step map."""
        self._latest_learner_step = (
            int(learner_step) if learner_step is not None else gen
        )
        self._gen_steps[gen] = self._latest_learner_step
        while len(self._gen_steps) > self._GEN_STEPS_CAP:
            self._gen_steps.pop(min(self._gen_steps))

    def _snapshot_params(self) -> Tuple[Any, int]:
        with self._param_lock:
            if self._params is None and self._quantized is not None:
                # dequant-on-read, cached until the next push
                from scalerl_tpu.runtime.quantize import dequantize_tree

                self._params = self._place(dequantize_tree(self._quantized))
            return self._params, self.generation

    def staleness_steps(self, served_generation: int) -> float:
        """Lag (in learner steps) between the newest pushed params and the
        generation that produced a transition/sequence — the ONE staleness
        definition every plane reports (docs/OBSERVABILITY.md).  A
        generation older than the bounded map reports the generation delta,
        which equals learner steps at push-per-step."""
        with self._param_lock:
            newest = self._latest_learner_step
            served = self._gen_steps.get(
                int(served_generation), int(served_generation)
            )
        return float(max(newest - served, 0))


class ParameterServer(ParamSnapshotPlane):
    """The DCN fleet's pull endpoint over the shared snapshot plane.

    The bespoke version tagging this class used to carry is gone: the
    monotonic ``generation`` id, the snapshot copy, and the thread-safety
    contract all come from :class:`ParamSnapshotPlane` — ``version`` is an
    alias for the plane's generation.  What remains here is the fleet's
    *pull* shape: pullers always receive host (numpy) pytrees, with the
    device->host fetch paid once per publish (``to_host=True``) or lazily
    on first pull, cached per generation (``to_host=False``).
    """

    def __init__(self) -> None:
        self._init_param_plane(None)
        self._is_host = True

    @property
    def version(self) -> int:
        with self._param_lock:
            return self.generation

    def push(self, weights: Any, to_host: bool = True) -> int:
        """Publish new weights; returns the new version (generation).

        With ``to_host=True`` the pytree is fetched to numpy once here, so N
        actor pulls cost zero device traffic.  SEED-style learners whose
        actors run device inference should push with ``to_host=False``: the
        per-step publish is then the plane's device-side copy + generation
        bump (no host sync), and the numpy snapshot is materialized lazily —
        once, cached per version — only if some off-host consumer pulls.
        The device copy detaches the snapshot from the learner's buffers:
        mesh learn steps donate their state (``parallel/train_step.py``), so
        storing the live params would leave pullers holding deleted arrays.
        """
        if to_host:
            snapshot = _to_host(weights)
        else:
            snapshot = _tree_map(jnp_copy, weights)
        with self._param_lock:
            self.generation += 1
            self._params = snapshot
            self._quantized = None
            self._is_host = to_host
            self._record_step(self.generation, None)
            return self.generation

    def pull(self, have_version: int = -1) -> Tuple[Optional[Any], int]:
        """Return (numpy weights, version), or (None, version) if current.

        Pullers always receive host (numpy) pytrees regardless of how the
        weights were pushed — a ``to_host=False`` publish is materialized
        here on first pull and the conversion is cached for the version.
        Materialization happens *outside* the lock (it blocks on the device
        finishing the in-flight step), so a slow pull never stalls the
        learner's next ``push``.
        """
        with self._param_lock:
            if self._params is None or have_version == self.generation:
                return None, self.generation
            weights, version, is_host = (
                self._params, self.generation, self._is_host,
            )
        if not is_host:
            weights = _to_host(weights)
            with self._param_lock:
                if self.generation == version:
                    self._params = weights
                    self._is_host = True
        return weights, version
