"""Versioned parameter server for actor weight publication.

Parity target: ``ParameterServer`` (``scalerl/hpc/parameter_server.py:4-33``)
— a push/pull weight holder — upgraded with what the reference lacked:
versioning (actors can skip a no-op pull), thread-safety (the reference had
no locking), and zero-copy host snapshots (device->host fetch happens once
per publish, not once per actor pull).  This is the "weight publication
without stalls" design of SURVEY.md §7: the learner publishes a snapshot;
actor pulls never block the train step.
"""

from __future__ import annotations

import sys
import threading
from typing import Any, Optional, Tuple

import numpy as np


def jnp_copy(x):
    """Async device-side copy (new buffer, survives donation of ``x``).

    jax is referenced only if it is already loaded: fleet workers and
    spawn children publish/pull plain numpy trees and must not pay the
    multi-second jax import just to hold weights.
    """
    jax = sys.modules.get("jax")
    if jax is not None and isinstance(x, jax.Array):
        import jax.numpy as jnp

        return jnp.copy(x)
    return np.asarray(x)


def _to_host(tree):
    """Materialize a weight pytree on the host in ONE batched fetch.

    ``jax.device_get`` transfers the whole tree in one call (the per-leaf
    ``np.asarray`` alternative pays one blocking round trip per layer —
    dozens per pull under the tunnel's 50-100 ms latency).  Processes that
    never imported jax can only hold numpy trees; they keep the per-leaf
    stdlib walk, which is already host-local and free.
    """
    jax = sys.modules.get("jax")
    if jax is not None:
        return jax.device_get(tree)
    return _tree_map(np.asarray, tree)


def _tree_map(fn, tree):
    """``jax.tree_util.tree_map`` when jax is loaded; a stdlib-container
    fallback otherwise.  A process that never imported jax can only be
    holding dict/list/tuple/leaf weight trees (fleet workers), so the
    fallback is complete for them — and flax/custom pytrees always arrive
    with jax already in ``sys.modules``."""
    jax = sys.modules.get("jax")
    if jax is not None:
        return jax.tree_util.tree_map(fn, tree)
    if tree is None:
        return None  # match jax: None is empty structure, not a leaf
    if isinstance(tree, dict):
        return {k: _tree_map(fn, v) for k, v in tree.items()}
    if isinstance(tree, tuple) and hasattr(tree, "_fields"):
        # NamedTuple: positional-field constructor, not iterable-accepting
        return type(tree)(*(_tree_map(fn, v) for v in tree))
    if isinstance(tree, (list, tuple)):
        return type(tree)(_tree_map(fn, v) for v in tree)
    return fn(tree)


class ParameterServer:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._version = 0
        self._weights: Any = None
        self._is_host = True

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    def push(self, weights: Any, to_host: bool = True) -> int:
        """Publish new weights; returns the new version.

        With ``to_host=True`` the pytree is fetched to numpy once here, so N
        actor pulls cost zero device traffic.  SEED-style learners whose
        actors run device inference should push with ``to_host=False``: the
        per-step publish is then an async *device-side copy* + version bump
        (no host sync), and the numpy snapshot is materialized lazily —
        once, cached per version — only if some off-host consumer pulls.
        The device copy detaches the snapshot from the learner's buffers:
        mesh learn steps donate their state (``parallel/train_step.py``), so
        storing the live params would leave pullers holding deleted arrays.
        """
        if to_host:
            weights = _to_host(weights)
        else:
            weights = _tree_map(jnp_copy, weights)
        with self._lock:
            self._version += 1
            self._weights = weights
            self._is_host = to_host
            return self._version

    def pull(self, have_version: int = -1) -> Tuple[Optional[Any], int]:
        """Return (numpy weights, version), or (None, version) if current.

        Pullers always receive host (numpy) pytrees regardless of how the
        weights were pushed — a ``to_host=False`` publish is materialized
        here on first pull and the conversion is cached for the version.
        Materialization happens *outside* the lock (it blocks on the device
        finishing the in-flight step), so a slow pull never stalls the
        learner's next ``push``.
        """
        with self._lock:
            if self._weights is None or have_version == self._version:
                return None, self._version
            weights, version, is_host = self._weights, self._version, self._is_host
        if not is_host:
            weights = _to_host(weights)
            with self._lock:
                if self._version == version:
                    self._weights = weights
                    self._is_host = True
        return weights, version
