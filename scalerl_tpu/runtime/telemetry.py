"""Unified telemetry plane: metrics registry, flight recorder, fleet merge.

Every subsystem grown so far shipped its own ad-hoc counters —
``hub.protocol_errors``, ``server.duplicate_results``,
``ShmRolloutRing.torn_reads``, the train-step guard's
``skipped_steps``/``nonfinite_grads``, per-queue ``stats()`` — with no
single place to read, export, or correlate them.  IMPALA (arxiv 1802.01561)
and the Podracer report (arxiv 2104.06272) both stress that actor-learner
throughput tuning lives or dies on cross-plane visibility (actor FPS vs.
learner steps/s vs. queue occupancy).  This module is that plane:

- :class:`MetricsRegistry` — a process-local, thread-safe registry of
  **counters**, **gauges**, **histograms** (bounded reservoir quantile
  sketch), and **rate meters** (``fps``, ``learn_steps_per_s``).  Subsystems
  either hold instrument objects (host-side integer bumps, JG001-clean by
  construction — no device value ever enters an instrument) or ``bind()`` a
  snapshot-time callable for object state that already exists (queue depths,
  ring occupancy).  ``snapshot()`` returns one merged nested tree.
- :class:`FlightRecorder` — a bounded ring buffer of recent structured
  events (reconnects, torn reads, watchdog probes, non-finite skips,
  checkpoint save/restore, chaos injections).  It is dumped alongside the
  faulthandler stack dump on watchdog stall, on divergence rollback, and on
  SIGTERM — the "what happened just before" half of every stall report.
- :class:`TelemetryAggregator` — the learner-side merge point for compact
  snapshots piggybacked on fleet heartbeat pongs and result-upload frames
  (codec v2 dict payloads; no new message round-trips).  Per-source latest
  plus key-wise aggregate series.
- Exporters — periodic JSONL (one snapshot per line) and a Prometheus-style
  text exposition file, both driven by one :class:`TelemetryExportLoop`
  thread off the same registry.

jax-free by design: fleet workers and spawn children import this for
pennies, and nothing here can ever issue a device transfer.  Device metrics
still arrive via the one batched transfer per chunk
(``runtime.dispatch.get_metrics``); trainers feed the already-host floats
into the registry (:func:`observe_train_metrics`).

Process-wide access: :func:`get_registry` / :func:`get_recorder` return the
default instances (created on first use); :func:`reset` swaps in fresh ones
(tests).  When ``SCALERL_TELEMETRY_DIR`` is set, the process writes a
``final_snapshot.json`` at exit — ``tools/tpu_watch.py`` attaches it to the
payload step summary.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Mapping, Optional, Tuple

from scalerl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

ENV_DIR = "SCALERL_TELEMETRY_DIR"
ENV_HOST_ID = "SCALERL_HOST_ID"

# instrument kind tags used by the Prometheus exposition writer
_KIND_COUNTER = "counter"
_KIND_GAUGE = "gauge"
_KIND_HISTOGRAM = "histogram"
_KIND_METER = "meter"


def _now() -> float:
    return time.monotonic()


_HOST_ID: Optional[str] = None


def host_id() -> str:
    """A stable per-process identity for merged multi-host artifacts
    (flight-event ordering, trace span files): ``SCALERL_HOST_ID`` when the
    deployment sets one, else ``<hostname>-<pid>`` — distinct per process,
    stable for the process lifetime."""
    global _HOST_ID
    if _HOST_ID is None:
        env = os.environ.get(ENV_HOST_ID, "")
        if env:
            _HOST_ID = env
        else:
            import socket as _socket

            _HOST_ID = f"{_socket.gethostname()}-{os.getpid()}"
    return _HOST_ID


# runtime/tracing.py registers its current-trace lookup here, so every
# flight event recorded while a span is active carries the trace id —
# without telemetry (imported by everything) importing the tracer
_TRACE_ID_PROVIDER: Optional[Callable[[], Optional[str]]] = None


def set_trace_id_provider(fn: Optional[Callable[[], Optional[str]]]) -> None:
    global _TRACE_ID_PROVIDER
    _TRACE_ID_PROVIDER = fn


# ---------------------------------------------------------------------------
# instruments


class Counter:
    """Monotonic event counter.  ``inc`` is a host-side integer add under a
    lock cheap enough for per-chunk call sites (the hot loops bump once per
    chunk/batch, never per element)."""

    kind = _KIND_COUNTER
    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str) -> None:
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value

    def read(self) -> float:
        return self._value


class Gauge:
    """Last-written value (replay size, eps, queue depth at log time)."""

    kind = _KIND_GAUGE
    __slots__ = ("name", "_value")

    def __init__(self, name: str) -> None:
        self.name = name
        self._value = 0.0

    def set(self, v: float) -> None:
        self._value = float(v)

    @property
    def value(self) -> float:
        return self._value

    def read(self) -> float:
        return self._value


class Histogram:
    """Count/sum/min/max plus a bounded quantile estimator — one of two
    backends, chosen at construction:

    - ``backend="reservoir"`` (default): deterministic systematic sampling
      (every k-th observation once full — no RNG so snapshots are
      reproducible in tests).  Adequate for SMALL-count distributions
      (step latencies, batch staleness); structurally biased at the tail
      once the count dwarfs the 256-slot reservoir.
    - ``backend="digest"``: a mergeable log-bucket sketch
      (``runtime/attribution.LatencyDigest`` — fixed γ-spaced buckets,
      DDSketch-style) whose quantiles stay within ``relative_error`` of
      the true value at ANY count, and whose merge across hosts/threads
      is exact integer addition.  The traffic-plane SLO instruments
      (``serving.latency_s``, ``router.latency_s`` — the autoscaler's p95
      signal) live here; a million-request p99 from a 256-sample
      reservoir is not a number worth gating on.
    """

    kind = _KIND_HISTOGRAM
    __slots__ = ("name", "_lock", "count", "sum", "min", "max", "_reservoir",
                 "_cap", "_stride", "backend", "_digest")

    def __init__(self, name: str, reservoir_size: int = 256,
                 backend: str = "reservoir",
                 relative_error: float = 0.01) -> None:
        if backend not in ("reservoir", "digest"):
            raise ValueError(f"unknown histogram backend {backend!r}")
        self.name = name
        self.backend = backend
        self._lock = threading.Lock()
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self._reservoir: List[float] = []
        self._cap = int(reservoir_size)
        self._stride = 1
        self._digest = None
        if backend == "digest":
            # deferred import: attribution imports telemetry for the
            # registry, so the reverse edge must not run at module load
            from scalerl_tpu.runtime.attribution import LatencyDigest

            self._digest = LatencyDigest(relative_error=relative_error)

    def observe(self, v: float) -> None:
        v = float(v)
        if self._digest is not None:
            with self._lock:
                self.count += 1
                self.sum += v
                self.min = min(self.min, v)
                self.max = max(self.max, v)
            self._digest.observe(v)
            return
        with self._lock:
            self.count += 1
            self.sum += v
            self.min = min(self.min, v)
            self.max = max(self.max, v)
            if len(self._reservoir) < self._cap:
                self._reservoir.append(v)
            else:
                # systematic thinning: keep a bounded, roughly uniform sample
                self._stride += 1
                if self.count % self._stride == 0:
                    self._reservoir[self.count % self._cap] = v

    def quantile(self, q: float) -> float:
        if self._digest is not None:
            return self._digest.quantile(q)
        with self._lock:
            if not self._reservoir:
                return 0.0
            data = sorted(self._reservoir)
        idx = min(len(data) - 1, max(0, int(q * (len(data) - 1))))
        return data[idx]

    def digest_wire(self) -> Optional[Dict[str, Any]]:
        """The mergeable digest snapshot (JSON-safe), or None on the
        reservoir backend — the fleet piggyback / artifact hook."""
        return self._digest.to_wire() if self._digest is not None else None

    def read(self) -> Dict[str, float]:
        with self._lock:
            if self.count == 0:
                return {"count": 0.0, "sum": 0.0, "mean": 0.0,
                        "min": 0.0, "max": 0.0, "p50": 0.0, "p95": 0.0,
                        "p99": 0.0}
            out = {
                "count": float(self.count),
                "sum": self.sum,
                "mean": self.sum / self.count,
                "min": self.min,
                "max": self.max,
            }
        out["p50"] = self.quantile(0.50)
        out["p95"] = self.quantile(0.95)  # the serving SLO quantile
        out["p99"] = self.quantile(0.99)
        if self._digest is not None:
            # the digest's tail stays trustworthy at any count — expose the
            # p999 the reservoir could never honestly report
            out["p999"] = self.quantile(0.999)
        return out


class RateMeter:
    """Sliding-window event rate (``fps``, ``learn_steps_per_s``).

    ``mark(n)`` records n events now; ``rate()`` is events/second over the
    trailing ``window_s`` seconds.  ``total`` is the lifetime event count
    (so the meter doubles as a counter in snapshots).
    """

    kind = _KIND_METER
    __slots__ = ("name", "window_s", "_lock", "_events", "total", "_t0")

    def __init__(self, name: str, window_s: float = 30.0) -> None:
        self.name = name
        self.window_s = float(window_s)
        self._lock = threading.Lock()
        self._events: Deque[Tuple[float, float]] = deque()
        self.total = 0.0
        self._t0 = _now()

    def mark(self, n: float = 1.0) -> None:
        t = _now()
        with self._lock:
            self.total += n
            self._events.append((t, float(n)))
            self._trim(t)

    def _trim(self, t: float) -> None:
        horizon = t - self.window_s
        while self._events and self._events[0][0] < horizon:
            self._events.popleft()

    def rate(self) -> float:
        t = _now()
        with self._lock:
            self._trim(t)
            if not self._events:
                return 0.0
            n = sum(c for _, c in self._events)
            # observed span, floored at 1 s so a fresh burst reports a
            # per-second rate instead of an absurd instantaneous one
            span = max(t - max(self._events[0][0], t - self.window_s), 1.0)
        return n / span

    def read(self) -> Dict[str, float]:
        return {"rate": self.rate(), "total": self.total}


Instrument = Any  # Counter | Gauge | Histogram | RateMeter


# ---------------------------------------------------------------------------
# registry


class MetricsRegistry:
    """Process-local, thread-safe instrument registry with a snapshot tree.

    Names are dotted paths (``hub.protocol_errors``, ``train.fps``); the
    snapshot nests on the dots.  Two ways in:

    - ``counter``/``gauge``/``histogram``/``meter`` return (creating once)
      the named instrument — the same name always yields the same object,
      so call sites don't need to thread instrument handles around.
    - ``bind(name, fn)`` registers a snapshot-time callable for state that
      already lives on an object (``queue.stats``, ``ring.stats``,
      ``aggregator.tree``).  ``fn`` may return a scalar or a dict subtree;
      a raising binding snapshots as an error string instead of killing the
      exporter (the object may have been torn down).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._instruments: Dict[str, Instrument] = {}
        self._bindings: Dict[str, Callable[[], Any]] = {}

    # -- instrument access ---------------------------------------------
    def _get(self, name: str, factory: Callable[[str], Instrument]):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = factory(name)
                self._instruments[name] = inst
            return inst

    def counter(self, name: str) -> Counter:
        inst = self._get(name, Counter)
        if not isinstance(inst, Counter):
            raise TypeError(f"instrument {name!r} is a {inst.kind}, not a counter")
        return inst

    def gauge(self, name: str) -> Gauge:
        inst = self._get(name, Gauge)
        if not isinstance(inst, Gauge):
            raise TypeError(f"instrument {name!r} is a {inst.kind}, not a gauge")
        return inst

    def histogram(self, name: str, reservoir_size: int = 256,
                  backend: str = "reservoir",
                  relative_error: float = 0.01) -> Histogram:
        inst = self._get(
            name,
            lambda n: Histogram(n, reservoir_size, backend=backend,
                                relative_error=relative_error),
        )
        if not isinstance(inst, Histogram):
            raise TypeError(f"instrument {name!r} is a {inst.kind}, not a histogram")
        return inst

    def meter(self, name: str, window_s: float = 30.0) -> RateMeter:
        inst = self._get(name, lambda n: RateMeter(n, window_s))
        if not isinstance(inst, RateMeter):
            raise TypeError(f"instrument {name!r} is a {inst.kind}, not a meter")
        return inst

    def bind(self, name: str, fn: Callable[[], Any]) -> None:
        """Bind a snapshot-time callable at ``name`` (scalar or dict subtree).
        Rebinding replaces — short-lived objects (tests, respawned rings)
        simply shadow their predecessor."""
        with self._lock:
            self._bindings[name] = fn

    def unbind(self, name: str) -> None:
        with self._lock:
            self._bindings.pop(name, None)

    def set_gauges(self, values: Mapping[str, float], prefix: str = "") -> None:
        """Bulk gauge write: the trainer idiom for a host metric dict —
        every numeric value lands as ``<prefix><key>``."""
        for k, v in values.items():
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            if isinstance(v, float) and not math.isfinite(v):
                continue  # NaN/Inf gauges poison aggregations downstream
            try:
                self.gauge(prefix + k).set(float(v))
            except TypeError:
                # the name is already a meter/counter (e.g. train.fps as a
                # RateMeter) — that instrument is the source of truth; the
                # bulk gauge write must not fight it
                continue

    # -- snapshots -----------------------------------------------------
    def _values(self) -> Dict[str, Any]:
        with self._lock:
            instruments = dict(self._instruments)
            bindings = dict(self._bindings)
        flat: Dict[str, Any] = {}
        for name, inst in instruments.items():
            flat[name] = inst.read()
        for name, fn in bindings.items():
            try:
                flat[name] = fn()
            except Exception as e:  # noqa: BLE001 — a dead binding must not kill a snapshot
                flat[name] = f"<error: {e!r}>"
        return flat

    def snapshot(self) -> Dict[str, Any]:
        """One merged nested tree of every instrument and binding."""
        tree: Dict[str, Any] = {}
        for name, value in self._values().items():
            parts = name.split(".")
            node = tree
            for p in parts[:-1]:
                nxt = node.get(p)
                if not isinstance(nxt, dict):
                    nxt = {} if nxt is None else {"_value": nxt}
                    node[p] = nxt
                node = nxt
            leaf = parts[-1]
            if isinstance(node.get(leaf), dict) and isinstance(value, dict):
                node[leaf].update(value)
            else:
                node[leaf] = value
        return tree

    def scalars(self, prefix: str = "") -> Dict[str, float]:
        """Flat ``{dotted.name: float}`` view (histograms/meters expand to
        their summary fields) — the logger/exposition write path."""
        out: Dict[str, float] = {}

        def emit(name: str, value: Any) -> None:
            if isinstance(value, dict):
                for k, v in value.items():
                    emit(f"{name}.{k}", v)
            elif isinstance(value, bool):
                out[name] = float(value)
            elif isinstance(value, (int, float)):
                out[name] = float(value)

        for name, value in self._values().items():
            emit(prefix + name, value)
        return out

    def compact(self, prefix: str = "") -> Dict[str, float]:
        """Compact flat snapshot for the fleet piggyback: counters, meter
        totals/rates, and gauges only — histograms ship their count/mean.
        Small enough to ride every heartbeat pong without bloating frames."""
        out: Dict[str, float] = {}
        for name, value in self.scalars(prefix).items():
            # drop the per-quantile histogram fields from the wire payload
            # (.p999 is the digest backend's extra tail field)
            if name.endswith((".p50", ".p95", ".p99", ".p999", ".min",
                              ".max", ".sum")):
                continue
            out[name] = value
        return out


# ---------------------------------------------------------------------------
# flight recorder


class FlightRecorder:
    """Bounded ring buffer of recent structured events.

    ``record(kind, **fields)`` is cheap (deque append under a lock) and safe
    from any thread; the recorder keeps only the newest ``capacity`` events,
    so it can run for days and still dump a readable tail on failure.
    """

    def __init__(self, capacity: int = 256) -> None:
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._events: Deque[Dict[str, Any]] = deque(maxlen=self.capacity)
        self.total_recorded = 0

    def record(self, kind: str, **fields: Any) -> None:
        evt = {
            "t_wall": time.time(),
            "t_mono": time.monotonic(),
            "kind": kind,
            # merged multi-host timelines (trace_report, soak verdicts)
            # order on (host_id, seq) — deterministic even when the hosts'
            # wall clocks disagree
            "host_id": host_id(),
        }
        if _TRACE_ID_PROVIDER is not None:
            try:
                tid = _TRACE_ID_PROVIDER()
            except Exception:  # noqa: BLE001 — stamping must never fail a record
                tid = None
            if tid:
                evt["trace"] = tid
        if fields:
            evt.update(fields)
        with self._lock:
            evt["seq"] = self.total_recorded  # monotonic per process
            self._events.append(evt)
            self.total_recorded += 1

    def events(self, kind: Optional[str] = None) -> List[Dict[str, Any]]:
        """The retained tail, oldest first; ``kind`` filters to one event
        kind (``events("autoscale_decision")`` — the soak/chaos assertions)."""
        with self._lock:
            evts = list(self._events)
        if kind is None:
            return evts
        return [e for e in evts if e.get("kind") == kind]

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def dump_text(self) -> str:
        evts = self.events()
        if not evts:
            return "<flight recorder empty>"
        lines = [
            f"flight recorder: last {len(evts)} events "
            f"({self.total_recorded} total recorded, capacity {self.capacity})"
        ]
        for e in evts:
            # host_id/seq are ordering stamps, constant/monotonic within one
            # process — noise in a single-process stall dump (trace stays:
            # it is the cross-reference into the span files)
            extra = {
                k: v
                for k, v in e.items()
                if k not in ("t_wall", "t_mono", "kind", "host_id", "seq")
            }
            stamp = time.strftime("%H:%M:%S", time.localtime(e["t_wall"]))
            lines.append(f"  [{stamp}] {e['kind']} {extra}" if extra
                         else f"  [{stamp}] {e['kind']}")
        return "\n".join(lines)

    def dump_json(self, path: str) -> str:
        """Write the event tail as JSON (``{"events": [...]}``); returns the
        path.  Best-effort: failures are logged, never raised — dumps run on
        failure paths (signal handlers, watchdog fires)."""
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with open(path, "w") as f:
                json.dump(
                    {
                        "total_recorded": self.total_recorded,
                        "capacity": self.capacity,
                        "events": self.events(),
                    },
                    f,
                    default=str,
                )
        except Exception as e:  # noqa: BLE001 — a dump failure must not mask the crash
            logger.warning("flight recorder dump to %s failed: %r", path, e)
        return path


# ---------------------------------------------------------------------------
# fleet aggregation (learner side)


class TelemetryAggregator:
    """Merge compact per-source snapshots into per-worker + aggregate series.

    Sources are fleet peers — ``gather:<base_worker_id>`` uplinks and the
    ``worker:<id>`` payloads they relay.  ``absorb`` keeps the latest
    snapshot per source (these are cumulative counters, so "latest" IS the
    series value) plus a last-seen stamp; ``aggregate`` sums each key across
    sources.  ``tree()`` is what the registry binding exposes under
    ``fleet.*`` in the merged snapshot.

    Elastic churn means dead sources: a preempted worker's series would
    otherwise sit in the learner's view forever (every respawn adds a
    fresh source id), so the aggregator is BOUNDED — ``max_sources > 0``
    evicts the stalest source when a new one would exceed the cap, and
    :meth:`evict_stale` drops every source silent past ``max_age_s``
    (``age_s`` in the tree is the staleness a human reads).
    """

    def __init__(self, max_sources: int = 0) -> None:
        self._lock = threading.Lock()
        self._latest: Dict[str, Dict[str, float]] = {}
        self._seen: Dict[str, float] = {}
        self.frames_absorbed = 0
        self.max_sources = int(max_sources)
        self.evicted = 0

    def absorb(self, source: str, compact: Mapping[str, Any]) -> None:
        if not isinstance(compact, Mapping):
            return
        clean = {
            k: float(v)
            for k, v in compact.items()
            if isinstance(v, (int, float)) and not isinstance(v, bool)
        }
        with self._lock:
            self._latest[str(source)] = clean
            self._seen[str(source)] = time.monotonic()
            self.frames_absorbed += 1
            while self.max_sources > 0 and len(self._latest) > self.max_sources:
                stalest = min(self._seen, key=self._seen.get)
                self._latest.pop(stalest, None)
                self._seen.pop(stalest, None)
                self.evicted += 1

    def evict_stale(self, max_age_s: float) -> int:
        """Drop every source silent for longer than ``max_age_s``; returns
        the count — the learner's fleet view stays bounded across elastic
        churn (dead gathers/workers age out instead of accumulating)."""
        horizon = time.monotonic() - max_age_s
        dropped = 0
        with self._lock:
            for src in [s for s, t in self._seen.items() if t < horizon]:
                self._latest.pop(src, None)
                self._seen.pop(src, None)
                dropped += 1
            self.evicted += dropped
        return dropped

    def absorb_payload(self, payload: Any) -> None:
        """Absorb one piggybacked ``{"src": ..., "v": {...}, "workers":
        {id: {...}}}`` payload (the fleet wire shape)."""
        if not isinstance(payload, Mapping):
            return
        src = payload.get("src")
        if src is not None:
            self.absorb(str(src), payload.get("v") or {})
        for wid, wsnap in (payload.get("workers") or {}).items():
            self.absorb(f"worker:{wid}", wsnap)

    def sources(self) -> List[str]:
        with self._lock:
            return sorted(self._latest)

    def aggregate(self) -> Dict[str, float]:
        agg: Dict[str, float] = {}
        with self._lock:
            snaps = list(self._latest.values())
        for snap in snaps:
            for k, v in snap.items():
                agg[k] = agg.get(k, 0.0) + v
        return agg

    def tree(self) -> Dict[str, Any]:
        with self._lock:
            per_worker = {src: dict(snap) for src, snap in self._latest.items()}
            seen = dict(self._seen)
        now = time.monotonic()
        return {
            "sources": len(per_worker),
            "frames_absorbed": self.frames_absorbed,
            "evicted": self.evicted,
            "aggregate": self.aggregate(),
            "per_worker": {
                src: {**snap, "age_s": round(now - seen.get(src, now), 3)}
                for src, snap in per_worker.items()
            },
        }


# ---------------------------------------------------------------------------
# exporters


class JsonlExporter:
    """Append one ``{"t": ..., "snapshot": {...}}`` line per write."""

    def __init__(self, path: str) -> None:
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    def write(self, snapshot: Mapping[str, Any]) -> None:
        line = json.dumps({"t": time.time(), "snapshot": snapshot}, default=str)
        with open(self.path, "a") as f:
            f.write(line + "\n")


class PrometheusExporter:
    """Write a Prometheus text-exposition file (atomic tmp+rename).

    Names are sanitized to the ``[a-zA-Z_][a-zA-Z0-9_]*`` charset with the
    repo-wide ``scalerl_`` prefix; scrapers (or a human with ``cat``) get
    the whole plane in one file.
    """

    def __init__(self, path: str) -> None:
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    @staticmethod
    def _sanitize(name: str) -> str:
        out = []
        for ch in name:
            out.append(ch if ch.isalnum() or ch == "_" else "_")
        s = "".join(out)
        if not s or not (s[0].isalpha() or s[0] == "_"):
            s = "_" + s
        return "scalerl_" + s

    def write(self, scalars: Mapping[str, float]) -> None:
        lines = []
        for name in sorted(scalars):
            v = scalars[name]
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            if isinstance(v, float) and not math.isfinite(v):
                v = 0.0
            lines.append(f"{self._sanitize(name)} {v}")
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            f.write("\n".join(lines) + "\n")
        os.replace(tmp, self.path)


class TelemetryExportLoop:
    """Background thread writing JSONL + Prometheus exposition every
    ``interval_s`` seconds from one registry.  ``flush()`` writes
    immediately (end-of-run / tests); ``stop()`` flushes once more so the
    files always hold the final state."""

    def __init__(
        self,
        out_dir: str,
        interval_s: float = 30.0,
        registry: Optional[MetricsRegistry] = None,
    ) -> None:
        self.out_dir = out_dir
        self.interval_s = float(interval_s)
        self.registry = registry
        self.jsonl = JsonlExporter(os.path.join(out_dir, "telemetry.jsonl"))
        self.prom = PrometheusExporter(os.path.join(out_dir, "metrics.prom"))
        self.writes = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _registry(self) -> MetricsRegistry:
        return self.registry if self.registry is not None else get_registry()

    def flush(self) -> None:
        reg = self._registry()
        try:
            self.jsonl.write(reg.snapshot())
            self.prom.write(reg.scalars())
            self.writes += 1
        except Exception:  # noqa: BLE001 — exporter must never kill the run
            logger.exception("telemetry export failed")

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.flush()

    def start(self) -> "TelemetryExportLoop":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="telemetry-export", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        self.flush()

    def __enter__(self) -> "TelemetryExportLoop":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


# ---------------------------------------------------------------------------
# process-wide defaults

_LOCK = threading.Lock()
_REGISTRY: Optional[MetricsRegistry] = None
_RECORDER: Optional[FlightRecorder] = None
_ENV_DUMP_INSTALLED = False


def _maybe_install_env_dump() -> None:
    """When ``SCALERL_TELEMETRY_DIR`` is set, write a final snapshot +
    flight-recorder tail at interpreter exit (the tpu_watch attachment)."""
    global _ENV_DUMP_INSTALLED
    if _ENV_DUMP_INSTALLED:
        return
    _ENV_DUMP_INSTALLED = True
    out_dir = os.environ.get(ENV_DIR, "")
    if not out_dir:
        return
    import atexit

    def _dump() -> None:
        try:
            write_final_snapshot(out_dir)
        except Exception:  # noqa: BLE001 — exit hooks must be silent
            pass

    atexit.register(_dump)


def write_final_snapshot(out_dir: str) -> str:
    """Write ``final_snapshot.json`` (merged tree + flight tail) to
    ``out_dir``; returns the path."""
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, "final_snapshot.json")
    payload = {
        "t": time.time(),
        "pid": os.getpid(),
        "snapshot": get_registry().snapshot(),
        "flight_recorder": get_recorder().events(),
    }
    tmp = path + f".tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f, default=str)
    os.replace(tmp, path)
    return path


def get_registry() -> MetricsRegistry:
    global _REGISTRY
    if _REGISTRY is None:
        with _LOCK:
            if _REGISTRY is None:
                _REGISTRY = MetricsRegistry()
    _maybe_install_env_dump()
    return _REGISTRY


def get_recorder() -> FlightRecorder:
    global _RECORDER
    if _RECORDER is None:
        with _LOCK:
            if _RECORDER is None:
                _RECORDER = FlightRecorder(
                    int(os.environ.get("SCALERL_FLIGHT_EVENTS", "256") or 256)
                )
    return _RECORDER


def reset() -> None:
    """Fresh default registry + recorder (tests)."""
    global _REGISTRY, _RECORDER
    with _LOCK:
        _REGISTRY = MetricsRegistry()
        _RECORDER = FlightRecorder()


def record_event(kind: str, **fields: Any) -> None:
    """Record one structured event on the default flight recorder."""
    get_recorder().record(kind, **fields)


def snapshot() -> Dict[str, Any]:
    """The merged tree of the default registry (module-level convenience)."""
    return get_registry().snapshot()


def compact_snapshot(prefix: str = "") -> Dict[str, float]:
    return get_registry().compact(prefix)


def flight_dump_path(tag: str) -> str:
    """Where failure-path flight dumps land: ``SCALERL_TELEMETRY_DIR`` when
    set, else the system tempdir."""
    import tempfile

    out_dir = os.environ.get(ENV_DIR, "") or tempfile.gettempdir()
    return os.path.join(out_dir, f"scalerl_flight_{tag}_{os.getpid()}.json")


def observe_train_metrics(host_metrics: Optional[Mapping[str, Any]]) -> None:
    """Fold one chunk/step's already-host metric dict into the registry.

    Accumulates the train-step guard counters (``skipped_steps``,
    ``nonfinite_grads``) and records a flight event when a chunk skipped
    non-finite updates.  Host floats only — callers pass the output of
    ``runtime.dispatch.get_metrics`` (or any plain dict), never device
    values, so this can never add a transfer to a hot loop.
    """
    if not host_metrics:
        return
    reg = get_registry()

    def _num(key: str) -> float:
        v = host_metrics.get(key, 0.0)
        try:
            f = float(v)
        except (TypeError, ValueError):
            return 0.0
        return f if math.isfinite(f) else 0.0

    skipped = _num("skipped_steps")
    nonfinite = _num("nonfinite_grads")
    if skipped > 0.0:
        reg.counter("train.skipped_steps").inc(skipped)
        record_event("nonfinite_skip", skipped_steps=skipped,
                     nonfinite_grads=nonfinite)
    if nonfinite > 0.0:
        reg.counter("train.nonfinite_grads").inc(nonfinite)


def observe_staleness(lag_steps: float, plane: str = "") -> float:
    """Set the unified ``staleness`` gauge: LEARNER STEPS BEHIND THE NEWEST
    GENERATION — the one staleness definition every distribution path
    reports (docs/OBSERVABILITY.md).

    ``serving.staleness``, genrl's generation lag, and the disagg snapshot
    lag used to each carry their own name and unit; they now all funnel
    here (computed via ``ParamSnapshotPlane.staleness_steps``, whose
    bounded generation -> learner-step map converts a served generation tag
    into learner steps).  ``plane`` additionally stamps
    ``staleness_plane.<plane>`` so a multi-plane process can still tell the
    reporters apart; the unified gauge always holds the latest report.
    """
    lag = float(max(lag_steps, 0.0))
    reg = get_registry()
    reg.gauge("staleness").set(lag)
    if plane:
        reg.gauge(f"staleness_plane.{plane}").set(lag)
    return lag
