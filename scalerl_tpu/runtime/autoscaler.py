"""Telemetry-driven fleet autoscaler: the control loop over the elastic fleet.

IMPALA (arxiv 1802.01561) and the Podracer report (arxiv 2104.06272) frame
actor-learner throughput tuning as balancing exactly three signals — actor
production rate vs. learner consumption rate vs. queue occupancy — and the
telemetry plane (``runtime/telemetry.py``, docs/OBSERVABILITY.md) already
exposes all three plus the bounded-admission shed counters.  This module
closes the loop: a jax-free decision engine that reads those signals and
issues **scale-up / scale-down / drain** actions through a pluggable
executor, so a fleet on preemptible capacity *rides* a spot wave instead of
merely surviving it.

Design contract:

- **Decisions are a pure table** over :class:`FleetSignals`
  (``Autoscaler.evaluate`` — unit-testable with synthetic vectors, no fleet
  or threads required).
- **Hysteresis**: a pressure verdict must persist for ``up_hysteresis`` /
  ``down_hysteresis`` consecutive evaluations before it becomes an action,
  so heartbeat jitter or one noisy queue sample never moves the fleet.
- **Cooldown**: after any action the engine holds for ``cooldown_s``
  regardless of pressure — scale actions take seconds to take effect
  (process spawn, drain handshake), and acting on the pre-action signals
  again is how fleets flap.
- **Floor**: ``live_workers < min_workers`` (a preemption wave just landed)
  bypasses both — backfilling capacity the operator asked for is never
  "flapping".
- Every decision that is not a steady hold lands in the FlightRecorder
  (``autoscale_decision`` events) and the registry (``autoscaler.*``), so a
  post-mortem can line scale actions up against the faults that drove them.

jax-free by design: the loop runs on the learner host next to the
``WorkerServer`` and must not touch the device.  The reference executor
(``fleet.cluster.ClusterExecutor``) spawns/drains Local/RemoteCluster
gathers; anything with ``worker_count``/``scale_up``/``scale_down`` works.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, replace
from typing import Any, Callable, Deque, Dict, Optional

from scalerl_tpu.runtime import telemetry
from scalerl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# decision vocabulary
SCALE_UP = "scale_up"
SCALE_DOWN = "scale_down"
HOLD = "hold"


@dataclass
class FleetSignals:
    """One evaluation's input vector — the Podracer tuning triad plus the
    bounded-admission and serving-SLO pressure signals."""

    fps: float = 0.0                 # actor-plane production rate (results/s or frames/s)
    learn_steps_per_s: float = 0.0   # learner consumption rate
    queue_occupancy: float = 0.0     # 0..1 fill of the results/rollout queue
    shed_delta: float = 0.0          # bounded-admission sheds since last eval
    serving_p95_ms: float = 0.0      # inference-plane latency SLO quantile
    # generation-tier signal (disaggregated sequence RL): the unified
    # staleness gauge — learner steps behind the newest param generation in
    # the consumed data.  High staleness means the generation tier is
    # underproducing relative to the learner (replay serving old
    # generations), the scale-up pressure of the sequence-RL triad.
    snapshot_staleness: float = 0.0
    live_workers: int = 0            # capacity the executor currently runs


@dataclass(frozen=True)
class AutoscalerConfig:
    """Knobs for the decision table and its anti-flap guards."""

    min_workers: int = 1             # hard floor: breached -> immediate backfill
    max_workers: int = 32            # hard ceiling for scale-up
    interval_s: float = 5.0          # evaluation cadence of the background loop
    scale_step: int = 1              # workers added/drained per action
    # decision-table thresholds
    low_occupancy: float = 0.2       # queue this empty = learner starved -> up
    high_occupancy: float = 0.9      # queue this full = actors flooding -> down
    # optional production target: actors should produce at least this many
    # fps per learner step/s before the starved verdict is suppressed
    # (0 disables the ratio rule; occupancy alone then drives scale-up)
    fps_per_learn_step: float = 0.0
    # optional serving-plane guard: p95 act latency above this sheds load by
    # draining workers (0 disables the rule)
    serving_p95_slo_ms: float = 0.0
    # serving-TIER capacity rule (the router's replica fleet, where
    # live_workers are replicas, not actors — opposite semantics from the
    # guard above): aggregate p95 past the up threshold means the tier is
    # out of capacity -> add a replica; p95 under the down threshold means
    # it is over-provisioned -> drain one.  0 disables either side.
    serving_scale_up_p95_ms: float = 0.0
    serving_scale_down_p95_ms: float = 0.0
    # optional generation-tier guard (disaggregated sequence RL): consumed
    # data staler than this many learner steps means the generation fleet
    # is underproducing — scale it up (0 disables the rule)
    max_staleness: float = 0.0
    # anti-flap guards
    up_hysteresis: int = 2           # consecutive starved verdicts before up
    down_hysteresis: int = 3         # consecutive flooded verdicts before down
    cooldown_s: float = 30.0         # hold window after any action

    def __post_init__(self) -> None:
        if self.min_workers < 0:
            raise ValueError(f"min_workers must be >= 0, got {self.min_workers}")
        if self.max_workers < self.min_workers:
            raise ValueError(
                f"max_workers ({self.max_workers}) must be >= min_workers "
                f"({self.min_workers})"
            )
        if self.scale_step < 1:
            raise ValueError(f"scale_step must be >= 1, got {self.scale_step}")
        if self.up_hysteresis < 1 or self.down_hysteresis < 1:
            raise ValueError("hysteresis thresholds must be >= 1")
        if (
            self.serving_scale_up_p95_ms > 0
            and self.serving_scale_down_p95_ms >= self.serving_scale_up_p95_ms
        ):
            raise ValueError(
                "serving_scale_down_p95_ms "
                f"({self.serving_scale_down_p95_ms}) must be < "
                f"serving_scale_up_p95_ms ({self.serving_scale_up_p95_ms}) "
                "or the tier flaps between the two verdicts"
            )
        if self.serving_scale_up_p95_ms > 0 and self.serving_p95_slo_ms > 0:
            raise ValueError(
                "serving_scale_up_p95_ms (serving-tier capacity: p95 adds "
                "replicas) and serving_p95_slo_ms (actor-fleet guard: p95 "
                "drains actors) are opposite semantics for one signal — "
                "configure one per autoscaler instance"
            )

    @classmethod
    def from_args(cls, args: Any) -> "AutoscalerConfig":
        """Build from the ``RLArguments.autoscale_*`` fields (config.py)."""
        cfg = cls(
            min_workers=getattr(args, "autoscale_min_workers", cls.min_workers),
            max_workers=getattr(args, "autoscale_max_workers", cls.max_workers),
            interval_s=getattr(args, "autoscale_interval_s", cls.interval_s),
            cooldown_s=getattr(args, "autoscale_cooldown_s", cls.cooldown_s),
            max_staleness=getattr(
                args, "autoscale_max_staleness", cls.max_staleness
            ),
            serving_scale_up_p95_ms=getattr(
                args, "autoscale_serving_up_p95_ms", cls.serving_scale_up_p95_ms
            ),
            serving_scale_down_p95_ms=getattr(
                args,
                "autoscale_serving_down_p95_ms",
                cls.serving_scale_down_p95_ms,
            ),
        )
        hyst = int(getattr(args, "autoscale_hysteresis", cfg.up_hysteresis))
        # down is deliberately one verdict slower than up: adding capacity
        # during a starve is cheap to undo, draining during a flood is not
        return replace(cfg, up_hysteresis=hyst, down_hysteresis=hyst + 1)


@dataclass
class Decision:
    """One evaluation's verdict: what to do, how much, and why."""

    action: str                      # scale_up | scale_down | hold
    delta: int                       # workers to add/drain (0 for hold)
    reason: str
    signals: FleetSignals
    t: float = 0.0


class Autoscaler:
    """The decision engine plus an optional background control loop.

    ``executor`` (duck-typed): ``worker_count() -> int``,
    ``scale_up(n: int)``, ``scale_down(n: int)``.  ``signal_source`` is a
    zero-arg callable returning :class:`FleetSignals`
    (:func:`fleet_signal_source` builds one over a ``WorkerServer``).
    Both are optional so the table can be unit-tested bare.
    """

    def __init__(
        self,
        config: AutoscalerConfig,
        executor: Any = None,
        signal_source: Optional[Callable[[], FleetSignals]] = None,
        name: str = "autoscaler",
    ) -> None:
        self.config = config
        self.executor = executor
        self.signal_source = signal_source
        self.name = name
        self.scale_ups = 0
        self.scale_downs = 0
        self.holds = 0
        self.decisions = 0
        self.last_decision: Optional[Decision] = None
        self._up_streak = 0
        self._down_streak = 0
        self._last_action_t = -float("inf")
        self._action_times: Deque[float] = deque(maxlen=256)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        telemetry.get_registry().bind(
            self.name,
            lambda: {
                "decisions": self.decisions,
                "scale_ups": self.scale_ups,
                "scale_downs": self.scale_downs,
                "holds": self.holds,
                "up_streak": self._up_streak,
                "down_streak": self._down_streak,
                "actions_per_min": round(self.actions_per_min(), 3),
                "min_workers": self.config.min_workers,
                "max_workers": self.config.max_workers,
            },
        )

    # -- flap accounting -----------------------------------------------
    def actions_per_min(self, window_s: float = 60.0, now: Optional[float] = None) -> float:
        """Actions issued over the trailing window, per minute — the soak
        gate's flap metric (tpu_watch marks ``!elastic(flap=...)``)."""
        now = time.monotonic() if now is None else now
        recent = sum(1 for t in self._action_times if now - t <= window_s)
        return recent * 60.0 / window_s

    # -- the decision table --------------------------------------------
    def _pressure(self, s: FleetSignals) -> Optional[str]:
        """Raw directional verdict from one signal vector, pre-hysteresis."""
        cfg = self.config
        if cfg.serving_scale_up_p95_ms > 0 or cfg.serving_scale_down_p95_ms > 0:
            # serving-tier capacity semantics (the router's replica fleet):
            # latency pressure ADDS capacity — checked before the actor
            # rules because replica sheds are a scale-UP signal here
            if (
                cfg.serving_scale_up_p95_ms > 0
                and s.serving_p95_ms > cfg.serving_scale_up_p95_ms
            ):
                return SCALE_UP  # tier out of capacity: add a replica
            if s.shed_delta > 0:
                return SCALE_UP  # replicas shedding = demand over capacity
            if (
                cfg.serving_scale_down_p95_ms > 0
                and 0.0 < s.serving_p95_ms <= cfg.serving_scale_down_p95_ms
            ):
                return SCALE_DOWN  # comfortably under SLO: drain a replica
            return None
        if s.shed_delta > 0:
            return SCALE_DOWN  # bounded admission is actively dropping data
        if s.queue_occupancy >= cfg.high_occupancy:
            return SCALE_DOWN  # queue depth IS policy lag; don't add to it
        if cfg.serving_p95_slo_ms > 0 and s.serving_p95_ms > cfg.serving_p95_slo_ms:
            return SCALE_DOWN  # inference plane past its SLO
        if cfg.max_staleness > 0 and s.snapshot_staleness > cfg.max_staleness:
            # generation tier underproducing: the learner is consuming
            # sequences from old param generations — add decode capacity
            return SCALE_UP
        if s.queue_occupancy <= cfg.low_occupancy:
            target = cfg.fps_per_learn_step * s.learn_steps_per_s
            if cfg.fps_per_learn_step <= 0 or s.fps < target:
                return SCALE_UP  # learner starved: queue empty, production short
        return None

    def evaluate(self, signals: FleetSignals, now: Optional[float] = None) -> Decision:
        """One decision from one signal vector.  Pure apart from the streak/
        cooldown state this engine exists to keep — inject ``now`` in tests."""
        now = time.monotonic() if now is None else now
        cfg = self.config
        live = int(signals.live_workers)
        self.decisions += 1

        # hard floor: a preemption wave dropped us below the operator's
        # minimum — backfill immediately, no hysteresis, no cooldown
        if live < cfg.min_workers:
            return self._act(
                SCALE_UP, cfg.min_workers - live, "below_min_workers",
                signals, now,
            )

        pressure = self._pressure(signals)
        if pressure is None:
            self._up_streak = 0
            self._down_streak = 0
            return self._hold("steady", signals, now, record=False)
        if pressure == SCALE_UP:
            self._up_streak += 1
            self._down_streak = 0
            streak, needed = self._up_streak, cfg.up_hysteresis
        else:
            self._down_streak += 1
            self._up_streak = 0
            streak, needed = self._down_streak, cfg.down_hysteresis
        if streak < needed:
            return self._hold(
                f"hysteresis:{pressure} ({streak}/{needed})", signals, now
            )
        if now - self._last_action_t < cfg.cooldown_s:
            return self._hold(f"cooldown:{pressure}", signals, now)
        serving_tier = (
            cfg.serving_scale_up_p95_ms > 0 or cfg.serving_scale_down_p95_ms > 0
        )
        if pressure == SCALE_UP:
            delta = min(cfg.scale_step, cfg.max_workers - live)
            if delta <= 0:
                return self._hold("at_max_workers", signals, now)
            why = "tier_over_capacity" if serving_tier else "learner_starved"
            return self._act(SCALE_UP, delta, why, signals, now)
        delta = min(cfg.scale_step, live - cfg.min_workers)
        if delta <= 0:
            return self._hold("at_min_workers", signals, now)
        why = "tier_over_provisioned" if serving_tier else "overload"
        return self._act(SCALE_DOWN, delta, why, signals, now)

    def _hold(self, reason: str, signals: FleetSignals, now: float,
              record: bool = True) -> Decision:
        self.holds += 1
        d = Decision(HOLD, 0, reason, signals, now)
        self.last_decision = d
        if record:
            # a suppressed pressure verdict is itself diagnostic: the flight
            # tail shows WHY the fleet did not move (steady holds are noise
            # and stay out of the bounded ring)
            telemetry.record_event(
                "autoscale_decision", action=HOLD, reason=reason,
                workers=signals.live_workers,
            )
        return d

    def _act(self, action: str, delta: int, reason: str,
             signals: FleetSignals, now: float) -> Decision:
        self._up_streak = 0
        self._down_streak = 0
        self._last_action_t = now
        self._action_times.append(now)
        if action == SCALE_UP:
            self.scale_ups += 1
            telemetry.get_registry().counter("autoscaler.scale_ups").inc()
        else:
            self.scale_downs += 1
            telemetry.get_registry().counter("autoscaler.scale_downs").inc()
        telemetry.record_event(
            "autoscale_decision", action=action, delta=delta, reason=reason,
            workers=signals.live_workers,
        )
        logger.info(
            "autoscaler: %s %+d workers (%s; live=%d occ=%.2f fps=%.1f "
            "learn/s=%.1f shed=%.0f)",
            action, delta if action == SCALE_UP else -delta, reason,
            signals.live_workers, signals.queue_occupancy, signals.fps,
            signals.learn_steps_per_s, signals.shed_delta,
        )
        d = Decision(action, delta, reason, signals, now)
        self.last_decision = d
        return d

    # -- wiring ---------------------------------------------------------
    def step(self, now: Optional[float] = None) -> Decision:
        """Read signals, decide, and apply through the executor."""
        signals = self.signal_source() if self.signal_source is not None else FleetSignals()
        if self.executor is not None:
            # capacity truth comes from the executor (spawned procs, booting
            # gathers included) — roster-registered counts lag spawn by the
            # child's boot time and would re-fire the floor rule every poll
            signals = replace(signals, live_workers=int(self.executor.worker_count()))
        decision = self.evaluate(signals, now)
        if self.executor is not None and decision.delta > 0:
            try:
                if decision.action == SCALE_UP:
                    self.executor.scale_up(decision.delta)
                elif decision.action == SCALE_DOWN:
                    self.executor.scale_down(decision.delta)
            except Exception as e:  # noqa: BLE001 — the loop must outlive one bad action
                logger.exception("autoscaler: executor %s failed", decision.action)
                telemetry.record_event(
                    "autoscale_error", action=decision.action, error=repr(e)
                )
        return decision

    def _loop(self) -> None:
        while not self._stop.wait(self.config.interval_s):
            try:
                self.step()
            except Exception:  # noqa: BLE001 — a bad signal read must not kill the loop
                logger.exception("autoscaler: step failed")

    def start(self) -> "Autoscaler":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name=self.name, daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def __enter__(self) -> "Autoscaler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def fleet_signal_source(
    server: Any,
    registry: Optional[Any] = None,
    slo: Optional[Callable[[], Dict[str, float]]] = None,
) -> Callable[[], FleetSignals]:
    """Signal reader over a ``WorkerServer`` + the telemetry registry.

    - ``fps``: the server's ``server.results_per_s`` ingest meter;
    - ``learn_steps_per_s``: the trainers' ``rates.learn_steps_per_s`` meter
      (0 until a learner marks it);
    - ``queue_occupancy``: the server results queue fill fraction;
    - ``shed_delta``: hub + results-queue sheds since the previous read;
    - ``serving_p95_ms``: from an optional ``slo()`` callable
      (``InferenceServer.slo``);
    - ``live_workers``: the server's gather roster (the executor's spawned
      count overrides this inside ``Autoscaler.step``).
    """
    last = {"shed": 0.0}

    def read() -> FleetSignals:
        reg = registry if registry is not None else telemetry.get_registry()
        shed = float(server.hub.shed_total + server.dropped_results)
        delta, last["shed"] = shed - last["shed"], shed
        maxsize = server.results.maxsize or 1
        p95 = 0.0
        if slo is not None:
            try:
                p95 = float((slo() or {}).get("p95_ms", 0.0))
            except Exception:  # noqa: BLE001 — a dead serving plane is not a signal
                p95 = 0.0
        return FleetSignals(
            fps=reg.meter("server.results_per_s").rate(),
            learn_steps_per_s=reg.meter("rates.learn_steps_per_s").rate(),
            queue_occupancy=server.results.qsize() / maxsize,
            shed_delta=delta,
            serving_p95_ms=p95,
            live_workers=server.live_worker_count(),
        )

    return read


def router_signal_source(router: Any) -> Callable[[], FleetSignals]:
    """Signal reader over a ``ServingRouter`` — the serving-TIER loop,
    where capacity units are replicas and the decision table runs the
    ``serving_scale_up/down_p95_ms`` rules.

    - ``serving_p95_ms``: the router's aggregate end-to-end p95 (admit ->
      client reply, retries and failover included — per-replica p95s
      structurally miss both);
    - ``shed_delta``: router sheds since the previous read (requests no
      routable replica could serve — demand past the tier's capacity, a
      scale-UP signal under tier semantics);
    - ``fps``: the router's request rate meter;
    - ``queue_occupancy`` is pinned mid-band: the occupancy rules encode
      actor-fleet semantics and must stay silent for this tier;
    - ``live_workers``: live replicas (``RouterTierExecutor``'s spawned
      count overrides this inside ``Autoscaler.step``).
    """
    last = {"shed": 0.0}

    def read() -> FleetSignals:
        reg = telemetry.get_registry()
        shed = float(router.shed)
        delta, last["shed"] = shed - last["shed"], shed
        return FleetSignals(
            fps=reg.meter("router.requests_per_s").rate(),
            queue_occupancy=0.5,
            shed_delta=delta,
            serving_p95_ms=float(router.aggregate_p95_ms()),
            live_workers=int(router.replica_count()),
        )

    return read
