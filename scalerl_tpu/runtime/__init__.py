"""Runtime layer: device loop, parameter server, rollout queue.

Lazy exports (PEP 562): ``DeviceActorLearnerLoop`` pulls in the full
JAX/agents/orbax stack (~5 s cold), but fleet workers and spawn-context
children import this package only for the jax-free ``ParameterServer`` /
``RolloutQueue`` — eager imports here would put seconds of dead weight on
every spawned actor process (and every remote CPU fleet host).
"""

from typing import TYPE_CHECKING

if TYPE_CHECKING:  # static analyzers see the real symbols
    from scalerl_tpu.runtime.device_loop import DeviceActorLearnerLoop  # noqa: F401
    from scalerl_tpu.runtime.dispatch import (  # noqa: F401
        MetricsPipeline,
        get_metrics,
        pipelined_drive,
    )
    from scalerl_tpu.runtime.param_server import ParameterServer  # noqa: F401
    from scalerl_tpu.runtime.rollout_queue import RolloutQueue  # noqa: F401
    from scalerl_tpu.runtime.chaos import (  # noqa: F401
        ChaosPlan,
        FaultInjector,
    )
    from scalerl_tpu.runtime.autoscaler import (  # noqa: F401
        Autoscaler,
        AutoscalerConfig,
        FleetSignals,
    )
    from scalerl_tpu.runtime.supervisor import (  # noqa: F401
        CheckpointCadence,
        DivergenceTripwire,
        PreemptionGuard,
        StallError,
        StallWatchdog,
    )
    from scalerl_tpu.runtime.telemetry import (  # noqa: F401
        FlightRecorder,
        MetricsRegistry,
        TelemetryAggregator,
        TelemetryExportLoop,
    )

_EXPORTS = {
    "DeviceActorLearnerLoop": "scalerl_tpu.runtime.device_loop",
    "MetricsPipeline": "scalerl_tpu.runtime.dispatch",
    "get_metrics": "scalerl_tpu.runtime.dispatch",
    "pipelined_drive": "scalerl_tpu.runtime.dispatch",
    "ParameterServer": "scalerl_tpu.runtime.param_server",
    "RolloutQueue": "scalerl_tpu.runtime.rollout_queue",
    "ChaosPlan": "scalerl_tpu.runtime.chaos",
    "FaultInjector": "scalerl_tpu.runtime.chaos",
    "Autoscaler": "scalerl_tpu.runtime.autoscaler",
    "AutoscalerConfig": "scalerl_tpu.runtime.autoscaler",
    "FleetSignals": "scalerl_tpu.runtime.autoscaler",
    "CheckpointCadence": "scalerl_tpu.runtime.supervisor",
    "DivergenceTripwire": "scalerl_tpu.runtime.supervisor",
    "PreemptionGuard": "scalerl_tpu.runtime.supervisor",
    "StallError": "scalerl_tpu.runtime.supervisor",
    "StallWatchdog": "scalerl_tpu.runtime.supervisor",
    "FlightRecorder": "scalerl_tpu.runtime.telemetry",
    "MetricsRegistry": "scalerl_tpu.runtime.telemetry",
    "TelemetryAggregator": "scalerl_tpu.runtime.telemetry",
    "TelemetryExportLoop": "scalerl_tpu.runtime.telemetry",
}

__all__ = list(_EXPORTS)


def __getattr__(name: str):
    module = _EXPORTS.get(name)
    if module is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(module), name)
