from scalerl_tpu.runtime.device_loop import DeviceActorLearnerLoop  # noqa: F401
from scalerl_tpu.runtime.param_server import ParameterServer  # noqa: F401
from scalerl_tpu.runtime.rollout_queue import RolloutQueue  # noqa: F401
