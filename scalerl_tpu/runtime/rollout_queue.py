"""Free/full rollout-slot queue: the host side of the learner infeed.

Parity target: the reference's shared-memory buffer pool cycled through
``free_queue``/``full_queue`` (``impala_atari.py:122-151,416-437``): a fixed
pool of trajectory slots; actors take a free index, fill the slot, put it on
the full queue; the learner drains ``batch_size`` indices, stacks, and
recycles them.

TPU-shaped differences: slots are pinned *numpy* staging buffers (actors
write with zero serialization), and ``get_batch`` assembles one contiguous
time-major batch and ships it device-side in a single transfer — the
reference instead moved per-slot torch tensors and stacked on the learner
(``impala_atari.py:222-268``).  Worker-crash funneling mirrors the vec-env
error plumbing (``pz_async_vec_env.py:467-488``): actors report exceptions
via ``report_error`` and the learner re-raises on the next get.
"""

from __future__ import annotations

import queue
import threading
import weakref
from typing import Dict, List, Optional, Tuple

import numpy as np

from scalerl_tpu.data.trajectory import TrajectorySpec
from scalerl_tpu.runtime import telemetry


class RolloutQueue:
    def __init__(
        self, spec: TrajectorySpec, num_slots: int, max_pending: int = 0
    ) -> None:
        """``max_pending`` > 0 arms bounded admission on the full queue:
        a ``commit`` that would leave more than ``max_pending`` consumable
        slots sheds the STALEST one back to the free pool instead
        (``shed_total``).  Queue depth IS worst-case policy lag (the
        host-plane Breakout stall, docs/PERFORMANCE.md), so a slow learner
        now costs dropped-oldest rollouts — bounded staleness — rather
        than unbounded lag.  0 keeps the old behavior (depth bounded only
        by ``num_slots``)."""
        if num_slots < 2:
            raise ValueError(f"num_slots must be >= 2, got {num_slots}")
        if max_pending < 0:
            raise ValueError(f"max_pending must be >= 0, got {max_pending}")
        self.spec = spec
        self.num_slots = num_slots
        self.max_pending = max_pending
        self.shed_total = 0
        self.slots: List[Dict[str, np.ndarray]] = [
            spec.host_zeros() for _ in range(num_slots)
        ]
        self.free: "queue.Queue[int]" = queue.Queue()
        self.full: "queue.Queue[int]" = queue.Queue()
        for i in range(num_slots):
            self.free.put(i)
        self._error: Optional[BaseException] = None
        self._error_lock = threading.Lock()
        self._closed = threading.Event()
        # telemetry plane: queue occupancy in the merged snapshot (weakref
        # snapshot-time binding — nothing on the acquire/commit hot path)
        q_ref = weakref.ref(self)
        telemetry.get_registry().bind(
            "queue", lambda: (lambda q: q.stats() if q is not None else {"gone": 1})(q_ref())
        )

    # -- actor side ----------------------------------------------------
    def acquire(self, timeout: Optional[float] = None) -> Optional[int]:
        """Take a free slot index (None on shutdown)."""
        while not self._closed.is_set():
            try:
                return self.free.get(timeout=0.1 if timeout is None else timeout)
            except queue.Empty:
                if timeout is not None:
                    return None
        return None

    def commit(self, idx: int) -> None:
        if self.max_pending > 0 and self.full.qsize() >= self.max_pending:
            # bounded admission: recycle the stalest full slot so the
            # freshest rollout is what the learner trains on next
            try:
                stale = self.full.get_nowait()
            except queue.Empty:
                stale = None
            if stale is not None:
                self.free.put(stale)
                self.shed_total += 1
                telemetry.get_registry().counter("queue.shed_total").inc()
        self.full.put(idx)

    def report_error(self, exc: BaseException) -> None:
        telemetry.get_registry().counter("queue.actor_errors").inc()
        telemetry.record_event("actor_error", error=repr(exc))
        with self._error_lock:
            if self._error is None:
                self._error = exc
        self._closed.set()

    # -- learner side --------------------------------------------------
    def _check_error(self) -> None:
        with self._error_lock:
            if self._error is not None:
                raise RuntimeError("actor worker died") from self._error

    def get_batch(
        self, batch_size: int, timeout: Optional[float] = None
    ) -> Tuple[Dict[str, np.ndarray], List[int]]:
        """Drain ``batch_size`` full slots into one [T+1, batch, ...] batch.

        Slots are recycled by the caller via ``recycle`` *after* the batch
        has been shipped to device (the stack below copies, so recycling
        immediately after this returns is also safe).
        """
        idxs: List[int] = []
        try:
            while len(idxs) < batch_size:
                self._check_error()
                try:
                    idxs.append(
                        self.full.get(timeout=0.5 if timeout is None else timeout)
                    )
                except queue.Empty:
                    if self._closed.is_set():
                        self._check_error()
                        raise RuntimeError("rollout queue closed")
                    if timeout is not None:
                        raise TimeoutError(
                            f"get_batch: only {len(idxs)}/{batch_size} slots ready"
                        )
            batch = {
                # core-state keys describe row 0 only: batch axis is 0; the
                # time-major fields batch on axis 1
                k: np.concatenate(
                    [self.slots[i][k] for i in idxs],
                    axis=0 if k.startswith("core_") else 1,
                )
                for k in self.slots[idxs[0]].keys()
            }
        except BaseException:
            # any exit (error funnel, timeout, close, KeyboardInterrupt,
            # a bad slot in the batch build): the drained slots are still
            # full and unconsumed — hand them back, or the pool leaks one
            # slot per exit until acquire() deadlocks.  Re-enqueueing at
            # the tail perturbs FIFO order: rollouts drained here age to
            # the back of the queue and pick up extra policy lag before
            # they are finally consumed.  Acceptable — V-trace corrects
            # bounded lag, and this path only runs on timeouts/teardown —
            # but callers that need strict lag bounds should drain and
            # drop instead of retrying.
            for i in idxs:
                self.full.put(i)
            raise
        return batch, idxs

    def recycle(self, idxs: List[int]) -> None:
        for i in idxs:
            self.free.put(i)

    def stats(self) -> Dict[str, int]:
        """Occupancy snapshot for watchdog stall reports: free/full queue
        depths (approximate under concurrency — qsize is advisory), total
        slots, and how many are in flight (acquired or being consumed)."""
        free, full = self.free.qsize(), self.full.qsize()
        return {
            "slots": self.num_slots,
            "free": free,
            "full": full,
            "in_flight": max(self.num_slots - free - full, 0),
            "shed_total": self.shed_total,
            "closed": int(self._closed.is_set()),
        }

    def close(self) -> None:
        self._closed.set()
