"""Runtime supervision: stall watchdog, preemption-safe checkpoint cadence,
and the liveness/backoff primitives behind fleet heartbeats.

The reference fleet simply forgot dead workers (SURVEY.md §5) and our port
only detected *closed* connections — a silently-dead TCP peer, a wedged
device dispatch, or a TPU preemption meant a silent hang or a lost run.
IMPALA-style actor-learner systems (arxiv 1802.01561) and Podracer-style
TPU-pod training (arxiv 2104.06272) treat liveness detection and
preemption-safe checkpointing as first-class; this module is that substrate,
jax-free so fleet workers and spawn children can import it for pennies:

- ``StallWatchdog`` — a monitor thread over named *progress sources*
  (counters the loops bump, or getter callables).  When nothing advances for
  ``deadline_s`` it dumps **all-thread stacks** via ``faulthandler`` plus any
  registered probes (queue depths, ring occupancy), then either invokes a
  recovery callback or interrupts the main thread so the run fails fast with
  a diagnosis instead of eating a CI budget.
- ``PreemptionGuard`` — SIGTERM/SIGINT land as a flag the training loop
  checks at its next safe point (slot boundary / chunk boundary), triggering
  the existing ``save_resume`` path before a clean exit.  A second signal
  falls through to the previous handler (force-quit stays possible).
- ``CheckpointCadence`` — the "save when due" decision shared by every
  trainer loop: frame-interval (``save_frequency``) OR wall-clock interval
  (``checkpoint_interval_s``), whichever fires first.
- ``exp_backoff`` / ``LivenessTracker`` — capped exponential reconnect
  delays and a last-seen table; ``fleet/hub.py`` and ``fleet/cluster.py``
  build the ping/pong heartbeat plane out of these.
"""

from __future__ import annotations

import faulthandler
import os
import random as _random
import signal
import sys
import tempfile
import threading
import time
from typing import Any, Callable, Dict, Hashable, List, Optional, Tuple

from scalerl_tpu.runtime import telemetry
from scalerl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# Heartbeat frame kinds (fleet wire protocol).  Kept here so transport-level
# filters and protocol handlers agree on one vocabulary.
PING = "ping"
PONG = "pong"

# Drain control frames (fleet elasticity plane): the server tells a gather to
# stop starting episodes, return unstarted tasks, flush retained uploads, and
# close cleanly — the scale-down / spot-preemption path that loses zero
# episodes (kill-and-respawn is the crash path; this is the deliberate one).
DRAIN = "drain"
DRAIN_DONE = "drain_done"


def make_ping() -> Dict[str, Any]:
    return {"kind": PING, "t": time.time()}


def make_pong(ping_msg: Dict[str, Any]) -> Dict[str, Any]:
    # echoes the ping's send time and adds the responder's wall clock +
    # host id: the pinger gets (t_send, t_peer, t_recv) per heartbeat —
    # exactly the NTP-style sample runtime/tracing.py's ClockSkewEstimator
    # needs to align multi-host trace timelines, with zero extra traffic
    return {
        "kind": PONG,
        "t": ping_msg.get("t", 0.0),
        "rt": time.time(),
        "host": telemetry.host_id(),
    }


def is_heartbeat(msg: Any) -> bool:
    return isinstance(msg, dict) and msg.get("kind") in (PING, PONG)


def make_drain() -> Dict[str, Any]:
    return {"kind": DRAIN, "t": time.time()}


def is_drain(msg: Any) -> bool:
    return isinstance(msg, dict) and msg.get("kind") == DRAIN


def exp_backoff(
    attempt: int,
    base: float = 0.5,
    cap: float = 10.0,
    jitter: bool = False,
    rng: Optional[Any] = None,
) -> float:
    """Capped exponential delay for reconnect attempt ``attempt`` (0-based).

    Default is deterministic (no jitter): fleet tests assert the schedule,
    and the handful of gathers per host cannot thundering-herd a learner.

    ``jitter=True`` opts into DECORRELATED jitter for paths where many
    peers share one failure clock — a dead serving replica puts every
    router probe and every fallen-back client on the same schedule, and
    synchronized redials arrive as a reconnect storm.  The draw is uniform
    in ``[base, min(cap, 3 * prev)]`` where ``prev`` is the deterministic
    delay of the previous attempt (the stateless rendering of the classic
    decorrelated-jitter recurrence ``sleep = rand(base, 3 * sleep_prev)``),
    so delays stay capped and attempt-ordered in expectation while peers
    spread out.  ``rng`` (anything with ``.uniform``) pins the stream for
    deterministic tests; default is the process-global ``random``.
    """
    if base <= 0:
        return 0.0
    if not jitter:
        return min(cap, base * (2.0 ** max(attempt, 0)))
    prev = min(cap, base * (2.0 ** max(attempt - 1, 0)))
    hi = max(min(cap, 3.0 * prev), base)
    return (rng if rng is not None else _random).uniform(base, hi)


class LivenessTracker:
    """Thread-safe last-seen table: ``beat(key)`` on any traffic,
    ``stale(timeout)`` lists keys silent for longer than ``timeout``."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._seen: Dict[Hashable, float] = {}

    def beat(self, key: Hashable) -> None:
        with self._lock:
            self._seen[key] = time.monotonic()

    def forget(self, key: Hashable) -> None:
        with self._lock:
            self._seen.pop(key, None)

    def last_seen(self, key: Hashable) -> Optional[float]:
        with self._lock:
            return self._seen.get(key)

    def stale(self, timeout: float) -> List[Hashable]:
        now = time.monotonic()
        with self._lock:
            return [k for k, t in self._seen.items() if now - t > timeout]


# ---------------------------------------------------------------------------
# stall watchdog


class StallError(RuntimeError):
    """No registered progress source advanced within the deadline."""


class ProgressCounter:
    """Monotonic counter a hot loop bumps; reads are lock-free snapshots.

    A torn read costs at most one extra watchdog poll — never a false
    stall — so ``bump`` stays cheap enough for per-chunk call sites.
    """

    __slots__ = ("name", "_value")

    def __init__(self, name: str) -> None:
        self.name = name
        self._value = 0

    def bump(self, n: int = 1) -> None:
        self._value += n

    @property
    def value(self) -> int:
        return self._value


class StallWatchdog:
    """Monitor thread that dumps all-thread stacks when progress stops.

    Progress sources are ``counter(name)`` objects the supervised loops bump
    and/or ``watch(name, fn)`` getters (e.g. ``lambda: trainer.env_frames``).
    Any source changing value between polls counts as progress.  After
    ``deadline_s`` with no change the watchdog fires ONCE per stall:

    1. writes a report — source values, probe outputs (queue depths, ring
       occupancy), and a ``faulthandler`` dump of every thread — to
       ``dump_path`` (default: a temp file) and the module logger;
    2. records it as ``self.stalled`` (``check()`` re-raises it in the
       supervised loop);
    3. invokes ``on_stall(StallError)`` when given — the recovery hook that
       can feed an elastic-restart budget — otherwise interrupts the main
       thread so a wedged-but-interruptible loop dies fast with a diagnosis.

    A loop blocked in an uninterruptible C call (a wedged device dispatch)
    cannot be unwound from Python; the dump still lands, which is the point:
    the run fails *diagnosed*.  If sources advance again after a fire the
    watchdog re-arms.
    """

    def __init__(
        self,
        deadline_s: float,
        poll_s: Optional[float] = None,
        on_stall: Optional[Callable[[StallError], None]] = None,
        dump_path: Optional[str] = None,
        interrupt_main: bool = True,
        name: str = "watchdog",
    ) -> None:
        if deadline_s <= 0:
            raise ValueError(f"deadline_s must be positive, got {deadline_s}")
        self.deadline_s = float(deadline_s)
        self.poll_s = poll_s if poll_s is not None else max(
            min(deadline_s / 4.0, 1.0), 0.01
        )
        self.on_stall = on_stall
        self.dump_path = dump_path
        self.interrupt_main = interrupt_main
        self.name = name
        self.stalled: Optional[StallError] = None
        self.fire_count = 0
        self.flight_dump_path: Optional[str] = None  # set on first fire
        self._counters: List[ProgressCounter] = []
        self._watches: List[Tuple[str, Callable[[], Any]]] = []
        self._probes: List[Tuple[str, Callable[[], Any]]] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- registration --------------------------------------------------
    def counter(self, name: str) -> ProgressCounter:
        c = ProgressCounter(name)
        with self._lock:
            self._counters.append(c)
        return c

    def watch(self, name: str, fn: Callable[[], Any]) -> None:
        """Register an external progress getter (read every poll)."""
        with self._lock:
            self._watches.append((name, fn))

    def add_probe(self, name: str, fn: Callable[[], Any]) -> None:
        """Extra state for the stall report only (never drives liveness):
        queue depths, ring occupancy, in-flight task counts."""
        with self._lock:
            self._probes.append((name, fn))

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "StallWatchdog":
        if self._thread is not None:
            return self
        # telemetry plane: the watchdog's verdict state is part of the
        # merged snapshot (supervisor.<name>.fire_count/stalled)
        telemetry.get_registry().bind(
            f"supervisor.{self.name}",
            lambda: {
                "fire_count": self.fire_count,
                "stalled": int(self.stalled is not None),
                "deadline_s": self.deadline_s,
            },
        )
        self._thread = threading.Thread(
            target=self._monitor, name=f"stall-{self.name}", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def __enter__(self) -> "StallWatchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def check(self) -> None:
        """Raise the recorded ``StallError`` (call from the supervised loop)."""
        if self.stalled is not None:
            raise self.stalled

    # -- internals -----------------------------------------------------
    def _snapshot(self) -> Dict[str, Any]:
        with self._lock:
            counters = list(self._counters)
            watches = list(self._watches)
        snap: Dict[str, Any] = {c.name: c.value for c in counters}
        for name, fn in watches:
            try:
                snap[name] = fn()
            except Exception as e:  # noqa: BLE001 — a dying getter is itself a stall symptom
                snap[name] = f"<error: {e!r}>"
        return snap

    def _monitor(self) -> None:
        last = self._snapshot()
        last_progress = time.monotonic()
        fired = False
        while not self._stop.wait(self.poll_s):
            snap = self._snapshot()
            if snap != last or not snap:
                last = snap
                last_progress = time.monotonic()
                fired = False  # progress resumed: re-arm
                continue
            stalled_for = time.monotonic() - last_progress
            if stalled_for >= self.deadline_s and not fired:
                fired = True
                self._fire(snap, stalled_for)

    def _fire(self, snap: Dict[str, Any], stalled_for: float) -> None:
        self.fire_count += 1
        telemetry.record_event(
            "watchdog_stall", watchdog=self.name, stalled_for_s=round(stalled_for, 1)
        )
        report = self._build_report(snap, stalled_for)
        # the flight recorder tail also lands as JSON next to the stack dump
        self.flight_dump_path = telemetry.get_recorder().dump_json(
            telemetry.flight_dump_path(f"stall_{self.name}")
        )
        logger.error("%s", report)
        err = StallError(report)
        self.stalled = err
        if self.on_stall is not None:
            try:
                self.on_stall(err)
            except Exception:  # noqa: BLE001 — recovery must not kill the monitor
                logger.exception("watchdog %s: on_stall callback failed", self.name)
        elif self.interrupt_main:
            import _thread

            _thread.interrupt_main()

    def _build_report(self, snap: Dict[str, Any], stalled_for: float) -> str:
        with self._lock:
            probes = list(self._probes)
        lines = [
            f"=== StallWatchdog[{self.name}]: no progress for "
            f"{stalled_for:.1f}s (deadline {self.deadline_s:.1f}s) ===",
            f"progress sources (frozen): {snap}",
        ]
        for name, fn in probes:
            try:
                value = fn()
                lines.append(f"probe {name}: {value}")
                telemetry.record_event(
                    "watchdog_probe", watchdog=self.name, probe=name, value=str(value)
                )
            except Exception as e:  # noqa: BLE001 — report what we can
                lines.append(f"probe {name}: <error: {e!r}>")
        lines.append("--- flight recorder (recent events) ---")
        lines.append(telemetry.get_recorder().dump_text())
        lines.append("--- all-thread stacks (faulthandler) ---")
        lines.append(self._dump_stacks())
        return "\n".join(lines)

    def _dump_stacks(self) -> str:
        """faulthandler writes to a real fd; round-trip through a file so the
        stacks also land in the report string (and thus the logger/callback)."""
        path = self.dump_path
        try:
            if path is None:
                fd, path = tempfile.mkstemp(prefix="scalerl_stall_", suffix=".txt")
                os.close(fd)
                self.dump_path = path
            with open(path, "w") as f:
                faulthandler.dump_traceback(file=f, all_threads=True)
            with open(path, "r") as f:
                return f.read()
        except Exception as e:  # noqa: BLE001 — a dump failure must not mask the stall
            return f"<faulthandler dump failed: {e!r}>"


# ---------------------------------------------------------------------------
# preemption-safe checkpointing


class PreemptionGuard:
    """Convert SIGTERM/SIGINT into a "save at the next safe point" flag.

    Training loops poll ``triggered`` at slot/chunk boundaries and run the
    existing ``save_resume`` path before exiting cleanly — a TPU preemption
    (SIGTERM from the scheduler) or Ctrl-C becomes a resumable checkpoint,
    not a lost run.  The SECOND occurrence of a signal falls through to the
    previously-installed handler (default: kill), so a wedged loop can still
    be force-quit.

    Signal handlers only install from the main thread; elsewhere
    ``install()`` is a no-op and ``triggered`` stays False (trainer loops
    embedded in worker threads keep their old behavior).  Use as a context
    manager so handlers are restored on exit.
    """

    def __init__(self, signals: Tuple[int, ...] = (signal.SIGTERM, signal.SIGINT)) -> None:
        self.signals = signals
        self._event = threading.Event()
        self._prev: Dict[int, Any] = {}
        self._installed = False
        self.received: Optional[int] = None
        self.flight_dump_path: Optional[str] = None  # set on first signal

    @property
    def triggered(self) -> bool:
        return self._event.is_set()

    def _handler(self, signum, frame) -> None:
        if self._event.is_set():
            # second signal: the user/scheduler means it — fall through
            prev = self._prev.get(signum)
            if callable(prev):
                prev(signum, frame)
                return
            if prev == signal.SIG_DFL:
                signal.signal(signum, signal.SIG_DFL)
                os.kill(os.getpid(), signum)
            return
        self.received = signum
        self._event.set()
        try:
            name = signal.Signals(signum).name
        except ValueError:
            name = str(signum)
        # flight recorder: the preemption is itself an event, and the tail
        # of everything that led up to it lands as JSON immediately — the
        # "save at next safe point" may never run if the loop is wedged
        telemetry.record_event("preemption_signal", signal=name)
        self.flight_dump_path = telemetry.get_recorder().dump_json(
            telemetry.flight_dump_path(f"signal_{name.lower()}")
        )
        # signal-safe enough: one write, no allocation-heavy formatting
        sys.stderr.write(
            f"[scalerl] caught {name}: checkpointing at next safe point "
            "(repeat to force-quit; flight recorder -> "
            f"{self.flight_dump_path})\n"
        )

    def install(self) -> "PreemptionGuard":
        if threading.current_thread() is not threading.main_thread():
            return self  # signal API is main-thread-only; stay inert
        if self._installed:
            return self
        for s in self.signals:
            try:
                self._prev[s] = signal.signal(s, self._handler)
            except (ValueError, OSError):  # non-main interpreter oddities
                self._prev.pop(s, None)
        self._installed = True
        return self

    def restore(self) -> None:
        if not self._installed:
            return
        for s, prev in self._prev.items():
            try:
                signal.signal(s, prev)
            except (ValueError, OSError):
                pass
        self._prev.clear()
        self._installed = False

    def simulate(self, signum: int = signal.SIGTERM) -> None:
        """Trip the guard as if ``signum`` arrived — the chaos ``preempt``
        hook and the threads that cannot own signal handlers use this, so
        every consumer sees one shape of preemption: the flag."""
        self._handler(signum, None)

    def poll_chaos(self, site: str) -> bool:
        """One seeded ``preempt`` draw at a safe point.  When the stream
        fires, the preemption is delivered as a REAL ``SIGTERM`` to this
        process when our handler is installed (the seeded fault walks the
        genuine signal path), or via :meth:`simulate` otherwise.  Returns
        ``triggered`` either way, so loops can write
        ``if guard.poll_chaos("learner"): save_and_exit()``."""
        if not self._event.is_set():
            from scalerl_tpu.runtime import chaos

            inj = chaos.active()
            if inj is not None and inj.preempt_victim(1, site=site) is not None:
                if self._installed:
                    signal.raise_signal(signal.SIGTERM)
                else:
                    self.simulate()
        return self._event.is_set()

    def __enter__(self) -> "PreemptionGuard":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.restore()


class DivergenceTripwire:
    """Host-side divergence breaker over the guarded train step's metrics.

    The jitted all-finite guard (``parallel/train_step.py``) already skips
    individual non-finite updates; this tripwire watches the
    ``skipped_steps`` counter it emits and, after ``k`` CONSECUTIVE bad
    steps (a diverged run, not a single poisoned batch), invokes the
    rollback callback — typically "restore agent state from the last good
    checkpoint" (``OffPolicyTrainer._divergence_rollback``).  jax-free: it
    consumes the already-materialized host metrics dict, adding zero device
    traffic.
    """

    def __init__(self, k: int, on_trip: Optional[Callable[[], None]]) -> None:
        self.k = int(k)
        self.on_trip = on_trip
        self.consecutive = 0
        self.trips = 0

    @property
    def enabled(self) -> bool:
        return self.k > 0 and self.on_trip is not None

    def observe(self, metrics: Optional[Dict[str, Any]]) -> bool:
        """Feed one step's host metrics; True when the rollback fired."""
        bad = 0.0
        if metrics:
            try:
                bad = float(metrics.get("skipped_steps", 0.0) or 0.0)
            except (TypeError, ValueError):
                bad = 0.0
        if bad > 0.0:
            self.consecutive += 1
        else:
            self.consecutive = 0
        if self.enabled and self.consecutive >= self.k:
            self.consecutive = 0
            self.trips += 1
            telemetry.get_registry().counter("supervisor.divergence_trips").inc()
            telemetry.record_event("divergence_trip", trips=self.trips, k=self.k)
            # flight tail alongside the rollback (the events leading into a
            # divergence are exactly what a post-mortem wants)
            telemetry.get_recorder().dump_json(
                telemetry.flight_dump_path("divergence")
            )
            self.on_trip()
            return True
        return False


class CheckpointCadence:
    """When is a resume save due?  Frame interval OR wall-clock interval.

    One implementation for every trainer loop: ``save_frequency`` (frames)
    is the reference-parity gate; ``checkpoint_interval_s`` (seconds) is the
    preemption-era gate that bounds lost work on slow-frame runs.  Either
    firing makes the save due; ``mark_saved`` resets both.  ``interval_s``
    (or ``frames``) <= 0 disables that gate.
    """

    def __init__(self, frames: int, interval_s: float, start_frames: int = 0) -> None:
        self.frames = int(frames)
        self.interval_s = float(interval_s)
        self._last_frames = int(start_frames)
        self._last_t = time.monotonic()

    def due(self, current_frames: int) -> bool:
        if self.frames > 0 and current_frames - self._last_frames >= self.frames:
            return True
        if self.interval_s > 0 and time.monotonic() - self._last_t >= self.interval_s:
            return True
        return False

    def mark_saved(self, current_frames: int) -> None:
        self._last_frames = int(current_frames)
        self._last_t = time.monotonic()
