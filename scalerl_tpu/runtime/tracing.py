"""Cross-tier distributed tracing: spans over the fleet wire.

Telemetry (``runtime/telemetry.py``) answers aggregate questions — what is
p95, how many sheds — but never causal ones: *why* did this sequence take
900 ms?  Queue wait, decode, a reconnect retransmit, or replay backlog?
SEED RL and MindSpeed RL (PAPERS.md, arxiv 2507.19017) both argue the
actor/generation/learner tiers bottleneck each other in non-obvious ways;
per-request causality across process boundaries is the substrate every
"compose the planes" tuning decision stands on.  This module is that
substrate, in the telemetry idiom:

- :class:`Span` / :class:`SpanContext` — trace_id/span_id/parent_id plus
  ``host_id`` and **host-side monotonic timestamps only** (graftlint JG001
  twin: a span must never force a device read to stamp a time).  Wall-clock
  is derived once per process from a (wall, monotonic) anchor, so a
  mid-run NTP step cannot corrupt durations, and cross-host alignment is a
  single per-host offset the :class:`ClockSkewEstimator` measures off the
  heartbeat ping/pong RTTs that already flow.
- **Head-based sampling** — the decision is made once at the trace ROOT
  (``SCALERL_TRACE_SAMPLE=<rate>``, default 0.0: hot loops pay nothing);
  every descendant follows its parent's decision because a span with a
  remote parent context is always recorded.  Finished spans land in a
  bounded ring (``SCALERL_TRACE_SPANS``), so overhead is O(1) like the
  FlightRecorder.
- **Context propagation piggybacked on existing frames** — the codec-v2
  message dicts gain an optional ``"trace"`` key the same way ``_telem``
  rides result uploads: serving ``act`` requests, fleet task leases,
  disagg ``seq_batch`` uploads, and snapshot pushes all carry their parent
  context with zero new round-trips (:func:`inject` / :func:`extract`).
- **Retroactive spans** (:func:`record_span`) — instrumentation sites
  stamp ``time.monotonic()`` at the boundaries they already cross and emit
  the span after the fact, so tracing never adds a blocking call to a hot
  loop.
- **Per-host JSONL export** — when ``SCALERL_TRACE_DIR`` is set every
  finished span is appended (line-buffered) to
  ``spans_<host>.jsonl``, so a SIGTERM'd generation host loses at most the
  span it was writing; ``tools/trace_report.py`` merges the files,
  reconstructs trace trees, emits Chrome ``trace_event`` JSON, and prints
  the critical-path breakdown.

jax-free by design: fleet workers, generation-host shells, and spawn
children import this for pennies, and nothing here can ever issue a device
transfer.  The FlightRecorder link is the other direction: this module
registers a trace-id provider with ``telemetry``, so every flight event
recorded while a span is active carries the active ``trace`` id — fault
forensics link both ways.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Mapping, Optional

from scalerl_tpu.runtime import telemetry
from scalerl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

ENV_SAMPLE = "SCALERL_TRACE_SAMPLE"
ENV_DIR = "SCALERL_TRACE_DIR"
ENV_SPANS = "SCALERL_TRACE_SPANS"

# the wire piggyback key: any protocol dict may carry one
# {"tid": ..., "sid": ...} context under this key (docs/OBSERVABILITY.md
# "Distributed tracing" documents the shape)
TRACE_KEY = "trace"

# one (wall, monotonic) anchor per process: every span's wall time is
# anchor_wall + (t_mono - anchor_mono), so a wall-clock step mid-run moves
# NOTHING (the timers.py lesson) and cross-host alignment reduces to one
# per-host offset
_ANCHOR_WALL = time.time()
_ANCHOR_MONO = time.monotonic()


def wall_of(t_mono: float) -> float:
    """Map a ``time.monotonic()`` stamp onto this process's wall anchor."""
    return _ANCHOR_WALL + (t_mono - _ANCHOR_MONO)


def new_id() -> str:
    return os.urandom(8).hex()


class SpanContext:
    """The propagated identity of a span: (trace_id, span_id)."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str) -> None:
        self.trace_id = trace_id
        self.span_id = span_id

    def to_wire(self) -> Dict[str, str]:
        return {"tid": self.trace_id, "sid": self.span_id}

    @classmethod
    def from_wire(cls, node: Any) -> Optional["SpanContext"]:
        if not isinstance(node, Mapping):
            return None
        tid, sid = node.get("tid"), node.get("sid")
        if not (isinstance(tid, str) and isinstance(sid, str)):
            return None
        return cls(tid, sid)

    def __repr__(self) -> str:  # debugging aid in stall dumps
        return f"SpanContext({self.trace_id}/{self.span_id})"


class Span:
    """One recorded operation.  Created by :meth:`Tracer.start_span`;
    ``end()`` (idempotent) hands it to the tracer's ring + sink."""

    __slots__ = (
        "name", "kind", "trace_id", "span_id", "parent_id", "host",
        "t_start", "t_end", "attrs", "_tracer", "_ended",
    )
    sampled = True

    def __init__(
        self,
        tracer: "Tracer",
        name: str,
        trace_id: str,
        span_id: str,
        parent_id: Optional[str],
        kind: str,
        t_start: float,
        attrs: Dict[str, Any],
    ) -> None:
        self._tracer = tracer
        self.name = name
        self.kind = kind
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.host = telemetry.host_id()
        self.t_start = t_start  # monotonic
        self.t_end: Optional[float] = None
        self.attrs = attrs
        self._ended = False

    @property
    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def end(self, t_end: Optional[float] = None, **attrs: Any) -> None:
        """Finish the span at ``t_end`` (``time.monotonic()``, default now).
        Host-side stamps ONLY — never materialize a device value to end a
        span (the JG001 fixture pair pins this)."""
        if self._ended:
            return
        self._ended = True
        if attrs:
            self.attrs.update(attrs)
        self.t_end = t_end if t_end is not None else time.monotonic()
        self._tracer._finish(self)

    def to_record(self) -> Dict[str, Any]:
        t_end = self.t_end if self.t_end is not None else self.t_start
        return {
            "trace": self.trace_id,
            "span": self.span_id,
            "parent": self.parent_id,
            "name": self.name,
            "kind": self.kind,
            "host": self.host,
            "t0": wall_of(self.t_start),
            "dur": max(t_end - self.t_start, 0.0),
            "attrs": self.attrs,
        }

    # context-manager protocol: activates the span for FlightRecorder
    # trace stamping, ends it on exit
    def __enter__(self) -> "Span":
        self._tracer._push_active(self)
        return self

    def __exit__(self, *exc: Any) -> None:
        self._tracer._pop_active(self)
        self.end()


class _NoopSpan:
    """The unsampled root: every operation is a no-op, ``context`` is None
    so :func:`inject` stays silent and descendants stay unsampled."""

    __slots__ = ()
    sampled = False
    context = None
    trace_id = None

    def end(self, t_end: Optional[float] = None, **attrs: Any) -> None:
        pass

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc: Any) -> None:
        pass


NOOP_SPAN = _NoopSpan()


def _context_of(parent: Any) -> Optional[SpanContext]:
    """Normalize a parent argument: Span, SpanContext, wire dict, or None."""
    if parent is None or parent is NOOP_SPAN:
        return None
    if isinstance(parent, SpanContext):
        return parent
    ctx = getattr(parent, "context", None)
    if isinstance(ctx, SpanContext):
        return ctx
    return SpanContext.from_wire(parent)


class Tracer:
    """Head-sampling span factory with a bounded finished-span ring and an
    optional per-host JSONL sink (``SCALERL_TRACE_DIR``)."""

    def __init__(
        self,
        sample_rate: Optional[float] = None,
        capacity: Optional[int] = None,
        out_dir: Optional[str] = None,
    ) -> None:
        if sample_rate is None:
            sample_rate = float(os.environ.get(ENV_SAMPLE, "0") or 0.0)
        if capacity is None:
            capacity = int(os.environ.get(ENV_SPANS, "4096") or 4096)
        self.sample_rate = max(0.0, min(float(sample_rate), 1.0))
        self.capacity = max(int(capacity), 1)
        self.out_dir = out_dir if out_dir is not None else os.environ.get(
            ENV_DIR, ""
        )
        self._lock = threading.Lock()
        self._ring: Deque[Dict[str, Any]] = deque()
        self.dropped = 0
        self._sink = None
        self._sink_path: Optional[str] = None
        self._tls = threading.local()
        self._rng = random.Random(os.urandom(8))
        self._listeners: List[Callable[[Dict[str, Any]], None]] = []

    # -- sampling + span creation ---------------------------------------
    def _sample(self) -> bool:
        if self.sample_rate <= 0.0:
            return False
        if self.sample_rate >= 1.0:
            return True
        return self._rng.random() < self.sample_rate

    def start_span(
        self,
        name: str,
        parent: Any = None,
        kind: str = "",
        t_start: Optional[float] = None,
        **attrs: Any,
    ):
        """A new span.  ``parent`` is a Span, SpanContext, wire dict, or
        None; with None the HEAD sampling decision is made here (rate 0 =
        free no-op), with a parent the span always records — descendants
        follow their root's decision across process boundaries.
        ``t_start`` is an optional ``time.monotonic()`` stamp for
        retroactive spans."""
        ctx = _context_of(parent)
        if ctx is None:
            if not self._sample():
                return NOOP_SPAN
            trace_id, parent_id = new_id(), None
        else:
            trace_id, parent_id = ctx.trace_id, ctx.span_id
        span = Span(
            self,
            name,
            trace_id,
            new_id(),
            parent_id,
            kind,
            t_start if t_start is not None else time.monotonic(),
            dict(attrs),
        )
        telemetry.get_registry().counter("trace.spans_started").inc()
        return span

    # -- finished-span plumbing -----------------------------------------
    def _finish(self, span: Span) -> None:
        rec = span.to_record()
        reg = telemetry.get_registry()
        reg.counter("trace.spans_finished").inc()
        with self._lock:
            if len(self._ring) >= self.capacity:
                self._ring.popleft()
                self.dropped += 1
                reg.counter("trace.spans_dropped").inc()
            self._ring.append(rec)
            self._sink_write(rec)
            listeners = list(self._listeners)
        # outside the ring lock: a listener (the TierLedger's online feed)
        # may take its own locks and must never be able to deadlock a span
        # end against finished()/clear()
        for fn in listeners:
            try:
                fn(rec)
            except Exception as e:  # noqa: BLE001 — a listener must never kill a span site
                logger.warning("trace listener failed: %r", e)

    def add_listener(self, fn: Callable[[Dict[str, Any]], None]) -> None:
        """Subscribe ``fn`` to every finished-span record (called after the
        ring append, outside the ring lock).  This is how the streaming
        tier attribution (``runtime/attribution.py``) consumes spans ONLINE
        without polling the bounded ring — same records the JSONL sink
        writes, zero extra stamps."""
        with self._lock:
            if fn not in self._listeners:
                self._listeners.append(fn)

    def remove_listener(self, fn: Callable[[Dict[str, Any]], None]) -> None:
        with self._lock:
            try:
                self._listeners.remove(fn)
            except ValueError:
                pass

    def finished(self) -> List[Dict[str, Any]]:
        """The retained span records, oldest first (bounded ring)."""
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    # -- active-span stack (FlightRecorder linkage) ---------------------
    def _push_active(self, span: Span) -> None:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        stack.append(span)

    def _pop_active(self, span: Span) -> None:
        stack = getattr(self._tls, "stack", None)
        if stack and stack[-1] is span:
            stack.pop()

    def current_span(self):
        stack = getattr(self._tls, "stack", None)
        return stack[-1] if stack else None

    def activate(self, parent: Any):
        """Context manager: make ``parent`` (Span/SpanContext/wire dict)
        the active trace for this thread WITHOUT creating a new span —
        flight events recorded inside carry its trace id."""
        return _Activation(self, _context_of(parent))

    # -- the per-host JSONL sink ----------------------------------------
    def _ensure_sink(self) -> bool:
        # called under self._lock; opens the per-host file + meta line once
        if self._sink is not None:
            return True
        try:
            os.makedirs(self.out_dir, exist_ok=True)
            host = "".join(
                ch if ch.isalnum() or ch in "-_" else "_"
                for ch in telemetry.host_id()
            )
            self._sink_path = os.path.join(
                self.out_dir, f"spans_{host}_{os.getpid()}.jsonl"
            )
            self._sink = open(self._sink_path, "a", buffering=1)
            self._sink.write(
                json.dumps(
                    {
                        "kind": "meta",
                        "host": telemetry.host_id(),
                        "pid": os.getpid(),
                        "anchor_wall": _ANCHOR_WALL,
                    },
                    default=str,
                )
                + "\n"
            )
            return True
        except Exception as e:  # noqa: BLE001 — the sink must never kill a span site
            logger.warning("trace sink open failed: %r", e)
            self.out_dir = ""
            return False

    # "meta"/"skew" are span-file record kinds consumed offline by
    # tools/trace_report.py, not codec-v2 wire frames — no recv pump ever
    # dispatches on them.  # graftlint: wire-ignore=meta,skew
    def _sink_write(self, obj: Dict[str, Any]) -> None:
        # called under self._lock.  Line-per-record append on a
        # line-buffered file: a SIGTERM'd host (no atexit) loses at most
        # the line in flight.
        if not self.out_dir or not self._ensure_sink():
            return
        try:
            self._sink.write(json.dumps(obj, default=str) + "\n")
        except Exception as e:  # noqa: BLE001
            logger.warning("trace sink write failed: %r", e)
            self.out_dir = ""  # stop retrying a broken sink

    def export_skew(self, estimator: Optional["ClockSkewEstimator"] = None) -> None:
        """Append this process's per-peer clock-skew offsets to the span
        file (``trace_report`` aligns other hosts' spans with them)."""
        est = estimator if estimator is not None else get_skew()
        with self._lock:
            if not self.out_dir:
                return
            self._sink_write(
                {"kind": "skew", "host": telemetry.host_id(),
                 "offsets": est.offsets()}
            )

    def close(self) -> None:
        with self._lock:
            if self._sink is not None:
                try:
                    self._sink.close()
                except Exception:  # noqa: BLE001 — teardown
                    pass
                self._sink = None


class _Activation:
    __slots__ = ("_tracer", "_ctx", "_span")

    def __init__(self, tracer: Tracer, ctx: Optional[SpanContext]) -> None:
        self._tracer = tracer
        self._ctx = ctx
        self._span = None

    def __enter__(self) -> Optional[SpanContext]:
        if self._ctx is not None:
            # a context-only activation rides the same stack as real spans
            holder = _CtxHolder(self._ctx)
            self._span = holder
            self._tracer._push_active(holder)
        return self._ctx

    def __exit__(self, *exc: Any) -> None:
        if self._span is not None:
            self._tracer._pop_active(self._span)


class _CtxHolder:
    """A stack entry for :meth:`Tracer.activate`: carries a trace id
    without being a recordable span."""

    __slots__ = ("trace_id", "context")
    sampled = True

    def __init__(self, ctx: SpanContext) -> None:
        self.trace_id = ctx.trace_id
        self.context = ctx


# ---------------------------------------------------------------------------
# wire propagation


def inject(msg: Dict[str, Any], parent: Any) -> Dict[str, Any]:
    """Stamp ``msg[TRACE_KEY]`` with the parent's context (no-op for
    unsampled/None parents).  Returns ``msg`` for chaining."""
    ctx = _context_of(parent)
    if ctx is not None and isinstance(msg, dict):
        msg[TRACE_KEY] = ctx.to_wire()
    return msg


def extract(msg: Any) -> Optional[SpanContext]:
    """The propagated context riding ``msg`` (dict with a ``trace`` key),
    or None.  Never mutates the message."""
    if not isinstance(msg, Mapping):
        return None
    return SpanContext.from_wire(msg.get(TRACE_KEY))


# ---------------------------------------------------------------------------
# clock-skew estimation off the existing heartbeat ping/pong RTTs


class ClockSkewEstimator:
    """Per-peer wall-clock offset from (ping t_send, pong rt, recv time).

    The classic NTP bound: ``offset = t_peer - (t_send + rtt / 2)``.  The
    sample taken at the smallest observed RTT is the tightest bound, so
    that one wins (an EWMA would let slow, asymmetric samples smear it).
    Offsets are measured at the OBSERVER — ``trace_report`` subtracts
    ``offset[host]`` from that host's span times to align every file on
    the observer's clock.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # peer -> (best_rtt, offset_at_best_rtt, samples)
        self._peers: Dict[str, List[float]] = {}

    def observe(
        self, peer: str, t_send: float, t_peer: float, t_recv: float
    ) -> None:
        rtt = max(t_recv - t_send, 0.0)
        offset = t_peer - (t_send + rtt / 2.0)
        with self._lock:
            entry = self._peers.get(peer)
            if entry is None:
                self._peers[peer] = [rtt, offset, 1.0]
            else:
                entry[2] += 1.0
                if rtt <= entry[0]:
                    entry[0], entry[1] = rtt, offset

    def offset(self, peer: str) -> float:
        with self._lock:
            entry = self._peers.get(peer)
            return entry[1] if entry is not None else 0.0

    def offsets(self) -> Dict[str, float]:
        with self._lock:
            return {p: e[1] for p, e in self._peers.items()}

    def samples(self, peer: str) -> int:
        with self._lock:
            entry = self._peers.get(peer)
            return int(entry[2]) if entry is not None else 0


def observe_pong(msg: Mapping[str, Any], t_recv: Optional[float] = None) -> None:
    """Feed one heartbeat pong into the default skew estimator.  Pongs
    carry the original ping's wall ``t`` plus the responder's ``rt`` and
    ``host`` (``supervisor.make_pong``); the hub calls this from its recv
    pump, so every heartbeat interval refreshes every link's offset with
    zero extra traffic."""
    if not isinstance(msg, Mapping):
        return
    peer, t_send, t_peer = msg.get("host"), msg.get("t"), msg.get("rt")
    if not peer or not isinstance(t_send, (int, float)) or not isinstance(
        t_peer, (int, float)
    ):
        return
    get_skew().observe(
        str(peer), float(t_send), float(t_peer),
        t_recv if t_recv is not None else time.time(),
    )


# ---------------------------------------------------------------------------
# process-wide defaults

_LOCK = threading.Lock()
_TRACER: Optional[Tracer] = None
_SKEW: Optional[ClockSkewEstimator] = None


def get_tracer() -> Tracer:
    global _TRACER
    if _TRACER is None:
        with _LOCK:
            if _TRACER is None:
                _TRACER = Tracer()
    return _TRACER


def get_skew() -> ClockSkewEstimator:
    global _SKEW
    if _SKEW is None:
        with _LOCK:
            if _SKEW is None:
                _SKEW = ClockSkewEstimator()
    return _SKEW


def reset() -> None:
    """Fresh default tracer + skew estimator, re-reading the env (tests)."""
    global _TRACER, _SKEW
    with _LOCK:
        if _TRACER is not None:
            _TRACER.close()
        _TRACER = Tracer()
        _SKEW = ClockSkewEstimator()


def start_span(name: str, parent: Any = None, kind: str = "", **attrs: Any):
    return get_tracer().start_span(name, parent=parent, kind=kind, **attrs)


def record_span(
    name: str,
    parent: Any,
    t_start: float,
    t_end: float,
    kind: str = "",
    **attrs: Any,
):
    """One-shot retroactive span from two ``time.monotonic()`` stamps the
    call site already took — the sanctioned hot-path idiom (the JG001
    good twin): no device value, no extra syscalls inside the loop."""
    span = get_tracer().start_span(
        name, parent=parent, kind=kind, t_start=t_start, **attrs
    )
    span.end(t_end=t_end)
    return span


def current_trace_id() -> Optional[str]:
    span = get_tracer().current_span()
    return getattr(span, "trace_id", None) if span is not None else None


def sampling_enabled() -> bool:
    """Cheap hot-loop predicate: is there any chance a root samples?"""
    return get_tracer().sample_rate > 0.0


def export_skew() -> None:
    get_tracer().export_skew()


# FlightRecorder linkage: every flight event recorded while a span (or an
# activate()d context) is live on this thread carries its trace id
telemetry.set_trace_id_provider(current_trace_id)
