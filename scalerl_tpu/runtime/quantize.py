"""Quantized parameter snapshots for non-learner replicas.

The ROADMAP's quantized-broadcast seed: at N generation/serving replicas x
B parameters, snapshot distribution bandwidth is the scaling wall, and the
non-learner copies never take gradients — so they can hold (and ship) a
lossy-compressed snapshot while the learner keeps full precision.  Two wire
formats:

- ``"int8"`` — per-leaf symmetric quantization: ``q = round(x / s)`` in
  int8 with ONE float32 scale ``s = max|x| / 127`` per leaf (4x smaller
  than f32, 2x smaller than bf16).  *f32-sensitive* leaves — anything with
  ``ndim <= 1`` (biases, LayerNorm scales, the value head's bias), where a
  per-leaf scale would smear across heterogeneous magnitudes and the
  payload is tiny anyway — pass through untouched.
- ``"bf16"`` — per-leaf cast; the cheap half-size format for snapshots
  that must stay within ~1e-2 of f32 logits.

Quantization runs device-side at push time (no host transfer); consumers
dequantize ON READ (:func:`dequantize_tree`) and cache the result per
generation, so the steady-state cost is one fused dequant per publish —
never per round.  Everything here is pure jnp: it composes with jit,
donation, and the transfer guard.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp

QUANT_MODES = ("int8", "bf16")


class QuantizedLeaf(NamedTuple):
    """One compressed array: payload + the metadata to reconstruct it.

    ``scale`` is a float32 scalar for int8 (symmetric, zero-point-free);
    ``None`` for the bf16 cast.  ``dtype`` is the original dtype's name so
    dequantization restores the exact leaf dtype the model was built with.
    """

    q: jnp.ndarray
    scale: Optional[jnp.ndarray]
    dtype: str


def _is_qleaf(x: Any) -> bool:
    return isinstance(x, QuantizedLeaf)


def _quantize_leaf(x: Any, mode: str) -> Any:
    if not isinstance(x, (jnp.ndarray, jax.Array)) or not jnp.issubdtype(
        x.dtype, jnp.floating
    ):
        return x
    if x.ndim <= 1:
        # f32-sensitive: norms/biases stay exact (and are tiny on the wire)
        return x
    if mode == "bf16":
        return QuantizedLeaf(
            q=x.astype(jnp.bfloat16), scale=None, dtype=x.dtype.name
        )
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)))
    scale = jnp.maximum(amax / 127.0, jnp.float32(1e-12))
    q = jnp.clip(
        jnp.round(x.astype(jnp.float32) / scale), -127, 127
    ).astype(jnp.int8)
    return QuantizedLeaf(q=q, scale=scale, dtype=x.dtype.name)


def _dequantize_leaf(x: Any) -> Any:
    if not _is_qleaf(x):
        return x
    if x.scale is None:
        return x.q.astype(jnp.dtype(x.dtype))
    return (x.q.astype(jnp.float32) * x.scale).astype(jnp.dtype(x.dtype))


def quantize_tree(tree: Any, mode: str) -> Any:
    """Compress every float leaf with ``ndim >= 2``; device-side ops only."""
    if mode not in QUANT_MODES:
        raise ValueError(
            f"quantize mode must be one of {QUANT_MODES}, got {mode!r}"
        )
    return jax.tree_util.tree_map(lambda x: _quantize_leaf(x, mode), tree)


def dequantize_tree(tree: Any) -> Any:
    """Reconstruct a :func:`quantize_tree` snapshot (original dtypes)."""
    return jax.tree_util.tree_map(
        _dequantize_leaf, tree, is_leaf=_is_qleaf
    )


def tree_wire_bytes(tree: Any) -> int:
    """Snapshot payload size in bytes — the broadcast-bandwidth number the
    int8/bf16 formats exist to shrink (QuantizedLeaf counts q + scale)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree, is_leaf=_is_qleaf):
        if _is_qleaf(leaf):
            total += leaf.q.size * leaf.q.dtype.itemsize
            if leaf.scale is not None:
                total += 4
        elif hasattr(leaf, "size") and hasattr(leaf, "dtype"):
            total += leaf.size * jnp.dtype(leaf.dtype).itemsize
    return total
