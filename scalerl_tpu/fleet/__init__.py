"""DCN actor-fleet layer: off-mesh CPU actors feeding the TPU learner host.

Capability parity with ``scalerl/hpc/`` (SURVEY.md §2.1): framed transport,
connection hub, job executor, worker/gather/server fleet protocol with entry
handshake + weight caching + batched uploads, and turn-based episode
generation — rebuilt on a flat binary codec instead of pickle.
"""

from scalerl_tpu.fleet.cluster import (
    ClusterExecutor,
    FleetConfig,
    Gather,
    LocalCluster,
    RemoteCluster,
    WorkerServer,
    apply_mass_kill,
    worker_loop,
)
from scalerl_tpu.fleet.framing import (
    ProtocolError,
    pack_message,
    pack_message_v1,
    unpack_message,
)
from scalerl_tpu.fleet.generation import (
    EpisodeGenerator,
    discounted_returns,
    make_generation_runner,
    masked_softmax,
)
from scalerl_tpu.fleet.hub import JobExecutor, QueueHub
from scalerl_tpu.fleet.transport import (
    Connection,
    PipeConnection,
    SocketConnection,
    connect_socket,
    listen_socket,
    open_worker_pipes,
    send_recv,
)

__all__ = [
    "ClusterExecutor",
    "FleetConfig",
    "apply_mass_kill",
    "Gather",
    "LocalCluster",
    "RemoteCluster",
    "WorkerServer",
    "worker_loop",
    "ProtocolError",
    "pack_message",
    "pack_message_v1",
    "unpack_message",
    "EpisodeGenerator",
    "discounted_returns",
    "make_generation_runner",
    "masked_softmax",
    "JobExecutor",
    "QueueHub",
    "Connection",
    "PipeConnection",
    "SocketConnection",
    "connect_socket",
    "listen_socket",
    "open_worker_pipes",
    "send_recv",
]
