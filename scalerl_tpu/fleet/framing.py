"""Flat binary message codec for the DCN actor-fleet data plane.

Parity target: the reference's pickle-over-TCP framing
(``scalerl/hpc/connection.py:26-83`` — 4-byte ``!i`` length prefix around a
pickle blob) and its bz2-compressed episode payloads
(``scalerl/hpc/generation.py:150-162``).

TPU-shaped differences (SURVEY.md §7 "off-mesh actor transport"): pickle
won't hit DCN throughput for pixel rollouts and is unsafe across trust
boundaries, so the codec here is a *flat* binary layout — a JSON structure
header describing a pytree of numpy arrays + scalars, followed by the raw
array bytes concatenated — with optional zlib compression of the array
section.  Arrays round-trip zero-parse (one ``np.frombuffer`` per leaf) and
the header stays human-debuggable.

Frame layout (network byte order):

    magic  b'SRL1'      4 bytes
    flags  u8           bit0 = array section zlib-compressed
    hlen   u32          JSON header length
    blen   u64          array-section length (compressed size if bit0)
    header hlen bytes   JSON
    body   blen bytes   concatenated array buffers
"""

from __future__ import annotations

import json
import socket
import struct
import zlib
from typing import Any, List, Tuple

import numpy as np

MAGIC = b"SRL1"
_HEADER = struct.Struct("!4sBIQ")
FLAG_ZLIB = 1
# sanity cap: a single frame larger than this is a protocol error, not data
MAX_FRAME = 1 << 34


def _encode_node(obj: Any, bufs: List[bytes], offset: List[int]) -> Any:
    if isinstance(obj, np.ndarray):
        if obj.dtype.hasobject:
            raise TypeError("fleet codec cannot encode object-dtype arrays")
        raw = np.ascontiguousarray(obj)
        data = raw.tobytes()
        node = {
            "t": "a",
            "d": raw.dtype.str,
            "s": list(raw.shape),
            "o": offset[0],
            "n": len(data),
        }
        bufs.append(data)
        offset[0] += len(data)
        return node
    if isinstance(obj, (np.integer,)):
        return {"t": "i", "v": int(obj)}
    if isinstance(obj, (np.floating,)):
        return {"t": "f", "v": float(obj)}
    if isinstance(obj, (np.bool_,)):
        return {"t": "b", "v": bool(obj)}
    if isinstance(obj, bytes):
        node = {"t": "y", "o": offset[0], "n": len(obj)}
        bufs.append(obj)
        offset[0] += len(obj)
        return node
    if isinstance(obj, dict):
        # keys are encoded as nodes so int keys (e.g. player ids) round-trip
        # faithfully instead of being coerced to str by JSON
        for k in obj.keys():
            if not (k is None or isinstance(k, (str, int, float, bool))):
                raise TypeError(f"fleet codec dict key {type(k).__name__}")
        return {
            "t": "d",
            "k": [_encode_node(k, bufs, offset) for k in obj.keys()],
            "v": [_encode_node(v, bufs, offset) for v in obj.values()],
        }
    if isinstance(obj, tuple):
        return {"t": "u", "v": [_encode_node(v, bufs, offset) for v in obj]}
    if isinstance(obj, list):
        return {"t": "l", "v": [_encode_node(v, bufs, offset) for v in obj]}
    if obj is None or isinstance(obj, (str, int, float, bool)):
        return {"t": "p", "v": obj}
    raise TypeError(f"fleet codec cannot encode {type(obj).__name__}")


def _decode_node(node: Any, body: memoryview) -> Any:
    t = node["t"]
    if t == "a":
        arr = np.frombuffer(
            body[node["o"]: node["o"] + node["n"]], dtype=np.dtype(node["d"])
        )
        return arr.reshape(node["s"])
    if t == "y":
        return bytes(body[node["o"]: node["o"] + node["n"]])
    if t == "d":
        return {
            _decode_node(k, body): _decode_node(v, body)
            for k, v in zip(node["k"], node["v"])
        }
    if t == "u":
        return tuple(_decode_node(v, body) for v in node["v"])
    if t == "l":
        return [_decode_node(v, body) for v in node["v"]]
    if t in ("p", "i", "f", "b"):
        return node["v"]
    raise ValueError(f"fleet codec: unknown node type {t!r}")


def pack_message(obj: Any, compress: bool = False) -> bytes:
    """Encode a pytree of numpy arrays / scalars / str / bytes into a frame."""
    bufs: List[bytes] = []
    offset = [0]
    tree = _encode_node(obj, bufs, offset)
    header = json.dumps(tree, separators=(",", ":")).encode()
    body = b"".join(bufs)
    flags = 0
    if compress and body:
        packed = zlib.compress(body, level=1)
        if len(packed) < len(body):
            body = packed
            flags |= FLAG_ZLIB
    return _HEADER.pack(MAGIC, flags, len(header), len(body)) + header + body


def unpack_message(frame: bytes) -> Any:
    magic, flags, hlen, blen = _HEADER.unpack_from(frame, 0)
    if magic != MAGIC:
        raise ValueError(f"bad frame magic {magic!r}")
    header_end = _HEADER.size + hlen
    tree = json.loads(frame[_HEADER.size:header_end])
    body = frame[header_end:header_end + blen]
    if flags & FLAG_ZLIB:
        body = zlib.decompress(body)
    # one body copy into a writable buffer so decoded arrays are mutable
    # views (np.frombuffer over immutable bytes yields read-only arrays)
    return _decode_node(tree, memoryview(bytearray(body)))


# ---------------------------------------------------------------------------
# socket-level framing: u32 length prefix around a packed message, mirroring
# the reference's '!i' prefix (connection.py:57-83) but with the flat codec.
_LEN = struct.Struct("!Q")


def send_frame(sock: socket.socket, data: bytes) -> None:
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def recv_frame(sock: socket.socket) -> bytes:
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if n > MAX_FRAME:
        raise ValueError(f"frame of {n} bytes exceeds MAX_FRAME")
    return _recv_exact(sock, n)
