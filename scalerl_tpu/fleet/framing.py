"""Flat binary message codec for the DCN actor-fleet data plane.

Parity target: the reference's pickle-over-TCP framing
(``scalerl/hpc/connection.py:26-83`` — 4-byte ``!i`` length prefix around a
pickle blob) and its bz2-compressed episode payloads
(``scalerl/hpc/generation.py:150-162``).

TPU-shaped differences (SURVEY.md §7 "off-mesh actor transport"): pickle
won't hit DCN throughput for pixel rollouts and is unsafe across trust
boundaries, so the codec here is a *flat* binary layout — a JSON structure
header describing a pytree of numpy arrays + scalars, followed by the raw
array bytes concatenated — with optional zlib compression of the array
section.  Arrays round-trip zero-parse (one ``np.frombuffer`` per leaf) and
the header stays human-debuggable.

v2 frame layout (network byte order):

    magic  b'SRL2'      4 bytes
    flags  u8           bit0 = array section zlib-compressed
    hlen   u32          JSON header length
    blen   u64          array-section length (compressed size if bit0)
    crc    u32          CRC32 over (magic..blen prefix) + header + body
    header hlen bytes   JSON
    body   blen bytes   concatenated array buffers

The CRC covers the *fixed prefix fields too* (computed with the crc word
absent), so a bit flip anywhere in the frame — including in ``flags`` or
the length fields — is detected.  v1 frames (``SRL1`` magic, no crc) still
decode for one rolling-upgrade window; ``pack_message_v1`` emits them for
tests and mixed-version fleets.

Error contract: EVERY malformed input — bad magic, short frame, oversize or
inconsistent ``hlen``/``blen``, checksum mismatch, undecodable
header/body — raises :class:`ProtocolError`.  ``ProtocolError`` derives
from ``ConnectionError`` on purpose: a corrupt frame desynchronizes the
byte stream, so the only safe recovery is the one the connection-loss
paths already implement (hub: drop the peer; gather: reconnect with capped
backoff and resend — PR 2's liveness plane).  Never wrong data, never a
bare ``struct.error`` mid-pump, never a multi-GiB allocation from a garbage
length field.
"""

from __future__ import annotations

import json
import socket
import struct
import zlib
from typing import Any, List, Optional, Tuple

import numpy as np

from scalerl_tpu.runtime import telemetry

# cached codec instruments: one registry-identity check + one lock'd float
# add per frame (frames are whole rollout batches — negligible).  Keyed on
# the registry OBJECT so a telemetry.reset() (tests) re-resolves instead of
# feeding counters into an orphaned registry.
_COUNTERS: Optional[Tuple[Any, ...]] = None
_COUNTERS_REG: Optional[Any] = None


def _codec_counters():
    global _COUNTERS, _COUNTERS_REG
    reg = telemetry.get_registry()
    if _COUNTERS is None or _COUNTERS_REG is not reg:
        _COUNTERS_REG = reg
        _COUNTERS = (
            reg.counter("codec.frames_packed"),
            reg.counter("codec.frames_unpacked"),
            reg.counter("codec.v1_frames"),
            reg.counter("codec.bytes_packed"),
        )
    return _COUNTERS


class ProtocolError(ConnectionError):
    """Malformed or corrupt frame: the stream can no longer be trusted.

    Subclasses ``ConnectionError`` so every existing disconnect/reconnect
    handler (``fleet/hub.py`` recv pump, ``fleet/cluster.py`` gather
    reconnect) treats a corrupt frame exactly like a broken link — reject
    and re-establish, instead of crashing the pump or decoding garbage.
    """


MAGIC = b"SRL2"
MAGIC_V1 = b"SRL1"
# v2: the crc u32 rides at the end of the fixed header; _BASE is the
# crc-less prefix the checksum is computed over
_BASE = struct.Struct("!4sBIQ")
_CRC = struct.Struct("!I")
_HEADER = struct.Struct("!4sBIQI")  # full v2 fixed header
_HEADER_V1 = struct.Struct("!4sBIQ")
FLAG_ZLIB = 1
# sanity cap: a single frame larger than this is a protocol error, not data
MAX_FRAME = 1 << 34


def _encode_node(obj: Any, bufs: List[bytes], offset: List[int]) -> Any:
    if isinstance(obj, np.ndarray):
        if obj.dtype.hasobject:
            raise TypeError("fleet codec cannot encode object-dtype arrays")
        raw = np.ascontiguousarray(obj)
        data = raw.tobytes()
        node = {
            "t": "a",
            "d": raw.dtype.str,
            "s": list(raw.shape),
            "o": offset[0],
            "n": len(data),
        }
        bufs.append(data)
        offset[0] += len(data)
        return node
    if isinstance(obj, (np.integer,)):
        return {"t": "i", "v": int(obj)}
    if isinstance(obj, (np.floating,)):
        return {"t": "f", "v": float(obj)}
    if isinstance(obj, (np.bool_,)):
        return {"t": "b", "v": bool(obj)}
    if isinstance(obj, bytes):
        node = {"t": "y", "o": offset[0], "n": len(obj)}
        bufs.append(obj)
        offset[0] += len(obj)
        return node
    if isinstance(obj, dict):
        # keys are encoded as nodes so int keys (e.g. player ids) round-trip
        # faithfully instead of being coerced to str by JSON
        for k in obj.keys():
            if not (k is None or isinstance(k, (str, int, float, bool))):
                raise TypeError(f"fleet codec dict key {type(k).__name__}")
        return {
            "t": "d",
            "k": [_encode_node(k, bufs, offset) for k in obj.keys()],
            "v": [_encode_node(v, bufs, offset) for v in obj.values()],
        }
    if isinstance(obj, tuple):
        return {"t": "u", "v": [_encode_node(v, bufs, offset) for v in obj]}
    if isinstance(obj, list):
        return {"t": "l", "v": [_encode_node(v, bufs, offset) for v in obj]}
    if obj is None or isinstance(obj, (str, int, float, bool)):
        return {"t": "p", "v": obj}
    raise TypeError(f"fleet codec cannot encode {type(obj).__name__}")


def _decode_node(node: Any, body: memoryview) -> Any:
    t = node["t"]
    if t == "a":
        o, n = node["o"], node["n"]
        if not (0 <= o and o + n <= len(body)):
            raise ValueError(f"array span [{o}, {o + n}) outside body")
        arr = np.frombuffer(body[o: o + n], dtype=np.dtype(node["d"]))
        return arr.reshape(node["s"])
    if t == "y":
        o, n = node["o"], node["n"]
        if not (0 <= o and o + n <= len(body)):
            raise ValueError(f"bytes span [{o}, {o + n}) outside body")
        return bytes(body[o: o + n])
    if t == "d":
        return {
            _decode_node(k, body): _decode_node(v, body)
            for k, v in zip(node["k"], node["v"])
        }
    if t == "u":
        return tuple(_decode_node(v, body) for v in node["v"])
    if t == "l":
        return [_decode_node(v, body) for v in node["v"]]
    if t in ("p", "i", "f", "b"):
        return node["v"]
    raise ValueError(f"fleet codec: unknown node type {t!r}")


def _encode(obj: Any, compress: bool) -> Tuple[int, bytes, bytes]:
    bufs: List[bytes] = []
    offset = [0]
    tree = _encode_node(obj, bufs, offset)
    header = json.dumps(tree, separators=(",", ":")).encode()
    body = b"".join(bufs)
    flags = 0
    if compress and body:
        packed = zlib.compress(body, level=1)
        if len(packed) < len(body):
            body = packed
            flags |= FLAG_ZLIB
    return flags, header, body


def pack_message(obj: Any, compress: bool = False) -> bytes:
    """Encode a pytree of numpy arrays / scalars / str / bytes into a
    checksummed v2 frame."""
    flags, header, body = _encode(obj, compress)
    prefix = _BASE.pack(MAGIC, flags, len(header), len(body))
    crc = zlib.crc32(body, zlib.crc32(header, zlib.crc32(prefix)))
    frame = prefix + _CRC.pack(crc) + header + body
    packed, _unpacked, _v1, nbytes = _codec_counters()
    packed.inc()
    nbytes.inc(len(frame))
    return frame


def pack_message_v1(obj: Any, compress: bool = False) -> bytes:
    """Encode a legacy SRL1 frame (no checksum) — rolling-upgrade sender."""
    flags, header, body = _encode(obj, compress)
    return _HEADER_V1.pack(MAGIC_V1, flags, len(header), len(body)) + header + body


def _decode_frame(flags: int, hlen: int, blen: int, frame: bytes, hdr_size: int) -> Any:
    if hlen > MAX_FRAME or blen > MAX_FRAME:
        raise ProtocolError(
            f"oversize header/body lengths (hlen={hlen}, blen={blen})"
        )
    if len(frame) != hdr_size + hlen + blen:
        raise ProtocolError(
            f"frame length {len(frame)} inconsistent with header "
            f"(expected {hdr_size + hlen + blen})"
        )
    header_end = hdr_size + hlen
    try:
        tree = json.loads(frame[hdr_size:header_end])
    except (ValueError, UnicodeDecodeError) as e:
        raise ProtocolError(f"undecodable frame header: {e}") from e
    body = frame[header_end:header_end + blen]
    if flags & FLAG_ZLIB:
        try:
            body = zlib.decompress(body)
        except zlib.error as e:
            raise ProtocolError(f"corrupt compressed body: {e}") from e
    try:
        # one body copy into a writable buffer so decoded arrays are mutable
        # views (np.frombuffer over immutable bytes yields read-only arrays)
        return _decode_node(tree, memoryview(bytearray(body)))
    except (KeyError, ValueError, TypeError, OverflowError) as e:
        raise ProtocolError(f"undecodable frame body: {e}") from e


def unpack_message(frame: bytes) -> Any:
    if len(frame) < 4:
        raise ProtocolError(f"frame of {len(frame)} bytes has no magic")
    magic = bytes(frame[:4])
    if magic == MAGIC:
        if len(frame) < _HEADER.size:
            raise ProtocolError(
                f"frame of {len(frame)} bytes shorter than the v2 header"
            )
        _magic, flags, hlen, blen = _BASE.unpack_from(frame, 0)
        (crc,) = _CRC.unpack_from(frame, _BASE.size)
        actual = zlib.crc32(frame[_HEADER.size:], zlib.crc32(frame[:_BASE.size]))
        if actual != crc:
            raise ProtocolError(
                f"frame checksum mismatch (stored {crc:#010x}, "
                f"computed {actual:#010x})"
            )
        _codec_counters()[1].inc()
        return _decode_frame(flags, hlen, blen, frame, _HEADER.size)
    if magic == MAGIC_V1:
        # rolling upgrade: decode pre-checksum senders for one window.  No
        # integrity verdict is possible here — only structural validation.
        if len(frame) < _HEADER_V1.size:
            raise ProtocolError(
                f"frame of {len(frame)} bytes shorter than the v1 header"
            )
        _magic, flags, hlen, blen = _HEADER_V1.unpack_from(frame, 0)
        _counters = _codec_counters()
        _counters[1].inc()
        _counters[2].inc()  # legacy senders still on the wire, worth seeing
        return _decode_frame(flags, hlen, blen, frame, _HEADER_V1.size)
    raise ProtocolError(f"bad frame magic {magic!r}")


# ---------------------------------------------------------------------------
# socket-level framing: u64 length prefix around a packed message, mirroring
# the reference's '!i' prefix (connection.py:57-83) but with the flat codec.
_LEN = struct.Struct("!Q")


def send_frame(sock: socket.socket, data: bytes) -> None:
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def recv_frame(sock: socket.socket) -> bytes:
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if n > MAX_FRAME:
        # typed reject BEFORE the allocation: a garbage length prefix must
        # not attempt a multi-GiB read
        raise ProtocolError(f"frame of {n} bytes exceeds MAX_FRAME")
    return _recv_exact(sock, n)
