"""Connection hub + job executor: the learner-host message plumbing.

Parity targets (``scalerl/hpc/connection.py``):
- ``QueueCommunicator`` (:271-327) → ``QueueHub``: async send/recv pump
  threads over a *set* of connections with bounded queues; dead connections
  are dropped, not fatal (a worker that dies mid-fleet must not take the
  learner down — SURVEY.md §5 failure-detection notes).
- ``MultiProcessJobExecutor`` (:207-268) → ``JobExecutor``: dispatches jobs
  from a generator to idle worker processes and funnels (optionally
  post-processed) results into a bounded output queue.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterator, List, Optional, Set, Tuple

from scalerl_tpu.fleet.transport import (
    Connection,
    open_worker_pipes,
    wait_readable,
)


class QueueHub:
    """Pumps a dynamic set of connections through in/out queues."""

    def __init__(self, maxsize: int = 256) -> None:
        self.input_queue: "queue.Queue[Tuple[Connection, Any]]" = queue.Queue(maxsize)
        self.output_queue: "queue.Queue[Tuple[Connection, Any]]" = queue.Queue(maxsize)
        self._conns: Set[Connection] = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._threads = [
            threading.Thread(target=self._recv_loop, daemon=True),
            threading.Thread(target=self._send_loop, daemon=True),
        ]
        for t in self._threads:
            t.start()

    def connection_count(self) -> int:
        with self._lock:
            return len(self._conns)

    def add_connection(self, conn: Connection) -> None:
        with self._lock:
            self._conns.add(conn)

    def disconnect(self, conn: Connection) -> None:
        with self._lock:
            self._conns.discard(conn)
        try:
            conn.close()
        except Exception:
            pass

    def recv(self, timeout: Optional[float] = None) -> Tuple[Connection, Any]:
        """Next (connection, message); raises queue.Empty on timeout."""
        return self.input_queue.get(timeout=timeout)

    def send(self, conn: Connection, msg: Any, compress: bool = False) -> None:
        self.output_queue.put((conn, (msg, compress)))

    def close(self) -> None:
        self._stop.set()
        with self._lock:
            conns, self._conns = list(self._conns), set()
        for c in conns:
            try:
                c.close()
            except Exception:
                pass

    def _recv_loop(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                conns = list(self._conns)
            if not conns:
                self._stop.wait(0.05)
                continue
            ready, dead = wait_readable(conns, timeout=0.05)
            for conn in dead:
                self.disconnect(conn)
            for conn in ready:
                try:
                    msg = conn.recv()
                except (EOFError, OSError, ConnectionError, ValueError):
                    self.disconnect(conn)
                    continue
                self.input_queue.put((conn, msg))

    def _send_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, (msg, compress) = self.output_queue.get(timeout=0.1)
            except queue.Empty:
                continue
            try:
                conn.send(msg, compress=compress)
            except (BrokenPipeError, OSError, ConnectionError):
                self.disconnect(conn)


class JobExecutor:
    """Feed jobs from a generator to N pipe workers; collect results.

    The worker ``target(conn, *args)`` loop should ``conn.recv()`` a job,
    process it, and ``conn.send(result)``; ``None`` job means shutdown.
    """

    def __init__(
        self,
        target: Callable[..., None],
        job_source: Iterator[Any],
        num_workers: int,
        postprocess: Optional[Callable[[Any], Any]] = None,
        out_maxsize: int = 8,
    ) -> None:
        self._job_source = job_source
        self._postprocess = postprocess
        self.results: "queue.Queue[Any]" = queue.Queue(out_maxsize)
        self._stop = threading.Event()
        self._retry: "queue.Queue[Any]" = queue.Queue()
        self._idle: "queue.Queue[Connection]" = queue.Queue()
        self._conns, self._procs = open_worker_pipes(
            num_workers, target, lambda i: (i,)
        )
        for c in self._conns:
            self._idle.put(c)
        self._threads = [
            threading.Thread(target=self._dispatch_loop, daemon=True),
            threading.Thread(target=self._collect_loop, daemon=True),
        ]

    def start(self) -> None:
        for t in self._threads:
            t.start()

    def _dispatch_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn = self._idle.get(timeout=0.1)
            except queue.Empty:
                continue
            try:
                job = self._retry.get_nowait()
            except queue.Empty:
                try:
                    job = next(self._job_source)
                except StopIteration:
                    self._idle.put(conn)
                    return
            try:
                conn.send(job)
            except (BrokenPipeError, OSError):
                # worker died: the generator cannot replay, so requeue the
                # job for the next idle worker instead of dropping it
                self._retry.put(job)
                continue

    def _collect_loop(self) -> None:
        while not self._stop.is_set():
            if not self._conns:
                self._stop.wait(0.05)
                continue
            ready, dead = wait_readable(list(self._conns), timeout=0.02)
            for conn in dead:
                self._conns.remove(conn)
            for conn in ready:
                try:
                    result = conn.recv()
                except (EOFError, OSError, ConnectionError):
                    if conn in self._conns:
                        self._conns.remove(conn)
                    continue
                if self._postprocess is not None:
                    result = self._postprocess(result)
                self.results.put(result)
                self._idle.put(conn)

    def shutdown(self, timeout: float = 5.0) -> None:
        self._stop.set()
        for conn in self._conns:
            try:
                conn.send(None)
            except (BrokenPipeError, OSError):
                pass
        for proc in self._procs:
            proc.join(timeout=timeout)
            if proc.is_alive():
                proc.terminate()
        for conn in self._conns:
            conn.close()
