"""Connection hub + job executor: the learner-host message plumbing.

Parity targets (``scalerl/hpc/connection.py``):
- ``QueueCommunicator`` (:271-327) → ``QueueHub``: async send/recv pump
  threads over a *set* of connections with bounded queues; dead connections
  are dropped, not fatal (a worker that dies mid-fleet must not take the
  learner down — SURVEY.md §5 failure-detection notes).
- ``MultiProcessJobExecutor`` (:207-268) → ``JobExecutor``: dispatches jobs
  from a generator to idle worker processes and funnels (optionally
  post-processed) results into a bounded output queue.

Heartbeats (runtime/supervisor.py vocabulary): with ``heartbeat_interval``
set, the hub pings every connection on that cadence and drops peers whose
uplink stays SILENT past the timeout — a closed socket already surfaces via
select/EOF, but a silently-dead one (yanked cable, wedged peer, half-open
TCP after a NAT reboot) previously hung forever.  Ping/pong frames are
swallowed inside the hub (pings answered in the recv pump, pongs counted as
liveness), so every protocol built on the hub gets liveness for free without
seeing a new message kind.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterator, List, Optional, Set, Tuple

from scalerl_tpu.fleet.framing import ProtocolError
from scalerl_tpu.fleet.transport import (
    Connection,
    open_worker_pipes,
    wait_readable,
)
from scalerl_tpu.runtime import telemetry, tracing
from scalerl_tpu.runtime.supervisor import (
    LivenessTracker,
    is_heartbeat,
    make_ping,
    make_pong,
)
from scalerl_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class QueueHub:
    """Pumps a dynamic set of connections through in/out queues.

    ``heartbeat_interval`` > 0 arms the liveness plane: ping every
    connection each interval; a connection with no inbound traffic (results,
    RPCs, or pongs all count) for ``heartbeat_timeout`` seconds (default
    2 x interval — the detection bound) is disconnected and reported via
    ``on_dead(conn, reason)``.  A connection that has never spoken gets
    ``first_contact_grace`` instead — spawned gather processes pay seconds
    of interpreter+import boot before their pump starts answering.
    """

    def __init__(
        self,
        maxsize: int = 256,
        heartbeat_interval: float = 0.0,
        heartbeat_timeout: float = 0.0,
        first_contact_grace: float = 120.0,
        on_dead: Optional[Callable[[Connection, str], None]] = None,
        on_telemetry: Optional[Callable[[Connection, Any], None]] = None,
        max_pending: int = 0,
        on_disconnect: Optional[Callable[[Connection], None]] = None,
    ) -> None:
        # max_pending > 0 arms BOUNDED ADMISSION on the inbound queue: when
        # the consumer lags that far behind, the stalest queued message is
        # shed (counted in shed_total) instead of the recv pump blocking on
        # a full queue — a blocked pump stops answering pings and the whole
        # liveness plane rots behind one slow consumer.  0 keeps the old
        # block-on-full behavior (maxsize still bounds memory).
        self.input_queue: "queue.Queue[Tuple[Connection, Any]]" = queue.Queue(maxsize)
        self.output_queue: "queue.Queue[Tuple[Connection, Any]]" = queue.Queue(maxsize)
        self.heartbeat_interval = heartbeat_interval
        self.heartbeat_timeout = heartbeat_timeout or 2.0 * heartbeat_interval
        self.first_contact_grace = max(first_contact_grace, self.heartbeat_timeout)
        self.max_pending = max_pending
        self.shed_total = 0
        self.on_dead = on_dead
        # piggybacked telemetry: any inbound dict carrying a "telem" key —
        # heartbeat pongs and result-upload frames — has the payload handed
        # to this callback in the recv pump (one merge point, no new
        # message kinds or round-trips)
        self.on_telemetry = on_telemetry
        # membership: fired for EVERY removal of a registered connection
        # (EOF, protocol error, liveness verdict) — unlike on_dead, which
        # only covers heartbeat verdicts.  The elastic fleet uses this to
        # requeue a dead gather's outstanding tasks and clean its roster
        # entry; close() does not fire it (teardown is not churn).
        self.on_disconnect = on_disconnect
        self.protocol_errors = 0  # corrupt frames rejected by the recv pump
        self.peers_dropped = 0  # liveness verdicts (silent peers dropped)
        telemetry.get_registry().bind(
            "hub",
            lambda: {
                "protocol_errors": self.protocol_errors,
                "peers_dropped": self.peers_dropped,
                "shed_total": self.shed_total,
                "connections": self.connection_count(),
                "input_depth": self.input_queue.qsize(),
                "output_depth": self.output_queue.qsize(),
            },
        )
        self._liveness = LivenessTracker()
        self._greeted: Set[Connection] = set()
        self._conns: Set[Connection] = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._threads = [
            threading.Thread(target=self._recv_loop, daemon=True),
            threading.Thread(target=self._send_loop, daemon=True),
        ]
        if heartbeat_interval > 0:
            self._threads.append(
                threading.Thread(target=self._heartbeat_loop, daemon=True)
            )
        for t in self._threads:
            t.start()

    def connection_count(self) -> int:
        with self._lock:
            return len(self._conns)

    def add_connection(self, conn: Connection) -> None:
        with self._lock:
            self._conns.add(conn)
        self._liveness.beat(conn)

    def disconnect(self, conn: Connection) -> None:
        with self._lock:
            present = conn in self._conns
            self._conns.discard(conn)
            self._greeted.discard(conn)
        self._liveness.forget(conn)
        try:
            conn.close()
        except Exception:
            pass
        if present and self.on_disconnect is not None:
            try:
                self.on_disconnect(conn)
            except Exception:  # noqa: BLE001 — membership hooks must not kill the pump
                logger.exception("hub: on_disconnect callback failed")

    def recv(self, timeout: Optional[float] = None) -> Tuple[Connection, Any]:
        """Next (connection, message); raises queue.Empty on timeout."""
        return self.input_queue.get(timeout=timeout)

    def send(self, conn: Connection, msg: Any, compress: bool = False) -> None:
        self.output_queue.put((conn, (msg, compress)))

    def close(self) -> None:
        self._stop.set()
        with self._lock:
            conns, self._conns = list(self._conns), set()
        for c in conns:
            try:
                c.close()
            except Exception:
                pass

    def _recv_loop(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                conns = list(self._conns)
            if not conns:
                self._stop.wait(0.05)
                continue
            ready, dead = wait_readable(conns, timeout=0.05)
            for conn in dead:
                self.disconnect(conn)
            for conn in ready:
                try:
                    msg = conn.recv()
                except ProtocolError as e:
                    # corrupt-frame reject: the stream is desynchronized, so
                    # drop the link — a socket gather reconnects through the
                    # accept loop (the PR 2 backoff path) and resends
                    self.protocol_errors += 1
                    telemetry.get_registry().counter("hub.protocol_errors").inc()
                    telemetry.record_event("protocol_error", error=str(e))
                    logger.warning("hub: corrupt frame rejected (%s)", e)
                    self.disconnect(conn)
                    continue
                except (EOFError, OSError, ConnectionError, ValueError):
                    self.disconnect(conn)
                    continue
                self._liveness.beat(conn)
                with self._lock:
                    self._greeted.add(conn)
                if (
                    self.on_telemetry is not None
                    and isinstance(msg, dict)
                    and "telem" in msg
                ):
                    # piggybacked fleet telemetry (pong or result upload)
                    try:
                        self.on_telemetry(conn, msg.get("telem"))
                    except Exception:  # noqa: BLE001 — telemetry must not kill the pump
                        logger.exception("hub: on_telemetry callback failed")
                if is_heartbeat(msg):
                    # swallowed here: pings answered in-pump, pongs are pure
                    # liveness — consumers never see a heartbeat kind
                    if msg.get("kind") == "ping":
                        self.send(conn, make_pong(msg))
                    elif "rt" in msg:
                        # the pong echoes our ping's wall t and adds the
                        # responder's rt/host: one free clock-skew sample
                        # per heartbeat, feeding the tracer's per-link
                        # offset table (tools/trace_report.py alignment)
                        tracing.observe_pong(msg)
                    continue
                if self.max_pending > 0:
                    # bounded admission: shed the STALEST queued message so
                    # the freshest data survives and the pump never blocks
                    # (a blocked pump stops answering pings); the loop also
                    # covers max_pending >= queue maxsize, where put_nowait
                    # is the binding constraint
                    while True:
                        if self.input_queue.qsize() >= self.max_pending:
                            self._shed_one()
                        try:
                            self.input_queue.put_nowait((conn, msg))
                            break
                        except queue.Full:
                            self._shed_one()
                else:
                    self.input_queue.put((conn, msg))

    def _shed_one(self) -> None:
        try:
            self.input_queue.get_nowait()
        except queue.Empty:
            return
        self.shed_total += 1
        telemetry.get_registry().counter("hub.shed_total").inc()

    def _send_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, (msg, compress) = self.output_queue.get(timeout=0.1)
            except queue.Empty:
                continue
            try:
                conn.send(msg, compress=compress)
            except (BrokenPipeError, OSError, ConnectionError):
                self.disconnect(conn)

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_interval):
            with self._lock:
                conns = list(self._conns)
                greeted = set(self._greeted)
            now_stale = set(self._liveness.stale(self.heartbeat_timeout))
            grace_stale = set(self._liveness.stale(self.first_contact_grace))
            for conn in conns:
                # detection bound: a peer that answers no ping for
                # heartbeat_timeout (= 2 intervals by default) is dead even
                # though its socket never closed
                stale = now_stale if conn in greeted else grace_stale
                if conn in stale:
                    reason = (
                        "heartbeat timeout: no traffic for "
                        f"{self.heartbeat_timeout:.1f}s"
                        if conn in greeted
                        else "heartbeat timeout: peer never spoke within "
                        f"{self.first_contact_grace:.1f}s of connecting"
                    )
                    logger.warning("hub: dropping silent connection (%s)", reason)
                    self.peers_dropped += 1
                    telemetry.record_event("peer_dead", reason=reason)
                    self.disconnect(conn)
                    if self.on_dead is not None:
                        try:
                            self.on_dead(conn, reason)
                        except Exception:  # noqa: BLE001 — reporter must not kill the pump
                            logger.exception("hub: on_dead callback failed")
                else:
                    self.send(conn, make_ping())


class JobExecutor:
    """Feed jobs from a generator to N pipe workers; collect results.

    The worker ``target(conn, *args)`` loop should ``conn.recv()`` a job,
    process it, and ``conn.send(result)``; ``None`` job means shutdown.
    """

    def __init__(
        self,
        target: Callable[..., None],
        job_source: Iterator[Any],
        num_workers: int,
        postprocess: Optional[Callable[[Any], Any]] = None,
        out_maxsize: int = 8,
    ) -> None:
        self._job_source = job_source
        self._postprocess = postprocess
        self.results: "queue.Queue[Any]" = queue.Queue(out_maxsize)
        self._stop = threading.Event()
        self._retry: "queue.Queue[Any]" = queue.Queue()
        self._idle: "queue.Queue[Connection]" = queue.Queue()
        self._conns, self._procs = open_worker_pipes(
            num_workers, target, lambda i: (i,)
        )
        for c in self._conns:
            self._idle.put(c)
        self._threads = [
            threading.Thread(target=self._dispatch_loop, daemon=True),
            threading.Thread(target=self._collect_loop, daemon=True),
        ]

    def start(self) -> None:
        for t in self._threads:
            t.start()

    def _dispatch_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn = self._idle.get(timeout=0.1)
            except queue.Empty:
                continue
            try:
                job = self._retry.get_nowait()
            except queue.Empty:
                try:
                    job = next(self._job_source)
                except StopIteration:
                    self._idle.put(conn)
                    return
            try:
                conn.send(job)
            except (BrokenPipeError, OSError):
                # worker died: the generator cannot replay, so requeue the
                # job for the next idle worker instead of dropping it
                self._retry.put(job)
                continue

    def _collect_loop(self) -> None:
        while not self._stop.is_set():
            if not self._conns:
                self._stop.wait(0.05)
                continue
            ready, dead = wait_readable(list(self._conns), timeout=0.02)
            for conn in dead:
                self._conns.remove(conn)
            for conn in ready:
                try:
                    result = conn.recv()
                except (EOFError, OSError, ConnectionError):
                    if conn in self._conns:
                        self._conns.remove(conn)
                    continue
                if self._postprocess is not None:
                    result = self._postprocess(result)
                self.results.put(result)
                self._idle.put(conn)

    def shutdown(self, timeout: float = 5.0) -> None:
        self._stop.set()
        for conn in self._conns:
            try:
                conn.send(None)
            except (BrokenPipeError, OSError):
                pass
        for proc in self._procs:
            proc.join(timeout=timeout)
            if proc.is_alive():
                proc.terminate()
        for conn in self._conns:
            conn.close()
