"""Actor-fleet protocol: workers, gathers, server, local/remote clusters.

Parity target: ``scalerl/hpc/worker.py`` (27-352) — the HandyRL-style fleet
that the reference vendors import-broken (SURVEY.md §2.1 caveat): a server
hands out rollout/eval tasks, per-host *gathers* fan 16-ish workers into one
uplink with task prefetch, model-blob caching, and batched result upload;
remote hosts join via an entry handshake.

TPU-shaped differences: this is the DCN control plane for **off-mesh CPU
actors** feeding a central TPU learner host (SEED-RL topology).  Weights are
versioned snapshots from ``runtime.param_server.ParameterServer`` (the
reference fetched models by monotonically increasing id with an unbounded
cache; here a gather caches only the newest version).  All payloads ride the
flat binary codec, with zlib on the rollout uplink.

Wire protocol (dicts over ``fleet.transport.Connection``):

    worker→gather   {"kind": "task"}                      request next task
                    {"kind": "params", "have": v}         fetch weights if stale
                    {"kind": "result", "v": {...}}        one episode result
    gather→server   {"kind": "task_batch", "n": k}        prefetch k tasks
                    {"kind": "params", "have": v}
                    {"kind": "result_batch", "v": [...], "seq": s}
                                                          batched upload, retained
                                                          by the gather until acked
    server→gather   {"kind": "task_batch", "v": [t...]}   t=None means stop
                    {"kind": "params", "version": v, "weights": tree}
                    {"kind": "result_ack", "seq": s}      upload s fully received

    Every result carries an at-least-once dedup key (worker_id,
    upload_epoch, episode_seq): un-acked uploads are resent after a
    reconnect — a cut link or a checksum-rejected frame costs a retransmit,
    never a lost or double-counted episode.
    entry handshake {"kind": "entry", "num_workers": n, "host": h}
                    → {"kind": "entry_ack", "base_worker_id": b, "config": {...}}

Elasticity plane (dynamic admission / draining — the scale-events layer the
autoscaler in ``runtime/autoscaler.py`` drives):

    gather→server   {"kind": "gather_hello", "base_worker_id": b,
                     "num_workers": n, "gather_epoch": e}
                                          membership announce, sent on connect
                                          AND after every reconnect — the
                                          server's live roster for scale
                                          decisions and targeted drains
                    {"kind": "task_return", "v": [t...]}
                                          unstarted prefetched tasks handed
                                          back on drain (the server reissues
                                          them; no episode is lost to a drain)
                    {"kind": "drain_done", "base_worker_id": b}
                                          drain complete: results flushed,
                                          every retained upload acked
    server→gather   {"kind": "drain"}     stop starting episodes, return
                                          unstarted tasks, flush + await acks,
                                          close cleanly (exit 0 — distinct
                                          from the kill-and-respawn path)

    Tasks the server hands out are stamped with a monotonic ``_task_id`` and
    tracked per gather link: a link that dies (EOF, protocol error, liveness
    verdict, SIGTERMed spot node) has its outstanding tasks requeued for the
    next gather, and results are deduplicated at TASK level too (a task that
    raced its requeue and completed twice counts once) — at-least-once
    execution, exactly-once episode accounting, across preemption waves.
"""

from __future__ import annotations

import multiprocessing as mp
import queue
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Any, Callable, Deque, Dict, List, Optional, Set, Tuple

from scalerl_tpu.fleet.framing import ProtocolError
from scalerl_tpu.fleet.hub import QueueHub
from scalerl_tpu.fleet.transport import (
    Connection,
    PipeConnection,
    accept_connection,
    connect_socket,
    listen_socket,
    open_worker_pipes,
    send_recv,
    wait_readable,
)
from scalerl_tpu.runtime import chaos, telemetry, tracing
from scalerl_tpu.runtime.param_server import ParameterServer
from scalerl_tpu.runtime.supervisor import (
    DRAIN,
    DRAIN_DONE,
    is_heartbeat,
    make_drain,
    make_pong,
)
from scalerl_tpu.runtime.telemetry import TelemetryAggregator
from scalerl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

ENTRY_PORT = 9999
WORKER_PORT = 9998

# EpisodeRunner: (task dict, weights pytree, worker_id) -> result dict
EpisodeRunner = Callable[[Dict[str, Any], Any, int], Dict[str, Any]]


@dataclass
class FleetConfig:
    num_workers: int = 4
    workers_per_gather: int = 16
    task_prefetch: int = 0          # 0 → 1 + workers/4, like the reference
    upload_batch: int = 4           # results batched per uplink message
    compress_uplink: bool = True
    entry_port: int = ENTRY_PORT
    worker_port: int = WORKER_PORT
    server_host: str = "127.0.0.1"
    # Liveness plane (runtime/supervisor.py): the server pings every gather
    # link on this cadence and declares a SILENT (not closed) peer dead
    # after heartbeat_timeout_s (0 → 2 x interval, the detection bound);
    # gathers treat a server link with no traffic for the same window as
    # dead and reconnect.  0 disables heartbeats entirely (pre-supervision
    # behavior: only closed connections are detected).
    heartbeat_interval_s: float = 5.0
    heartbeat_timeout_s: float = 0.0
    # Socket-gather reconnect: capped exponential backoff
    # (supervisor.exp_backoff) after a lost server link, up to max_reconnects
    # attempts across the gather's lifetime before it gives up and exits.
    reconnect_backoff_s: float = 0.5
    reconnect_backoff_cap_s: float = 10.0
    max_reconnects: int = 5
    # Bounded admission (the fleet-wide max_pending/shed_total vocabulary,
    # shared with RolloutQueue and the inference batcher): when > 0, the
    # server hub sheds the stalest queued inbound message once this many
    # are pending instead of blocking its recv pump on a slow consumer —
    # unbounded queue growth silently becomes latency and policy lag.
    # 0 (default) keeps the pre-serving block-on-full behavior.
    max_pending: int = 0
    # Telemetry plane (runtime/telemetry.py): gathers piggyback compact
    # registry snapshots (their own counters + per-worker payloads relayed
    # from worker results) on heartbeat pongs and result-upload frames; the
    # server merges them into per-worker and aggregate series.  No new
    # message kinds or round-trips — just extra dict keys on existing v2
    # codec frames.  False strips the piggyback (pre-telemetry wire shape).
    telemetry_piggyback: bool = True
    extra: Dict[str, Any] = field(default_factory=dict)

    @property
    def num_gathers(self) -> int:
        return 1 + max(0, self.num_workers - 1) // self.workers_per_gather

    def prefetch(self, workers: int) -> int:
        return self.task_prefetch or 1 + workers // 4

    @property
    def heartbeat_timeout(self) -> float:
        return self.heartbeat_timeout_s or 2.0 * self.heartbeat_interval_s


# ---------------------------------------------------------------------------
# worker


def worker_loop(
    conn: Connection,
    worker_id: int,
    runner: EpisodeRunner,
    epoch_salt: int = 0,
) -> None:
    """Task loop: parity with ``Worker.run`` (``hpc/worker.py:96-120``).

    Runner exceptions are *reported upstream* before the worker exits —
    the reference's fleet simply forgot dead workers (SURVEY.md §5
    failure-detection notes); here the server surfaces them.

    Every result carries an at-least-once dedup key: ``(worker_id,
    upload_epoch, episode_seq)``.  A gather that loses its server link
    resends the in-flight upload on the fresh connection (PR 2's
    reconnect path), so the server may legitimately see a result twice;
    the per-worker monotonic ``episode_seq`` lets it drop the duplicate
    instead of double-counting the episode into replay.  ``upload_epoch``
    is a random per-worker-process nonce so an elastically *respawned*
    worker (same id, fresh seq counter) is not mistaken for a replay —
    and ``epoch_salt`` (the owning gather's ``gather_epoch`` nonce) rides
    its high bits, so every worker of a respawned gather is provably in a
    fresh epoch even against a per-worker randomness collision: a slow
    duplicate from the corpse gather can never collide with the
    replacement's live sequence.
    """
    import os as _os
    import traceback

    weights: Any = None
    version = -1
    upload_epoch = (int(epoch_salt) << 32) | int.from_bytes(_os.urandom(4), "big")
    episode_seq = 0
    reg = telemetry.get_registry()
    ep_meter = reg.meter("worker.episodes_per_s")
    try:
        while True:
            task = send_recv(conn, {"kind": "task"})
            if task is None:
                break
            t_task = time.monotonic()
            task_ctx = tracing.extract(task)
            want = int(task.get("param_version", -1))
            if want >= 0 and want != version:
                reply = send_recv(
                    conn, {"kind": "params", "have": version, "want": want}
                )
                if reply is not None:
                    version = int(reply["version"])
                    weights = reply["weights"]
                    reg.counter("worker.param_fetches").inc()
            try:
                # activate the task's trace for the episode: any flight
                # event recorded inside (env error, chaos injection in this
                # process) carries the trace id — forensics link both ways
                with tracing.get_tracer().activate(task_ctx):
                    result = runner(task, weights, worker_id)
                if task_ctx is not None:
                    tracing.record_span(
                        "task.episode", parent=task_ctx, t_start=t_task,
                        t_end=time.monotonic(), kind="fleet",
                        worker=worker_id,
                    )
            except Exception as exc:  # noqa: BLE001 - funneled upstream
                reg.counter("worker.errors").inc()
                conn.send(
                    {
                        "kind": "worker_error",
                        "v": {
                            "worker_id": worker_id,
                            "task": task,
                            "error": repr(exc),
                            "traceback": traceback.format_exc(),
                        },
                    }
                )
                break
            result["worker_id"] = worker_id
            result["param_version"] = version
            result["upload_epoch"] = upload_epoch
            result["episode_seq"] = episode_seq
            episode_seq += 1
            # echo the server's task id so it can close the outstanding-task
            # entry (and requeue-survivors dedup at task level)
            tid = task.get("_task_id") if isinstance(task, dict) else None
            if tid is not None:
                result["_task_id"] = tid
            reg.counter("worker.episodes").inc()
            ep_meter.mark()
            # compact telemetry piggyback: rides the existing result frame
            # up through the gather to the server's aggregator — no extra
            # messages (the gather strips it before the dedup-keyed upload)
            result["_telem"] = reg.compact()
            conn.send({"kind": "result", "v": result})
    except (EOFError, OSError, ConnectionError, KeyboardInterrupt):
        pass
    finally:
        conn.close()


# ---------------------------------------------------------------------------
# gather


class Gather:
    """Per-host fan-in proxy: parity with ``Gather.run`` (``hpc/worker.py:153-232``).

    Liveness (runtime/supervisor.py): the gather answers server pings in its
    select loop, treats a server link silent past ``config.heartbeat_timeout``
    as dead, and — given a ``reconnect`` factory (socket gathers) — replaces
    the link with capped exponential backoff instead of dying, resending the
    in-flight upload/RPC on the fresh link (at-least-once delivery: the
    server may see a duplicate result batch after a mid-upload cut, which is
    harmless for rollout streams).  Pipe gathers (``LocalCluster``) keep the
    old die-on-error behavior: a dead pipe means a dead parent.
    """

    def __init__(
        self,
        server_conn: Connection,
        config: FleetConfig,
        runner: EpisodeRunner,
        base_worker_id: int,
        num_workers: int,
        reconnect: Optional[Callable[[], Connection]] = None,
    ) -> None:
        import os as _os

        self.server = server_conn
        self.config = config
        self.reconnect = reconnect
        self.reconnects_used = 0
        self._server_seen = time.monotonic()
        self.tasks: "queue.Queue[Any]" = queue.Queue()
        self.results: List[Dict[str, Any]] = []
        self.num_workers = num_workers
        # gather-level incarnation nonce: salts every child worker's
        # upload_epoch (high bits), so a respawned gather's whole worker
        # range is provably a fresh epoch — a slow duplicate from the dead
        # predecessor can never collide with this incarnation's sequences
        self.gather_epoch = int.from_bytes(_os.urandom(4), "big")
        # drain protocol (scale-down / spot SIGTERM): a server "drain" frame
        # stops new episodes, returns unstarted tasks, flushes + awaits acks,
        # then exits cleanly with a "drain_done"
        self.draining = False
        self._drain_requested = False
        # at-least-once uploads, completed: every result batch is RETAINED
        # under a gather-local upload seq until the server acks it
        # ("result_ack").  A batch the server never processed — the link
        # was cut mid-frame, or the frame arrived corrupt and was rejected
        # (ProtocolError -> disconnect) — is resent after the reconnect;
        # the server's (worker_id, episode_seq) dedup makes the redelivery
        # exactly-once from replay's point of view.
        self._upload_seq = 0
        self._unacked: Dict[int, List[Dict[str, Any]]] = {}
        self._params_version = -1
        self._params_msg: Any = None
        # telemetry plane: this gather's own counters plus the newest
        # compact snapshot relayed from each worker's result stream; both
        # ride the uplink on pongs and result-batch frames
        self.base_worker_id = base_worker_id
        self._worker_telem: Dict[int, Dict[str, float]] = {}
        self._reg = telemetry.get_registry()
        self._reg.bind(
            "gather",
            lambda: {
                "unacked_uploads": len(self._unacked),
                "live_workers": len(self.worker_conns),
                "reconnects": self.reconnects_used,
                "params_version": self._params_version,
            },
        )
        self.worker_conns, self.worker_procs = open_worker_pipes(
            num_workers,
            worker_loop,
            lambda i: (base_worker_id + i, runner, self.gather_epoch),
        )
        # task source exhausted: serve None to further requests, but keep
        # running until every worker has drained its final result and closed
        self._exhausted = False
        # membership announce: the server's roster (scale decisions, targeted
        # drains) learns about this gather before any task traffic flows
        self._send_hello()

    def _send_hello(self) -> None:
        self.server.send(
            {
                "kind": "gather_hello",
                "base_worker_id": self.base_worker_id,
                "num_workers": self.num_workers,
                "gather_epoch": self.gather_epoch,
            }
        )

    # -- server link ---------------------------------------------------
    def _replace_server_conn(self, why: Exception) -> None:
        """Reconnect with capped exponential backoff, or re-raise ``why``."""
        if self.reconnect is None:
            raise why if isinstance(why, Exception) else ConnectionError(str(why))
        from scalerl_tpu.runtime.supervisor import exp_backoff

        try:
            self.server.close()
        except Exception:  # noqa: BLE001 — link already broken
            pass
        while self.reconnects_used < self.config.max_reconnects:
            delay = exp_backoff(
                self.reconnects_used,
                self.config.reconnect_backoff_s,
                self.config.reconnect_backoff_cap_s,
            )
            self.reconnects_used += 1
            self._reg.counter("gather.reconnect_attempts").inc()
            telemetry.record_event(
                "reconnect", attempt=self.reconnects_used, why=repr(why)
            )
            logger.warning(
                "gather: server link lost (%r); reconnecting in %.2fs "
                "(attempt %d/%d)",
                why, delay, self.reconnects_used, self.config.max_reconnects,
            )
            time.sleep(delay)
            try:
                self.server = self.reconnect()
                self._server_seen = time.monotonic()
                # re-announce membership FIRST: the server requeued this
                # gather's outstanding tasks when the old link dropped, and
                # the fresh roster entry is what targeted drains address
                self._send_hello()
                # the cut may have eaten in-flight uploads (or the server
                # rejected a corrupt frame and dropped the link): resend
                # everything unacked on the fresh link; a failure here is
                # just another failed reconnect attempt
                self._resend_unacked()
                return
            except (ConnectionError, OSError) as e:
                why = e
        raise ConnectionError(
            f"gather: server unreachable after {self.reconnects_used} "
            "reconnect attempts"
        ) from why

    def _recv_from_server(self) -> Any:
        """One server frame, heartbeats filtered (pings answered inline).

        On a reconnectable (socket) link with heartbeats enabled the wait is
        bounded by the liveness timeout — a silently-dead server surfaces as
        ``TimeoutError`` for the reconnect path instead of a forever-block.
        Pipe links keep unbounded waits: a pipe cannot die silently (peer
        death closes the fd), and a timeout would only convert a slow server
        on a loaded host into a dead gather.
        """
        timeout = (
            self.config.heartbeat_timeout
            if self.config.heartbeat_interval_s > 0 and self.reconnect is not None
            else None
        )
        while True:
            msg = self.server.recv(timeout=timeout)
            self._server_seen = time.monotonic()
            if is_heartbeat(msg):
                if msg.get("kind") == "ping":
                    self.server.send(self._make_pong(msg))
                continue
            if isinstance(msg, dict) and msg.get("kind") == "result_ack":
                # upload acks arrive unsolicited, possibly ahead of an RPC
                # reply — filter them like heartbeats
                self._unacked.pop(int(msg.get("seq", -1)), None)
                continue
            if isinstance(msg, dict) and msg.get("kind") == DRAIN:
                # drain is unsolicited too; flag it and let the main loop
                # run the protocol outside any in-flight RPC (sending the
                # task_return from here would re-enter the reconnect path)
                self._drain_requested = True
                continue
            return msg

    def _server_rpc(self, msg: Dict[str, Any], compress: bool = False) -> Any:
        """send+recv with heartbeat filtering and reconnect-with-retry."""
        while True:
            try:
                self.server.send(msg, compress=compress)
                return self._recv_from_server()
            except (ConnectionError, EOFError, OSError, TimeoutError) as e:
                self._replace_server_conn(e)

    def _server_send(self, msg: Dict[str, Any], compress: bool = False) -> None:
        while True:
            try:
                self.server.send(msg, compress=compress)
                return
            except (ConnectionError, BrokenPipeError, OSError) as e:
                self._replace_server_conn(e)

    def _pump_server(self) -> None:
        """Drain unsolicited server frames (pings) outside any RPC."""
        try:
            while self.server.poll(0):
                msg = self.server.recv()
                self._server_seen = time.monotonic()
                if is_heartbeat(msg):
                    if msg.get("kind") == "ping":
                        self.server.send(self._make_pong(msg))
                elif isinstance(msg, dict) and msg.get("kind") == "result_ack":
                    self._unacked.pop(int(msg.get("seq", -1)), None)
                elif isinstance(msg, dict) and msg.get("kind") == DRAIN:
                    self._drain_requested = True
                else:
                    logger.warning(
                        "gather: unsolicited server message %r",
                        msg.get("kind") if isinstance(msg, dict) else type(msg),
                    )
        except (ConnectionError, EOFError, OSError) as e:
            self._replace_server_conn(e)

    # -- telemetry piggyback -------------------------------------------
    def _telemetry_payload(self) -> Dict[str, Any]:
        """Compact snapshot for the uplink: this gather's registry plus the
        newest per-worker snapshots relayed off the result stream."""
        return {
            "src": f"gather:{self.base_worker_id}",
            "v": self._reg.compact(),
            "workers": {str(w): s for w, s in self._worker_telem.items()},
        }

    def _make_pong(self, ping_msg: Dict[str, Any]) -> Dict[str, Any]:
        pong = make_pong(ping_msg)
        if self.config.telemetry_piggyback:
            # heartbeat pongs carry the compact snapshot: a silent-but-idle
            # gather still reports series every heartbeat interval
            pong["telem"] = self._telemetry_payload()
        return pong

    def _check_server_liveness(self) -> None:
        # silent-death is a TCP pathology: pipe links (reconnect=None) skip
        # the staleness verdict — their failure mode is EOF, caught above
        if self.config.heartbeat_interval_s <= 0 or self.reconnect is None:
            return
        if time.monotonic() - self._server_seen > self.config.heartbeat_timeout:
            self._replace_server_conn(
                TimeoutError(
                    "no server traffic for "
                    f"{self.config.heartbeat_timeout:.1f}s"
                )
            )

    # -- drain protocol -------------------------------------------------
    def _begin_drain(self) -> None:
        """Stop starting episodes: serve None to further task requests and
        hand every unstarted prefetched task back to the server for
        reissue.  Workers finish the episode they hold (its result flushes
        normally), then exit on the None task; the run loop completes the
        protocol once the last worker is gone."""
        if self.draining:
            return
        self.draining = True
        self._exhausted = True
        self._reg.counter("gather.drains").inc()
        telemetry.record_event("drain_begin", base=self.base_worker_id)
        returned: List[Any] = []
        while True:
            try:
                t = self.tasks.get_nowait()
            except queue.Empty:
                break
            if t is not None:
                returned.append(t)
        if returned:
            self._server_send({"kind": "task_return", "v": returned})
        logger.info(
            "gather %d: draining (%d unstarted tasks returned, %d workers "
            "finishing)",
            self.base_worker_id, len(returned), len(self.worker_conns),
        )

    def _await_acks(self, timeout: float = 30.0) -> bool:
        """Pump the server link until every retained upload is acked (or the
        deadline passes) — the zero-lost-uploads half of a clean close."""
        deadline = time.monotonic() + timeout
        while self._unacked and time.monotonic() < deadline:
            try:
                if self.server.poll(0.1):
                    self._pump_server()
                self._check_server_liveness()
            except (ConnectionError, EOFError, OSError, TimeoutError) as e:
                try:
                    self._replace_server_conn(e)
                except (ConnectionError, EOFError, OSError):
                    return False  # reconnect budget spent: uploads stay retained
        return not self._unacked

    # -- main loop -----------------------------------------------------
    def run(self) -> None:
        try:
            while self.worker_conns:
                # snapshot the server link: a reconnect mid-sweep (triggered
                # by any conn in this iteration) replaces self.server, and
                # the STALE object may still sit in ready/dead — it must
                # never be mistaken for a dead worker pipe
                server_conn = self.server
                ready, dead = wait_readable(
                    self.worker_conns + [server_conn], timeout=0.02
                )
                for conn in dead:
                    if conn is server_conn:
                        if conn is self.server:  # not already replaced
                            self._replace_server_conn(
                                ConnectionError("server connection invalid")
                            )
                    elif conn in self.worker_conns:
                        self.worker_conns.remove(conn)
                for conn in ready:
                    if conn is server_conn:
                        if conn is self.server:
                            self._pump_server()
                        continue
                    try:
                        msg = conn.recv()
                    except (EOFError, OSError, ConnectionError):
                        if conn in self.worker_conns:
                            self.worker_conns.remove(conn)
                        continue
                    self._handle(conn, msg)
                self._check_server_liveness()
                if self._drain_requested and not self.draining:
                    self._begin_drain()
            # every worker exited cleanly: final flush, then hold for the
            # server's acks so a drain/scale-down loses zero retained
            # uploads (the at-least-once retention is pointless if the
            # process exits before redelivery could happen)
            self._flush_results()
            acked = self._await_acks()
            if self.draining:
                telemetry.record_event(
                    "drain_done", base=self.base_worker_id, acked=acked
                )
                self._server_send(
                    {"kind": DRAIN_DONE, "base_worker_id": self.base_worker_id}
                )
        finally:
            self._flush_results()
            for c in self.worker_conns:
                c.close()

    def _handle(self, conn: Connection, msg: Dict[str, Any]) -> None:
        kind = msg["kind"]
        if kind == "task":
            if self.tasks.empty() and not self._exhausted:
                n = self.config.prefetch(len(self.worker_conns))
                batch = self._server_rpc({"kind": "task_batch", "n": n})
                for t in batch["v"]:
                    self.tasks.put(t)
            task = None if self._exhausted else self.tasks.get()
            if task is None:
                self._exhausted = True
            else:
                self._reg.counter("gather.tasks_served").inc()
            conn.send(task)
        elif kind == "params":
            have = int(msg["have"])
            want = int(msg.get("want", -1))
            if (
                self._params_version < 0          # cache miss
                or have == self._params_version   # worker already at cache
                or want > self._params_version    # task needs newer weights
            ):
                reply = self._server_rpc(
                    {"kind": "params", "have": self._params_version}
                )
                if reply is not None:
                    self._params_version = int(reply["version"])
                    self._params_msg = reply
            if self._params_msg is not None and have != self._params_version:
                conn.send(self._params_msg)
            else:
                conn.send(None)
        elif kind == "result":
            result = msg["v"]
            # relay point for worker telemetry: keep the newest compact
            # snapshot per worker, strip it from the dedup-keyed upload
            telem = result.pop("_telem", None) if isinstance(result, dict) else None
            if telem is not None:
                self._worker_telem[result.get("worker_id", -1)] = telem
            self._reg.counter("gather.results").inc()
            self.results.append(result)
            if len(self.results) >= self.config.upload_batch:
                self._flush_results()
        elif kind == "worker_error":
            # forward immediately (ahead of batched results) so the server
            # learns about the dead worker without waiting for a batch
            self._server_send({"kind": "worker_error", "v": msg["v"]})
        else:
            logger.warning("gather: unknown message kind %r", kind)

    def _flush_results(self) -> None:
        if self.results:
            batch, self.results = self.results, []
            self._upload_seq += 1
            self._unacked[self._upload_seq] = batch
            self._reg.counter("gather.uploads").inc()
            msg = {"kind": "result_batch", "v": batch, "seq": self._upload_seq}
            if self.config.telemetry_piggyback:
                # the upload frame is the other piggyback carrier: a busy
                # gather reports fresher than the heartbeat cadence for free
                msg["telem"] = self._telemetry_payload()
            self._server_send(msg, compress=self.config.compress_uplink)

    def _resend_unacked(self) -> None:
        """Replay every retained (un-acked) upload on the current link —
        plain sends: the caller owns reconnect-on-failure."""
        for seq in sorted(self._unacked):
            self.server.send(
                {"kind": "result_batch", "v": self._unacked[seq], "seq": seq},
                compress=self.config.compress_uplink,
            )


def gather_main(
    server_conn: Connection,
    config: FleetConfig,
    runner: EpisodeRunner,
    base_worker_id: int,
    num_workers: int,
    reconnect: Optional[Callable[[], Connection]] = None,
) -> None:
    try:
        Gather(
            server_conn, config, runner, base_worker_id, num_workers,
            reconnect=reconnect,
        ).run()
    except (KeyboardInterrupt, ConnectionError, EOFError, OSError):
        pass


# ---------------------------------------------------------------------------
# server


class WorkerServer:
    """Learner-side fleet endpoint.

    Parity with ``WorkerServer`` + ``ParameterServer`` capability
    (``hpc/worker.py:269-297``, ``hpc/parameter_server.py``): an entry
    listener hands out worker-id ranges to remote hosts; a worker listener
    feeds gather connections into a ``QueueHub``; the trainer publishes
    weights and drains episode results.
    """

    def __init__(
        self,
        config: FleetConfig,
        task_source: Callable[[], Optional[Dict[str, Any]]],
        result_maxsize: int = 4096,
        worker_error_maxsize: int = 256,
    ) -> None:
        self.config = config
        self.task_source = task_source
        self.params = ParameterServer()
        # heartbeat plane: the hub pings every gather link and reports a
        # silently-dead one (socket open, peer gone) here within
        # ~2 heartbeat intervals — closed sockets were already detected,
        # silent ones previously hung the fleet forever
        # fleet telemetry merge point: gathers piggyback compact snapshots
        # on pongs and uploads; the hub's recv pump hands every "telem"
        # payload here, and the aggregator's tree rides the process-wide
        # registry snapshot under fleet.*.  BOUNDED: elastic churn mints a
        # fresh source id per respawn, so dead sources must age out instead
        # of accumulating in the learner's view forever
        self.telemetry = TelemetryAggregator(max_sources=1024)
        self.hub = QueueHub(
            heartbeat_interval=config.heartbeat_interval_s,
            heartbeat_timeout=config.heartbeat_timeout
            if config.heartbeat_interval_s > 0
            else 0.0,
            on_dead=self._on_dead_connection,
            on_telemetry=lambda _conn, payload: self.telemetry.absorb_payload(payload),
            max_pending=config.max_pending,
            on_disconnect=self._on_disconnect,
        )
        self.results: "queue.Queue[Dict[str, Any]]" = queue.Queue(result_maxsize)
        # bounded error funnel: nobody is REQUIRED to poll this on a long
        # elastic run (gathers churn constantly on preemptible capacity), so
        # it must never grow without bound — the stalest entry is evicted on
        # overflow while the full history survives as the
        # server.worker_errors_total counter + per-error FlightRecorder
        # events (report_worker_error)
        self.worker_errors: "queue.Queue[Dict[str, Any]]" = queue.Queue(
            worker_error_maxsize
        )
        self.worker_errors_total = 0
        self.worker_errors_dropped = 0
        self.total_results = 0
        self.dropped_results = 0
        # elastic membership roster: conn -> {base_worker_id, num_workers,
        # gather_epoch, draining, joined_t}, fed by gather_hello frames and
        # pruned on disconnect/drain_done — what scale decisions and
        # targeted drains address
        self.gather_links: Dict[Connection, Dict[str, Any]] = {}
        self._roster_lock = threading.Lock()
        self.gathers_joined = 0
        self.gathers_drained = 0
        # exactly-once task accounting across elastic churn: every task
        # handed out carries a monotonic _task_id tracked per link; a dead
        # link's outstanding tasks requeue, and completions dedup at task
        # level so a requeue that raced its original execution counts once
        self._task_lock = threading.Lock()
        self._next_task_id = 0
        self._outstanding: Dict[int, Tuple[Connection, Any]] = {}
        self._conn_tasks: Dict[Connection, Set[int]] = {}
        self._completed_tasks: "OrderedDict[int, None]" = OrderedDict()
        self._completed_cap = 65536
        # open per-task root spans (head-sampled at dispatch; closed by the
        # dedup verdict) — bounded like the completed-task table
        self._task_traces: "OrderedDict[int, Any]" = OrderedDict()
        self._returned_tasks: Deque[Any] = deque()
        self.requeued_tasks = 0
        self.duplicate_tasks = 0
        reg = telemetry.get_registry()
        reg.bind("fleet", self.telemetry.tree)
        reg.bind(
            "server",
            lambda: {
                "total_results": self.total_results,
                "duplicate_results": self.duplicate_results,
                "dropped_results": self.dropped_results,
                "results_queued": self.results.qsize(),
                "worker_errors": self.worker_errors.qsize(),
                "worker_errors_total": self.worker_errors_total,
                "worker_errors_dropped": self.worker_errors_dropped,
                "param_version": self.params.version,
                "live_gathers": self.live_gather_count(),
                "live_workers": self.live_worker_count(),
                "gathers_joined": self.gathers_joined,
                "gathers_drained": self.gathers_drained,
                "outstanding_tasks": len(self._outstanding),
                "requeued_tasks": self.requeued_tasks,
                "duplicate_tasks": self.duplicate_tasks,
            },
        )
        # at-least-once dedup: per worker, per upload_epoch, the newest
        # episode_seq accepted (a bounded few epochs retained per worker) —
        # a reconnect-resent duplicate has the same epoch and a seq we
        # already consumed, and a SLOW duplicate from a dead gather's old
        # epoch stays recognizable even after its respawn registered a
        # fresh epoch (the single-(epoch, seq) table this replaces would
        # have been reset by the late frame and double-counted it)
        self._dedup_seen: Dict[int, "OrderedDict[int, int]"] = {}
        self._dedup_epochs_per_worker = 4
        self.duplicate_results = 0
        self._next_worker_id = 0
        self._id_lock = threading.Lock()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._server_socks: List[Any] = []

    def report_worker_error(self, err: Dict[str, Any]) -> None:
        """One funnel for every fleet failure report: bounded queue for
        pollers, monotonic counter + FlightRecorder event for everyone else
        (the queue may overflow on a long elastic run; the telemetry plane
        never loses the count)."""
        self.worker_errors_total += 1
        telemetry.get_registry().counter("server.worker_errors_total").inc()
        telemetry.record_event(
            "worker_error",
            worker_id=err.get("worker_id"),
            error=str(err.get("error"))[:200],
        )
        while True:
            try:
                self.worker_errors.put_nowait(err)
                return
            except queue.Full:
                try:
                    self.worker_errors.get_nowait()
                    self.worker_errors_dropped += 1
                except queue.Empty:
                    pass

    def _on_dead_connection(self, conn: Connection, reason: str) -> None:
        """Hub liveness verdict: mark the gather's workers dead so the
        trainer sees it (``worker_errors``) instead of silently losing
        throughput.  A socket gather that survived (e.g. network partition
        healed) reconnects on its own and re-registers via the accept
        loop."""
        logger.error("fleet: gather connection declared dead (%s)", reason)
        self.report_worker_error(
            {"worker_id": None, "task": None, "error": f"gather link dead: {reason}"}
        )

    def _on_disconnect(self, conn: Connection) -> None:
        """ANY removal of a gather link (EOF, corrupt frame, liveness
        verdict, preempted node): drop its roster entry and requeue its
        outstanding tasks so the remaining/backfilled fleet picks them up.
        A reconnecting gather still runs those tasks — the task-level
        completion dedup makes the double execution count once."""
        with self._roster_lock:
            self.gather_links.pop(conn, None)
        requeued = []
        with self._task_lock:
            for tid in self._conn_tasks.pop(conn, set()):
                entry = self._outstanding.pop(tid, None)
                if entry is not None and tid not in self._completed_tasks:
                    requeued.append(entry[1])
            self._returned_tasks.extend(requeued)
            self.requeued_tasks += len(requeued)
        if requeued:
            telemetry.get_registry().counter("server.requeued_tasks").inc(
                len(requeued)
            )
            telemetry.record_event(
                "tasks_requeued", count=len(requeued), why="disconnect"
            )
            logger.warning(
                "fleet: requeued %d outstanding tasks from a dropped gather "
                "link", len(requeued),
            )

    def _is_duplicate(self, result: Dict[str, Any]) -> bool:
        """At-least-once dedup on the (worker_id, upload_epoch, episode_seq)
        key stamped by ``worker_loop``.  Per-worker results flow through one
        gather in order (reconnect resends preserve order), so "seq <= newest
        accepted within the same epoch" identifies a resend exactly.  A
        bounded history of recent epochs is kept PER WORKER so a slow
        duplicate from a dead gather (old epoch) arriving after its
        respawn's fresh epoch is still recognized instead of resetting the
        table.  Results without the key (foreign runners) are always
        accepted."""
        wid = result.get("worker_id")
        seq = result.get("episode_seq")
        if wid is None or seq is None:
            return False
        epoch = int(result.get("upload_epoch", 0))
        seq = int(seq)
        epochs = self._dedup_seen.setdefault(wid, OrderedDict())
        last = epochs.get(epoch)
        if last is not None and seq <= last:
            return True
        epochs[epoch] = seq if last is None else max(last, seq)
        epochs.move_to_end(epoch)
        while len(epochs) > self._dedup_epochs_per_worker:
            epochs.popitem(last=False)
        return False

    # -- trainer API ---------------------------------------------------
    def publish(self, weights: Any) -> int:
        return self.params.push(weights)

    def telemetry_snapshot(self) -> Dict[str, Any]:
        """ONE merged tree: this process's registry (server/hub/codec/ring/
        queue/supervisor instruments) plus the fleet aggregator's per-worker
        and aggregate series under ``fleet.*``."""
        return telemetry.snapshot()

    def get_result(self, timeout: Optional[float] = None) -> Optional[Dict[str, Any]]:
        try:
            return self.results.get(timeout=timeout)
        except queue.Empty:
            return None

    def assign_worker_ids(self, n: int) -> int:
        with self._id_lock:
            base = self._next_worker_id
            self._next_worker_id += n
            return base

    # -- elastic membership --------------------------------------------
    def live_gather_count(self) -> int:
        with self._roster_lock:
            return len(self.gather_links)

    def live_worker_count(self) -> int:
        """Workers behind currently-registered, non-draining gather links —
        the roster view of fleet capacity (spawned-but-booting gathers are
        invisible here until their hello lands; executors that spawn
        processes should count those themselves)."""
        with self._roster_lock:
            return sum(
                info["num_workers"]
                for info in self.gather_links.values()
                if not info.get("draining")
            )

    def drain_workers(self, n_workers: int) -> int:
        """Scale-down: ask the newest-joined gathers covering ``n_workers``
        to drain — stop starting episodes, return unstarted tasks, flush and
        await acks, then exit cleanly (``drain_done``).  Returns the worker
        count actually asked to drain.  Zero episodes are lost: in-flight
        episodes complete and upload, unstarted tasks reissue elsewhere."""
        with self._roster_lock:
            candidates = sorted(
                (
                    (conn, info)
                    for conn, info in self.gather_links.items()
                    if not info.get("draining")
                ),
                key=lambda item: item[1].get("joined_t", 0.0),
                reverse=True,  # LIFO: drain the newest capacity first
            )
            picked = []
            covered = 0
            for conn, info in candidates:
                if covered >= n_workers:
                    break
                info["draining"] = True
                picked.append((conn, info))
                covered += info["num_workers"]
        for conn, info in picked:
            telemetry.record_event(
                "drain_request",
                base=info["base_worker_id"],
                workers=info["num_workers"],
            )
            telemetry.get_registry().counter("server.drain_requests").inc()
            self.hub.send(conn, make_drain())
        return covered

    # -- bring-up ------------------------------------------------------
    def start(self, listen: bool = False) -> None:
        self._threads.append(
            threading.Thread(target=self._serve_loop, daemon=True)
        )
        if listen:
            entry = listen_socket(self.config.entry_port)
            workers = listen_socket(self.config.worker_port)
            self._server_socks = [entry, workers]
            self._threads.append(
                threading.Thread(target=self._entry_loop, args=(entry,), daemon=True)
            )
            self._threads.append(
                threading.Thread(target=self._accept_loop, args=(workers,), daemon=True)
            )
        for t in self._threads:
            t.start()

    def add_gather_connection(self, conn: Connection) -> None:
        self.hub.add_connection(conn)

    def _entry_loop(self, sock) -> None:
        while not self._stop.is_set():
            try:
                conn = accept_connection(sock, timeout=0.5)
            except (TimeoutError, OSError):
                continue
            try:
                msg = conn.recv(timeout=10.0)
                if not isinstance(msg, dict) or msg.get("kind") != "entry":
                    raise ProtocolError(
                        f"entry port expects an 'entry' frame, got "
                        f"{msg.get('kind') if isinstance(msg, dict) else type(msg).__name__!r}"
                    )
                n = int(msg["num_workers"])
                base = self.assign_worker_ids(n)
                conn.send(
                    {
                        "kind": "entry_ack",
                        "base_worker_id": base,
                        "config": {
                            "workers_per_gather": self.config.workers_per_gather,
                            "upload_batch": self.config.upload_batch,
                            "worker_port": self.config.worker_port,
                            # liveness policy is the learner's call: remote
                            # hosts adopt its heartbeat cadence so detection
                            # bounds match on both ends of every link
                            "heartbeat_interval_s": self.config.heartbeat_interval_s,
                            "heartbeat_timeout_s": self.config.heartbeat_timeout_s,
                            # like the heartbeat policy, the telemetry
                            # piggyback is the learner's call
                            "telemetry_piggyback": self.config.telemetry_piggyback,
                            "extra": self.config.extra,
                        },
                    }
                )
            except Exception:
                logger.exception("entry handshake failed")
            finally:
                conn.close()

    def _accept_loop(self, sock) -> None:
        while not self._stop.is_set():
            try:
                conn = accept_connection(sock, timeout=0.5)
            except (TimeoutError, OSError):
                continue
            self.hub.add_connection(conn)

    def _serve_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, msg = self.hub.recv(timeout=0.2)
            except queue.Empty:
                continue
            try:
                self._handle(conn, msg)
            except Exception:
                logger.exception("server: failed handling %r", msg.get("kind"))

    def _next_task(self) -> Optional[Any]:
        """Requeued tasks (returned on drain, or orphaned by a dead gather)
        take priority over the source — they were already accounted as
        handed out, and reissue is how a scale event loses zero episodes."""
        with self._task_lock:
            if self._returned_tasks:
                return self._returned_tasks.popleft()
        return None if self._stop.is_set() else self.task_source()

    def _record_outstanding(self, conn: Connection, task: Any) -> Any:
        """Stamp (once) and track the task under the issuing link."""
        if not isinstance(task, dict):
            return task
        task = dict(task)
        with self._task_lock:
            if "_task_id" not in task:
                task["_task_id"] = self._next_task_id
                self._next_task_id += 1
                # head-sampled task trace: the root rides the task frame
                # (dispatch -> worker episode -> upload -> dedup verdict);
                # a requeued task keeps its original context
                root = tracing.start_span(
                    "task", kind="fleet", task=task["_task_id"]
                )
                if root.sampled:
                    self._task_traces[task["_task_id"]] = root
                    while len(self._task_traces) > self._completed_cap:
                        _tid, stale = self._task_traces.popitem(last=False)
                        stale.end(verdict="abandoned")
                    tracing.inject(task, root)
            tid = task["_task_id"]
            self._outstanding[tid] = (conn, task)
            self._conn_tasks.setdefault(conn, set()).add(tid)
        return task

    def _handle(self, conn: Connection, msg: Dict[str, Any]) -> None:
        kind = msg["kind"]
        if kind == "task_batch":
            n = int(msg["n"])
            tasks = []
            for _ in range(n):
                t = self._next_task()
                if t is not None:
                    t = self._record_outstanding(conn, t)
                tasks.append(t)
                if t is None:
                    break
            self.hub.send(conn, {"kind": "task_batch", "v": tasks})
        elif kind == "params":
            weights, version = self.params.pull(int(msg["have"]))
            if weights is None:
                self.hub.send(conn, None)
            else:
                self.hub.send(
                    conn, {"kind": "params", "version": version, "weights": weights}
                )
        elif kind == "result_batch":
            if "seq" in msg:
                # ack FIRST: at-least-once means the gather retains the
                # batch until this lands; dedup below absorbs redelivery
                self.hub.send(conn, {"kind": "result_ack", "seq": msg["seq"]})
            reg = telemetry.get_registry()
            for r in msg["v"]:
                if self._is_duplicate(r):
                    self.duplicate_results += 1
                    reg.counter("server.duplicate_results").inc()
                    continue
                # task-level exactly-once: a task orphaned by a dead/drained
                # gather was requeued and may complete TWICE (the corpse's
                # workers finished it, and so did the reissue) — the second
                # completion is dropped here, keeping the episode count
                # exact across preemption waves
                tid = r.pop("_task_id", None) if isinstance(r, dict) else None
                if tid is not None:
                    with self._task_lock:
                        if tid in self._completed_tasks:
                            self.duplicate_tasks += 1
                            dup_task = True
                        else:
                            self._completed_tasks[tid] = None
                            while len(self._completed_tasks) > self._completed_cap:
                                self._completed_tasks.popitem(last=False)
                            entry = self._outstanding.pop(tid, None)
                            if entry is not None:
                                self._conn_tasks.get(entry[0], set()).discard(tid)
                            dup_task = False
                        root = self._task_traces.pop(tid, None)
                    if root is not None:
                        # the dedup verdict closes the task trace either way
                        root.end(
                            verdict="duplicate" if dup_task else "accepted"
                        )
                    if dup_task:
                        reg.counter("server.duplicate_tasks").inc()
                        continue
                self.total_results += 1
                reg.meter("server.results_per_s").mark()
                try:
                    self.results.put_nowait(r)
                except queue.Full:
                    # backpressure: evict the stalest queued result so the
                    # freshest episodes survive (off-policy freshness)
                    try:
                        self.results.get_nowait()
                        self.dropped_results += 1
                    except queue.Empty:
                        pass
                    try:
                        self.results.put_nowait(r)
                    except queue.Full:
                        self.dropped_results += 1
        elif kind == "gather_hello":
            # dynamic admission: a gather (initial, respawned, late-joining,
            # or reconnecting) announces its worker range — the roster entry
            # is what scale decisions count and targeted drains address
            with self._roster_lock:
                self.gather_links[conn] = {
                    "base_worker_id": int(msg.get("base_worker_id", -1)),
                    "num_workers": int(msg.get("num_workers", 0)),
                    "gather_epoch": int(msg.get("gather_epoch", 0)),
                    "draining": False,
                    "joined_t": time.monotonic(),
                }
                self.gathers_joined += 1
            telemetry.get_registry().counter("server.gathers_joined").inc()
            telemetry.record_event(
                "gather_join",
                base=msg.get("base_worker_id"),
                workers=msg.get("num_workers"),
            )
        elif kind == "task_return":
            # drain protocol: unstarted prefetched tasks come home for
            # reissue — accounting-wise they were never started
            requeued = 0
            with self._task_lock:
                for t in msg["v"]:
                    tid = t.get("_task_id") if isinstance(t, dict) else None
                    if tid is not None:
                        entry = self._outstanding.pop(tid, None)
                        if entry is not None:
                            self._conn_tasks.get(entry[0], set()).discard(tid)
                        if tid in self._completed_tasks:
                            continue  # raced a completion: nothing to redo
                    self._returned_tasks.append(t)
                    requeued += 1
                self.requeued_tasks += requeued
            if requeued:
                telemetry.get_registry().counter("server.requeued_tasks").inc(
                    requeued
                )
                telemetry.record_event(
                    "tasks_requeued", count=requeued, why="drain"
                )
        elif kind == DRAIN_DONE:
            with self._roster_lock:
                info = self.gather_links.pop(conn, None)
                self.gathers_drained += 1
            telemetry.get_registry().counter("server.gathers_drained").inc()
            telemetry.record_event(
                "gather_drained",
                base=msg.get("base_worker_id"),
                workers=(info or {}).get("num_workers"),
            )
            logger.info(
                "fleet: gather %s drained cleanly", msg.get("base_worker_id")
            )
        elif kind == "worker_error":
            err = msg["v"]
            logger.error(
                "fleet worker %s failed on task %r:\n%s",
                err.get("worker_id"),
                err.get("task"),
                err.get("traceback", err.get("error")),
            )
            self.report_worker_error(err)
        else:
            logger.warning("server: unknown message kind %r", kind)

    def stop(self) -> None:
        self._stop.set()
        self.hub.close()
        for s in self._server_socks:
            try:
                s.close()
            except OSError:
                pass


# ---------------------------------------------------------------------------
# clusters


class LocalCluster:
    """Gathers as local processes over pipes (parity: ``WorkerCluster``,
    ``hpc/worker.py:241-258``) — doubles as the multi-node simulator.

    ``max_restarts``: elastic recovery, beyond the reference (whose fleet
    simply forgot dead workers — SURVEY.md §5).  When > 0, a supervisor
    thread respawns a gather that dies unexpectedly — same worker-id range,
    fresh pipe registered with the server, and a fresh ``gather_epoch``
    nonce salting its workers' upload epochs so a slow duplicate from the
    corpse can never collide with the replacement's sequences — up to
    ``max_restarts`` times across the cluster.  The ``QueueHub`` already
    drops the dead pipe; the learner sees at most a brief throughput dip.
    0 (default) keeps the fail-fast behavior (errors surface via
    ``server.worker_errors``).

    Deliberate elasticity rides next to the crash path: ``scale_up`` admits
    fresh gathers mid-run (new worker-id ranges), the server's
    ``drain_workers`` closes gathers with zero episode loss, and
    ``ClusterExecutor`` packages both for ``runtime/autoscaler.py``.
    """

    def __init__(
        self,
        server: WorkerServer,
        config: FleetConfig,
        runner: EpisodeRunner,
        mp_context: Optional[str] = None,
        max_restarts: int = 0,
    ) -> None:
        self.server = server
        self.config = config
        self.runner = runner
        # fork-after-JAX can deadlock in XLA's thread pools; when the
        # parent holds a JAX runtime and no context was requested, start()
        # auto-selects spawn (runners must be picklable, e.g.
        # GenerationRunner over module-level fns)
        self.mp_context = mp_context
        self.max_restarts = max_restarts
        self.restarts = 0
        self.procs: List[mp.Process] = []
        self._spans: List[Tuple[int, int]] = []  # (base_worker_id, n) per gather
        self._ctx = None
        self._scale_lock = threading.Lock()
        self._stopping = threading.Event()
        self._supervisor: Optional[threading.Thread] = None

    def spawned_worker_count(self) -> int:
        """Workers behind live gather processes — the executor-side capacity
        truth (includes gathers still booting, which the server roster
        cannot see yet; excludes the dead and the cleanly exited)."""
        with self._scale_lock:
            return sum(
                n for (base, n), p in zip(self._spans, self.procs) if p.is_alive()
            )

    def scale_up(self, num_workers: int) -> int:
        """Dynamic admission: add ``num_workers`` of capacity mid-run as
        fresh gather processes with FRESH worker-id ranges (never a reuse
        of a dead range — the dedup epochs make reuse safe, fresh ranges
        make it legible).  Returns the worker count actually added."""
        if self._ctx is None:
            raise RuntimeError("scale_up before start(): no mp context yet")
        per = self.config.workers_per_gather
        remaining = int(num_workers)
        added = 0
        while remaining > 0:
            n = min(per, remaining)
            remaining -= n
            base = self.server.assign_worker_ids(n)
            with self._scale_lock:
                self._spawn(len(self.procs), base, n)
            added += n
        return added

    def _spawn(self, slot: int, base: int, n: int) -> None:
        parent, child = self._ctx.Pipe(duplex=True)
        # gathers spawn worker children, so they cannot be daemonic;
        # join() terminates stragglers and their daemonic workers
        proc = self._ctx.Process(
            target=gather_main,
            args=(PipeConnection(child), self.config, self.runner, base, n),
        )
        proc.start()
        child.close()
        self.server.add_gather_connection(PipeConnection(parent))
        if slot < len(self.procs):
            self.procs[slot] = proc
        else:
            self.procs.append(proc)
            self._spans.append((base, n))

    def start(self) -> None:
        from scalerl_tpu.utils.platform import safe_mp_context

        per = self.config.workers_per_gather
        remaining = self.config.num_workers
        self._ctx = mp.get_context(safe_mp_context(self.mp_context))
        for g in range(self.config.num_gathers):
            n = min(per, remaining)
            remaining -= n
            base = self.server.assign_worker_ids(n)
            self._spawn(g, base, n)
        inj = chaos.active()
        mass_kill_armed = inj is not None and inj.plan.rates.get("mass_kill", 0.0) > 0
        if self.max_restarts > 0 or mass_kill_armed:
            # the supervisor doubles as the chaos preemption-wave driver:
            # with mass_kill configured it runs even at max_restarts=0 so
            # the AUTOSCALER (not the respawn budget) does the backfilling
            self._supervisor = threading.Thread(
                target=self._supervise, name="fleet-supervisor", daemon=True
            )
            self._supervisor.start()

    def chaos_poll(self) -> List[int]:
        """One seeded preemption-wave draw against the live gather procs
        (``mass_kill`` chaos kind); returns the killed slot indices."""
        return apply_mass_kill(self.procs, site="fleet")

    def _supervise(self) -> None:
        given_up: set = set()
        while not self._stopping.wait(0.5):
            self.chaos_poll()
            for slot, proc in enumerate(self.procs):
                if (
                    proc.is_alive()
                    or slot in given_up
                    or self._stopping.is_set()
                ):
                    continue
                if proc.exitcode == 0:
                    # clean exit (task source drained): not a failure —
                    # respawning would just burn budget on process churn
                    given_up.add(slot)
                    continue
                if self.restarts >= self.max_restarts:
                    # budget exhausted: surface it the fail-fast way (the
                    # learner polls worker_errors) and keep watching the
                    # OTHER slots rather than abandoning supervision
                    logger.error(
                        "fleet gather %d died (exit %s); restart budget "
                        "exhausted (%d used)",
                        slot, proc.exitcode, self.restarts,
                    )
                    self.server.report_worker_error(
                        {
                            "worker_id": None,
                            "task": None,
                            "error": (
                                f"gather {slot} died (exit {proc.exitcode}); "
                                f"restart budget exhausted "
                                f"({self.restarts}/{self.max_restarts})"
                            ),
                        }
                    )
                    given_up.add(slot)
                    continue
                self.restarts += 1
                base, n = self._spans[slot]
                logger.warning(
                    "fleet gather %d died (exit %s); respawning workers "
                    "%d..%d (restart %d/%d)",
                    slot, proc.exitcode, base, base + n - 1,
                    self.restarts, self.max_restarts,
                )
                self._spawn(slot, base, n)

    def join(self, timeout: float = 10.0) -> None:
        self._stopping.set()
        if self._supervisor is not None:
            self._supervisor.join(timeout=2.0)
        deadline = time.monotonic() + timeout
        for p in self.procs:
            p.join(timeout=max(0.1, deadline - time.monotonic()))
            if p.is_alive():
                p.terminate()


class RemoteCluster:
    """Remote-host side: entry handshake then socket gathers (parity:
    ``RemoteWorkerCluster.run`` + ``entry``, ``hpc/worker.py:300-341``)."""

    def __init__(
        self,
        config: FleetConfig,
        runner: EpisodeRunner,
        num_workers: Optional[int] = None,
        mp_context: Optional[str] = None,
    ) -> None:
        self.config = config
        self.runner = runner
        self.num_workers = num_workers or config.num_workers
        self.mp_context = mp_context  # see LocalCluster: auto-spawn if JAX in parent
        self.procs: List[mp.Process] = []
        self._spans: List[Tuple[int, int]] = []  # (base_worker_id, n) per proc
        self._adopted: Optional[FleetConfig] = None
        self._scale_lock = threading.Lock()

    def entry(self) -> Tuple[int, Dict[str, Any]]:
        conn = connect_socket(self.config.server_host, self.config.entry_port)
        try:
            ack = send_recv(
                conn, {"kind": "entry", "num_workers": self.num_workers, "host": ""}
            )
            if not isinstance(ack, dict) or ack.get("kind") != "entry_ack":
                raise ProtocolError(
                    f"entry handshake expects an 'entry_ack' reply, got "
                    f"{ack.get('kind') if isinstance(ack, dict) else type(ack).__name__!r}"
                )
            return int(ack["base_worker_id"]), ack["config"]
        finally:
            conn.close()

    def _adopt(self, remote_cfg: Dict[str, Any]) -> FleetConfig:
        import dataclasses

        # adopt the learner side's fleet policy from the handshake
        return dataclasses.replace(
            self.config,
            workers_per_gather=int(
                remote_cfg.get("workers_per_gather", self.config.workers_per_gather)
            ),
            worker_port=int(
                remote_cfg.get("worker_port", self.config.worker_port)
            ),
            upload_batch=int(
                remote_cfg.get("upload_batch", self.config.upload_batch)
            ),
            heartbeat_interval_s=float(
                remote_cfg.get(
                    "heartbeat_interval_s", self.config.heartbeat_interval_s
                )
            ),
            heartbeat_timeout_s=float(
                remote_cfg.get(
                    "heartbeat_timeout_s", self.config.heartbeat_timeout_s
                )
            ),
            telemetry_piggyback=bool(
                remote_cfg.get(
                    "telemetry_piggyback", self.config.telemetry_piggyback
                )
            ),
            extra={**self.config.extra, **remote_cfg.get("extra", {})},
        )

    def _launch(self, config: FleetConfig, base: int, num_workers: int) -> None:
        from scalerl_tpu.utils.platform import safe_mp_context

        per = config.workers_per_gather
        remaining = num_workers
        offset = 0
        ctx = mp.get_context(safe_mp_context(self.mp_context))
        while remaining > 0:
            n = min(per, remaining)
            proc = ctx.Process(
                target=_remote_gather_main,
                args=(
                    self.config.server_host,
                    config.worker_port,
                    config,
                    self.runner,
                    base + offset,
                    n,
                ),
            )
            proc.start()
            with self._scale_lock:
                self.procs.append(proc)
                self._spans.append((base + offset, n))
            remaining -= n
            offset += n

    def start(self) -> None:
        base, remote_cfg = self.entry()
        self._adopted = self._adopt(remote_cfg)
        self._launch(self._adopted, base, self.num_workers)

    def scale_up(self, num_workers: int) -> int:
        """Dynamic admission from the remote-host side: a FRESH entry
        handshake mid-run assigns a new worker-id range and new socket
        gathers join the live fleet — the late-join path a spot replacement
        node takes.  Returns the worker count added."""
        base, remote_cfg = self.entry()
        config = self._adopted if self._adopted is not None else self._adopt(remote_cfg)
        self._launch(config, base, int(num_workers))
        return int(num_workers)

    def spawned_worker_count(self) -> int:
        """Executor-side capacity truth (see LocalCluster)."""
        with self._scale_lock:
            return sum(
                n for (base, n), p in zip(self._spans, self.procs) if p.is_alive()
            )

    def chaos_poll(self) -> List[int]:
        """One seeded preemption-wave draw against the gather procs."""
        return apply_mass_kill(self.procs, site="fleet")

    def join(self, timeout: float = 10.0) -> None:
        deadline = time.monotonic() + timeout
        for p in self.procs:
            p.join(timeout=max(0.1, deadline - time.monotonic()))
            if p.is_alive():
                p.terminate()


def _remote_gather_main(host, port, config, runner, base, n) -> None:
    conn = connect_socket(host, port)
    # one attempt per call: Gather._replace_server_conn owns the capped
    # exponential backoff schedule and the max_reconnects budget
    reconnect = lambda: connect_socket(host, port, retries=1)  # noqa: E731
    gather_main(conn, config, runner, base, n, reconnect=reconnect)


# ---------------------------------------------------------------------------
# elasticity: preemption waves + the autoscaler's reference executor


def apply_mass_kill(procs: List[mp.Process], site: str = "fleet") -> List[int]:
    """One ``mass_kill`` chaos draw against ``procs``: when the active
    injector's seeded wave fires, SIGTERM the chosen live peers (a spot
    preemption wave in miniature) and return their indices.  No injector or
    no fire → empty list, zero cost."""
    inj = chaos.active()
    if inj is None:
        return []
    alive = [i for i, p in enumerate(procs) if p.is_alive()]
    victims = inj.mass_kill_victims(len(alive), site=site)
    if not victims:
        return []
    killed = [alive[v] for v in victims]
    for i in killed:
        procs[i].terminate()
    telemetry.record_event("mass_kill", site=site, victims=killed)
    logger.warning(
        "chaos: mass_kill wave terminated %d/%d gathers (slots %s)",
        len(killed), len(alive), killed,
    )
    return killed


def apply_preempt(
    procs: List[mp.Process], site: str = "fleet"
) -> Optional[int]:
    """One ``preempt`` chaos draw against ``procs``: when the active
    injector fires, SIGTERM exactly ONE chosen live peer (a single spot
    reclaim, the unit the preemption-resume machinery must absorb) and
    return its index.  No injector or no fire → ``None``, zero cost."""
    inj = chaos.active()
    if inj is None:
        return None
    alive = [i for i, p in enumerate(procs) if p.is_alive()]
    victim = inj.preempt_victim(len(alive), site=site)
    if victim is None:
        return None
    i = alive[victim]
    procs[i].terminate()
    telemetry.record_event("preempt", site=site, victim=i)
    logger.warning(
        "chaos: preempt SIGTERMed peer slot %d (1/%d alive)", i, len(alive)
    )
    return i


class ClusterExecutor:
    """The autoscaler's reference ``ScaleExecutor`` over a ``WorkerServer``
    plus a Local/RemoteCluster.

    - ``worker_count``: the CLUSTER's spawned-process view (booting gathers
      count; dead ones don't) — using the server roster here would re-fire
      the floor rule every poll while a replacement boots.
    - ``scale_up``: spawn fresh gathers with fresh worker-id ranges
      (``cluster.scale_up``).
    - ``scale_down``: the server's drain protocol (``drain_workers``) — a
      deliberate zero-loss close, never a kill.
    """

    def __init__(self, server: WorkerServer, cluster: Any) -> None:
        self.server = server
        self.cluster = cluster

    def worker_count(self) -> int:
        return self.cluster.spawned_worker_count()

    def scale_up(self, n: int) -> int:
        return self.cluster.scale_up(n)

    def scale_down(self, n: int) -> int:
        return self.server.drain_workers(n)
