"""Actor-fleet protocol: workers, gathers, server, local/remote clusters.

Parity target: ``scalerl/hpc/worker.py`` (27-352) — the HandyRL-style fleet
that the reference vendors import-broken (SURVEY.md §2.1 caveat): a server
hands out rollout/eval tasks, per-host *gathers* fan 16-ish workers into one
uplink with task prefetch, model-blob caching, and batched result upload;
remote hosts join via an entry handshake.

TPU-shaped differences: this is the DCN control plane for **off-mesh CPU
actors** feeding a central TPU learner host (SEED-RL topology).  Weights are
versioned snapshots from ``runtime.param_server.ParameterServer`` (the
reference fetched models by monotonically increasing id with an unbounded
cache; here a gather caches only the newest version).  All payloads ride the
flat binary codec, with zlib on the rollout uplink.

Wire protocol (dicts over ``fleet.transport.Connection``):

    worker→gather   {"kind": "task"}                      request next task
                    {"kind": "params", "have": v}         fetch weights if stale
                    {"kind": "result", "v": {...}}        one episode result
    gather→server   {"kind": "task_batch", "n": k}        prefetch k tasks
                    {"kind": "params", "have": v}
                    {"kind": "result_batch", "v": [...], "seq": s}
                                                          batched upload, retained
                                                          by the gather until acked
    server→gather   {"kind": "task_batch", "v": [t...]}   t=None means stop
                    {"kind": "params", "version": v, "weights": tree}
                    {"kind": "result_ack", "seq": s}      upload s fully received

    Every result carries an at-least-once dedup key (worker_id,
    upload_epoch, episode_seq): un-acked uploads are resent after a
    reconnect — a cut link or a checksum-rejected frame costs a retransmit,
    never a lost or double-counted episode.
    entry handshake {"kind": "entry", "num_workers": n, "host": h}
                    → {"kind": "entry_ack", "base_worker_id": b, "config": {...}}
"""

from __future__ import annotations

import multiprocessing as mp
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from scalerl_tpu.fleet.hub import QueueHub
from scalerl_tpu.fleet.transport import (
    Connection,
    PipeConnection,
    accept_connection,
    connect_socket,
    listen_socket,
    open_worker_pipes,
    send_recv,
    wait_readable,
)
from scalerl_tpu.runtime import telemetry
from scalerl_tpu.runtime.param_server import ParameterServer
from scalerl_tpu.runtime.supervisor import is_heartbeat, make_pong
from scalerl_tpu.runtime.telemetry import TelemetryAggregator
from scalerl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

ENTRY_PORT = 9999
WORKER_PORT = 9998

# EpisodeRunner: (task dict, weights pytree, worker_id) -> result dict
EpisodeRunner = Callable[[Dict[str, Any], Any, int], Dict[str, Any]]


@dataclass
class FleetConfig:
    num_workers: int = 4
    workers_per_gather: int = 16
    task_prefetch: int = 0          # 0 → 1 + workers/4, like the reference
    upload_batch: int = 4           # results batched per uplink message
    compress_uplink: bool = True
    entry_port: int = ENTRY_PORT
    worker_port: int = WORKER_PORT
    server_host: str = "127.0.0.1"
    # Liveness plane (runtime/supervisor.py): the server pings every gather
    # link on this cadence and declares a SILENT (not closed) peer dead
    # after heartbeat_timeout_s (0 → 2 x interval, the detection bound);
    # gathers treat a server link with no traffic for the same window as
    # dead and reconnect.  0 disables heartbeats entirely (pre-supervision
    # behavior: only closed connections are detected).
    heartbeat_interval_s: float = 5.0
    heartbeat_timeout_s: float = 0.0
    # Socket-gather reconnect: capped exponential backoff
    # (supervisor.exp_backoff) after a lost server link, up to max_reconnects
    # attempts across the gather's lifetime before it gives up and exits.
    reconnect_backoff_s: float = 0.5
    reconnect_backoff_cap_s: float = 10.0
    max_reconnects: int = 5
    # Bounded admission (the fleet-wide max_pending/shed_total vocabulary,
    # shared with RolloutQueue and the inference batcher): when > 0, the
    # server hub sheds the stalest queued inbound message once this many
    # are pending instead of blocking its recv pump on a slow consumer —
    # unbounded queue growth silently becomes latency and policy lag.
    # 0 (default) keeps the pre-serving block-on-full behavior.
    max_pending: int = 0
    # Telemetry plane (runtime/telemetry.py): gathers piggyback compact
    # registry snapshots (their own counters + per-worker payloads relayed
    # from worker results) on heartbeat pongs and result-upload frames; the
    # server merges them into per-worker and aggregate series.  No new
    # message kinds or round-trips — just extra dict keys on existing v2
    # codec frames.  False strips the piggyback (pre-telemetry wire shape).
    telemetry_piggyback: bool = True
    extra: Dict[str, Any] = field(default_factory=dict)

    @property
    def num_gathers(self) -> int:
        return 1 + max(0, self.num_workers - 1) // self.workers_per_gather

    def prefetch(self, workers: int) -> int:
        return self.task_prefetch or 1 + workers // 4

    @property
    def heartbeat_timeout(self) -> float:
        return self.heartbeat_timeout_s or 2.0 * self.heartbeat_interval_s


# ---------------------------------------------------------------------------
# worker


def worker_loop(conn: Connection, worker_id: int, runner: EpisodeRunner) -> None:
    """Task loop: parity with ``Worker.run`` (``hpc/worker.py:96-120``).

    Runner exceptions are *reported upstream* before the worker exits —
    the reference's fleet simply forgot dead workers (SURVEY.md §5
    failure-detection notes); here the server surfaces them.

    Every result carries an at-least-once dedup key: ``(worker_id,
    upload_epoch, episode_seq)``.  A gather that loses its server link
    resends the in-flight upload on the fresh connection (PR 2's
    reconnect path), so the server may legitimately see a result twice;
    the per-worker monotonic ``episode_seq`` lets it drop the duplicate
    instead of double-counting the episode into replay.  ``upload_epoch``
    is a random per-worker-process nonce so an elastically *respawned*
    worker (same id, fresh seq counter) is not mistaken for a replay.
    """
    import os as _os
    import traceback

    weights: Any = None
    version = -1
    upload_epoch = int.from_bytes(_os.urandom(4), "big")
    episode_seq = 0
    reg = telemetry.get_registry()
    ep_meter = reg.meter("worker.episodes_per_s")
    try:
        while True:
            task = send_recv(conn, {"kind": "task"})
            if task is None:
                break
            want = int(task.get("param_version", -1))
            if want >= 0 and want != version:
                reply = send_recv(
                    conn, {"kind": "params", "have": version, "want": want}
                )
                if reply is not None:
                    version = int(reply["version"])
                    weights = reply["weights"]
                    reg.counter("worker.param_fetches").inc()
            try:
                result = runner(task, weights, worker_id)
            except Exception as exc:  # noqa: BLE001 - funneled upstream
                reg.counter("worker.errors").inc()
                conn.send(
                    {
                        "kind": "worker_error",
                        "v": {
                            "worker_id": worker_id,
                            "task": task,
                            "error": repr(exc),
                            "traceback": traceback.format_exc(),
                        },
                    }
                )
                break
            result["worker_id"] = worker_id
            result["param_version"] = version
            result["upload_epoch"] = upload_epoch
            result["episode_seq"] = episode_seq
            episode_seq += 1
            reg.counter("worker.episodes").inc()
            ep_meter.mark()
            # compact telemetry piggyback: rides the existing result frame
            # up through the gather to the server's aggregator — no extra
            # messages (the gather strips it before the dedup-keyed upload)
            result["_telem"] = reg.compact()
            conn.send({"kind": "result", "v": result})
    except (EOFError, OSError, ConnectionError, KeyboardInterrupt):
        pass
    finally:
        conn.close()


# ---------------------------------------------------------------------------
# gather


class Gather:
    """Per-host fan-in proxy: parity with ``Gather.run`` (``hpc/worker.py:153-232``).

    Liveness (runtime/supervisor.py): the gather answers server pings in its
    select loop, treats a server link silent past ``config.heartbeat_timeout``
    as dead, and — given a ``reconnect`` factory (socket gathers) — replaces
    the link with capped exponential backoff instead of dying, resending the
    in-flight upload/RPC on the fresh link (at-least-once delivery: the
    server may see a duplicate result batch after a mid-upload cut, which is
    harmless for rollout streams).  Pipe gathers (``LocalCluster``) keep the
    old die-on-error behavior: a dead pipe means a dead parent.
    """

    def __init__(
        self,
        server_conn: Connection,
        config: FleetConfig,
        runner: EpisodeRunner,
        base_worker_id: int,
        num_workers: int,
        reconnect: Optional[Callable[[], Connection]] = None,
    ) -> None:
        self.server = server_conn
        self.config = config
        self.reconnect = reconnect
        self.reconnects_used = 0
        self._server_seen = time.monotonic()
        self.tasks: "queue.Queue[Any]" = queue.Queue()
        self.results: List[Dict[str, Any]] = []
        # at-least-once uploads, completed: every result batch is RETAINED
        # under a gather-local upload seq until the server acks it
        # ("result_ack").  A batch the server never processed — the link
        # was cut mid-frame, or the frame arrived corrupt and was rejected
        # (ProtocolError -> disconnect) — is resent after the reconnect;
        # the server's (worker_id, episode_seq) dedup makes the redelivery
        # exactly-once from replay's point of view.
        self._upload_seq = 0
        self._unacked: Dict[int, List[Dict[str, Any]]] = {}
        self._params_version = -1
        self._params_msg: Any = None
        # telemetry plane: this gather's own counters plus the newest
        # compact snapshot relayed from each worker's result stream; both
        # ride the uplink on pongs and result-batch frames
        self.base_worker_id = base_worker_id
        self._worker_telem: Dict[int, Dict[str, float]] = {}
        self._reg = telemetry.get_registry()
        self._reg.bind(
            "gather",
            lambda: {
                "unacked_uploads": len(self._unacked),
                "live_workers": len(self.worker_conns),
                "reconnects": self.reconnects_used,
                "params_version": self._params_version,
            },
        )
        self.worker_conns, self.worker_procs = open_worker_pipes(
            num_workers,
            worker_loop,
            lambda i: (base_worker_id + i, runner),
        )
        # task source exhausted: serve None to further requests, but keep
        # running until every worker has drained its final result and closed
        self._exhausted = False

    # -- server link ---------------------------------------------------
    def _replace_server_conn(self, why: Exception) -> None:
        """Reconnect with capped exponential backoff, or re-raise ``why``."""
        if self.reconnect is None:
            raise why if isinstance(why, Exception) else ConnectionError(str(why))
        from scalerl_tpu.runtime.supervisor import exp_backoff

        try:
            self.server.close()
        except Exception:  # noqa: BLE001 — link already broken
            pass
        while self.reconnects_used < self.config.max_reconnects:
            delay = exp_backoff(
                self.reconnects_used,
                self.config.reconnect_backoff_s,
                self.config.reconnect_backoff_cap_s,
            )
            self.reconnects_used += 1
            self._reg.counter("gather.reconnect_attempts").inc()
            telemetry.record_event(
                "reconnect", attempt=self.reconnects_used, why=repr(why)
            )
            logger.warning(
                "gather: server link lost (%r); reconnecting in %.2fs "
                "(attempt %d/%d)",
                why, delay, self.reconnects_used, self.config.max_reconnects,
            )
            time.sleep(delay)
            try:
                self.server = self.reconnect()
                self._server_seen = time.monotonic()
                # the cut may have eaten in-flight uploads (or the server
                # rejected a corrupt frame and dropped the link): resend
                # everything unacked on the fresh link; a failure here is
                # just another failed reconnect attempt
                self._resend_unacked()
                return
            except (ConnectionError, OSError) as e:
                why = e
        raise ConnectionError(
            f"gather: server unreachable after {self.reconnects_used} "
            "reconnect attempts"
        ) from why

    def _recv_from_server(self) -> Any:
        """One server frame, heartbeats filtered (pings answered inline).

        On a reconnectable (socket) link with heartbeats enabled the wait is
        bounded by the liveness timeout — a silently-dead server surfaces as
        ``TimeoutError`` for the reconnect path instead of a forever-block.
        Pipe links keep unbounded waits: a pipe cannot die silently (peer
        death closes the fd), and a timeout would only convert a slow server
        on a loaded host into a dead gather.
        """
        timeout = (
            self.config.heartbeat_timeout
            if self.config.heartbeat_interval_s > 0 and self.reconnect is not None
            else None
        )
        while True:
            msg = self.server.recv(timeout=timeout)
            self._server_seen = time.monotonic()
            if is_heartbeat(msg):
                if msg.get("kind") == "ping":
                    self.server.send(self._make_pong(msg))
                continue
            if isinstance(msg, dict) and msg.get("kind") == "result_ack":
                # upload acks arrive unsolicited, possibly ahead of an RPC
                # reply — filter them like heartbeats
                self._unacked.pop(int(msg.get("seq", -1)), None)
                continue
            return msg

    def _server_rpc(self, msg: Dict[str, Any], compress: bool = False) -> Any:
        """send+recv with heartbeat filtering and reconnect-with-retry."""
        while True:
            try:
                self.server.send(msg, compress=compress)
                return self._recv_from_server()
            except (ConnectionError, EOFError, OSError, TimeoutError) as e:
                self._replace_server_conn(e)

    def _server_send(self, msg: Dict[str, Any], compress: bool = False) -> None:
        while True:
            try:
                self.server.send(msg, compress=compress)
                return
            except (ConnectionError, BrokenPipeError, OSError) as e:
                self._replace_server_conn(e)

    def _pump_server(self) -> None:
        """Drain unsolicited server frames (pings) outside any RPC."""
        try:
            while self.server.poll(0):
                msg = self.server.recv()
                self._server_seen = time.monotonic()
                if is_heartbeat(msg):
                    if msg.get("kind") == "ping":
                        self.server.send(self._make_pong(msg))
                elif isinstance(msg, dict) and msg.get("kind") == "result_ack":
                    self._unacked.pop(int(msg.get("seq", -1)), None)
                else:
                    logger.warning(
                        "gather: unsolicited server message %r",
                        msg.get("kind") if isinstance(msg, dict) else type(msg),
                    )
        except (ConnectionError, EOFError, OSError) as e:
            self._replace_server_conn(e)

    # -- telemetry piggyback -------------------------------------------
    def _telemetry_payload(self) -> Dict[str, Any]:
        """Compact snapshot for the uplink: this gather's registry plus the
        newest per-worker snapshots relayed off the result stream."""
        return {
            "src": f"gather:{self.base_worker_id}",
            "v": self._reg.compact(),
            "workers": {str(w): s for w, s in self._worker_telem.items()},
        }

    def _make_pong(self, ping_msg: Dict[str, Any]) -> Dict[str, Any]:
        pong = make_pong(ping_msg)
        if self.config.telemetry_piggyback:
            # heartbeat pongs carry the compact snapshot: a silent-but-idle
            # gather still reports series every heartbeat interval
            pong["telem"] = self._telemetry_payload()
        return pong

    def _check_server_liveness(self) -> None:
        # silent-death is a TCP pathology: pipe links (reconnect=None) skip
        # the staleness verdict — their failure mode is EOF, caught above
        if self.config.heartbeat_interval_s <= 0 or self.reconnect is None:
            return
        if time.monotonic() - self._server_seen > self.config.heartbeat_timeout:
            self._replace_server_conn(
                TimeoutError(
                    "no server traffic for "
                    f"{self.config.heartbeat_timeout:.1f}s"
                )
            )

    # -- main loop -----------------------------------------------------
    def run(self) -> None:
        try:
            while self.worker_conns:
                # snapshot the server link: a reconnect mid-sweep (triggered
                # by any conn in this iteration) replaces self.server, and
                # the STALE object may still sit in ready/dead — it must
                # never be mistaken for a dead worker pipe
                server_conn = self.server
                ready, dead = wait_readable(
                    self.worker_conns + [server_conn], timeout=0.02
                )
                for conn in dead:
                    if conn is server_conn:
                        if conn is self.server:  # not already replaced
                            self._replace_server_conn(
                                ConnectionError("server connection invalid")
                            )
                    elif conn in self.worker_conns:
                        self.worker_conns.remove(conn)
                for conn in ready:
                    if conn is server_conn:
                        if conn is self.server:
                            self._pump_server()
                        continue
                    try:
                        msg = conn.recv()
                    except (EOFError, OSError, ConnectionError):
                        if conn in self.worker_conns:
                            self.worker_conns.remove(conn)
                        continue
                    self._handle(conn, msg)
                self._check_server_liveness()
        finally:
            self._flush_results()
            for c in self.worker_conns:
                c.close()

    def _handle(self, conn: Connection, msg: Dict[str, Any]) -> None:
        kind = msg["kind"]
        if kind == "task":
            if self.tasks.empty() and not self._exhausted:
                n = self.config.prefetch(len(self.worker_conns))
                batch = self._server_rpc({"kind": "task_batch", "n": n})
                for t in batch["v"]:
                    self.tasks.put(t)
            task = None if self._exhausted else self.tasks.get()
            if task is None:
                self._exhausted = True
            else:
                self._reg.counter("gather.tasks_served").inc()
            conn.send(task)
        elif kind == "params":
            have = int(msg["have"])
            want = int(msg.get("want", -1))
            if (
                self._params_version < 0          # cache miss
                or have == self._params_version   # worker already at cache
                or want > self._params_version    # task needs newer weights
            ):
                reply = self._server_rpc(
                    {"kind": "params", "have": self._params_version}
                )
                if reply is not None:
                    self._params_version = int(reply["version"])
                    self._params_msg = reply
            if self._params_msg is not None and have != self._params_version:
                conn.send(self._params_msg)
            else:
                conn.send(None)
        elif kind == "result":
            result = msg["v"]
            # relay point for worker telemetry: keep the newest compact
            # snapshot per worker, strip it from the dedup-keyed upload
            telem = result.pop("_telem", None) if isinstance(result, dict) else None
            if telem is not None:
                self._worker_telem[result.get("worker_id", -1)] = telem
            self._reg.counter("gather.results").inc()
            self.results.append(result)
            if len(self.results) >= self.config.upload_batch:
                self._flush_results()
        elif kind == "worker_error":
            # forward immediately (ahead of batched results) so the server
            # learns about the dead worker without waiting for a batch
            self._server_send({"kind": "worker_error", "v": msg["v"]})
        else:
            logger.warning("gather: unknown message kind %r", kind)

    def _flush_results(self) -> None:
        if self.results:
            batch, self.results = self.results, []
            self._upload_seq += 1
            self._unacked[self._upload_seq] = batch
            self._reg.counter("gather.uploads").inc()
            msg = {"kind": "result_batch", "v": batch, "seq": self._upload_seq}
            if self.config.telemetry_piggyback:
                # the upload frame is the other piggyback carrier: a busy
                # gather reports fresher than the heartbeat cadence for free
                msg["telem"] = self._telemetry_payload()
            self._server_send(msg, compress=self.config.compress_uplink)

    def _resend_unacked(self) -> None:
        """Replay every retained (un-acked) upload on the current link —
        plain sends: the caller owns reconnect-on-failure."""
        for seq in sorted(self._unacked):
            self.server.send(
                {"kind": "result_batch", "v": self._unacked[seq], "seq": seq},
                compress=self.config.compress_uplink,
            )


def gather_main(
    server_conn: Connection,
    config: FleetConfig,
    runner: EpisodeRunner,
    base_worker_id: int,
    num_workers: int,
    reconnect: Optional[Callable[[], Connection]] = None,
) -> None:
    try:
        Gather(
            server_conn, config, runner, base_worker_id, num_workers,
            reconnect=reconnect,
        ).run()
    except (KeyboardInterrupt, ConnectionError, EOFError, OSError):
        pass


# ---------------------------------------------------------------------------
# server


class WorkerServer:
    """Learner-side fleet endpoint.

    Parity with ``WorkerServer`` + ``ParameterServer`` capability
    (``hpc/worker.py:269-297``, ``hpc/parameter_server.py``): an entry
    listener hands out worker-id ranges to remote hosts; a worker listener
    feeds gather connections into a ``QueueHub``; the trainer publishes
    weights and drains episode results.
    """

    def __init__(
        self,
        config: FleetConfig,
        task_source: Callable[[], Optional[Dict[str, Any]]],
        result_maxsize: int = 4096,
    ) -> None:
        self.config = config
        self.task_source = task_source
        self.params = ParameterServer()
        # heartbeat plane: the hub pings every gather link and reports a
        # silently-dead one (socket open, peer gone) here within
        # ~2 heartbeat intervals — closed sockets were already detected,
        # silent ones previously hung the fleet forever
        # fleet telemetry merge point: gathers piggyback compact snapshots
        # on pongs and uploads; the hub's recv pump hands every "telem"
        # payload here, and the aggregator's tree rides the process-wide
        # registry snapshot under fleet.*
        self.telemetry = TelemetryAggregator()
        self.hub = QueueHub(
            heartbeat_interval=config.heartbeat_interval_s,
            heartbeat_timeout=config.heartbeat_timeout
            if config.heartbeat_interval_s > 0
            else 0.0,
            on_dead=self._on_dead_connection,
            on_telemetry=lambda _conn, payload: self.telemetry.absorb_payload(payload),
            max_pending=config.max_pending,
        )
        self.results: "queue.Queue[Dict[str, Any]]" = queue.Queue(result_maxsize)
        self.worker_errors: "queue.Queue[Dict[str, Any]]" = queue.Queue()
        self.total_results = 0
        self.dropped_results = 0
        reg = telemetry.get_registry()
        reg.bind("fleet", self.telemetry.tree)
        reg.bind(
            "server",
            lambda: {
                "total_results": self.total_results,
                "duplicate_results": self.duplicate_results,
                "dropped_results": self.dropped_results,
                "results_queued": self.results.qsize(),
                "worker_errors": self.worker_errors.qsize(),
                "param_version": self.params.version,
            },
        )
        # at-least-once dedup: per worker, the (upload_epoch, newest
        # episode_seq) accepted; a reconnect-resent duplicate has the same
        # epoch and a seq we already consumed
        self._dedup_seen: Dict[int, Tuple[int, int]] = {}
        self.duplicate_results = 0
        self._next_worker_id = 0
        self._id_lock = threading.Lock()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._server_socks: List[Any] = []

    def _on_dead_connection(self, conn: Connection, reason: str) -> None:
        """Hub liveness verdict: mark the gather's workers dead so the
        trainer sees it (``worker_errors``) instead of silently losing
        throughput.  A socket gather that survived (e.g. network partition
        healed) reconnects on its own and re-registers via the accept
        loop."""
        logger.error("fleet: gather connection declared dead (%s)", reason)
        self.worker_errors.put(
            {"worker_id": None, "task": None, "error": f"gather link dead: {reason}"}
        )

    def _is_duplicate(self, result: Dict[str, Any]) -> bool:
        """At-least-once dedup on the (worker_id, upload_epoch, episode_seq)
        key stamped by ``worker_loop``.  Per-worker results flow through one
        gather in order (reconnect resends preserve order), so "seq <= newest
        accepted within the same epoch" identifies a resend exactly.  Results
        without the key (foreign runners) are always accepted."""
        wid = result.get("worker_id")
        seq = result.get("episode_seq")
        if wid is None or seq is None:
            return False
        epoch = int(result.get("upload_epoch", 0))
        seq = int(seq)
        last = self._dedup_seen.get(wid)
        if last is not None and last[0] == epoch and seq <= last[1]:
            return True
        self._dedup_seen[wid] = (
            (epoch, seq)
            if last is None or last[0] != epoch
            else (epoch, max(last[1], seq))
        )
        return False

    # -- trainer API ---------------------------------------------------
    def publish(self, weights: Any) -> int:
        return self.params.push(weights)

    def telemetry_snapshot(self) -> Dict[str, Any]:
        """ONE merged tree: this process's registry (server/hub/codec/ring/
        queue/supervisor instruments) plus the fleet aggregator's per-worker
        and aggregate series under ``fleet.*``."""
        return telemetry.snapshot()

    def get_result(self, timeout: Optional[float] = None) -> Optional[Dict[str, Any]]:
        try:
            return self.results.get(timeout=timeout)
        except queue.Empty:
            return None

    def assign_worker_ids(self, n: int) -> int:
        with self._id_lock:
            base = self._next_worker_id
            self._next_worker_id += n
            return base

    # -- bring-up ------------------------------------------------------
    def start(self, listen: bool = False) -> None:
        self._threads.append(
            threading.Thread(target=self._serve_loop, daemon=True)
        )
        if listen:
            entry = listen_socket(self.config.entry_port)
            workers = listen_socket(self.config.worker_port)
            self._server_socks = [entry, workers]
            self._threads.append(
                threading.Thread(target=self._entry_loop, args=(entry,), daemon=True)
            )
            self._threads.append(
                threading.Thread(target=self._accept_loop, args=(workers,), daemon=True)
            )
        for t in self._threads:
            t.start()

    def add_gather_connection(self, conn: Connection) -> None:
        self.hub.add_connection(conn)

    def _entry_loop(self, sock) -> None:
        while not self._stop.is_set():
            try:
                conn = accept_connection(sock, timeout=0.5)
            except (TimeoutError, OSError):
                continue
            try:
                msg = conn.recv(timeout=10.0)
                n = int(msg["num_workers"])
                base = self.assign_worker_ids(n)
                conn.send(
                    {
                        "kind": "entry_ack",
                        "base_worker_id": base,
                        "config": {
                            "workers_per_gather": self.config.workers_per_gather,
                            "upload_batch": self.config.upload_batch,
                            "worker_port": self.config.worker_port,
                            # liveness policy is the learner's call: remote
                            # hosts adopt its heartbeat cadence so detection
                            # bounds match on both ends of every link
                            "heartbeat_interval_s": self.config.heartbeat_interval_s,
                            "heartbeat_timeout_s": self.config.heartbeat_timeout_s,
                            # like the heartbeat policy, the telemetry
                            # piggyback is the learner's call
                            "telemetry_piggyback": self.config.telemetry_piggyback,
                            "extra": self.config.extra,
                        },
                    }
                )
            except Exception:
                logger.exception("entry handshake failed")
            finally:
                conn.close()

    def _accept_loop(self, sock) -> None:
        while not self._stop.is_set():
            try:
                conn = accept_connection(sock, timeout=0.5)
            except (TimeoutError, OSError):
                continue
            self.hub.add_connection(conn)

    def _serve_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, msg = self.hub.recv(timeout=0.2)
            except queue.Empty:
                continue
            try:
                self._handle(conn, msg)
            except Exception:
                logger.exception("server: failed handling %r", msg.get("kind"))

    def _handle(self, conn: Connection, msg: Dict[str, Any]) -> None:
        kind = msg["kind"]
        if kind == "task_batch":
            n = int(msg["n"])
            tasks = []
            for _ in range(n):
                t = None if self._stop.is_set() else self.task_source()
                tasks.append(t)
                if t is None:
                    break
            self.hub.send(conn, {"kind": "task_batch", "v": tasks})
        elif kind == "params":
            weights, version = self.params.pull(int(msg["have"]))
            if weights is None:
                self.hub.send(conn, None)
            else:
                self.hub.send(
                    conn, {"kind": "params", "version": version, "weights": weights}
                )
        elif kind == "result_batch":
            if "seq" in msg:
                # ack FIRST: at-least-once means the gather retains the
                # batch until this lands; dedup below absorbs redelivery
                self.hub.send(conn, {"kind": "result_ack", "seq": msg["seq"]})
            reg = telemetry.get_registry()
            for r in msg["v"]:
                if self._is_duplicate(r):
                    self.duplicate_results += 1
                    reg.counter("server.duplicate_results").inc()
                    continue
                self.total_results += 1
                reg.meter("server.results_per_s").mark()
                try:
                    self.results.put_nowait(r)
                except queue.Full:
                    # backpressure: evict the stalest queued result so the
                    # freshest episodes survive (off-policy freshness)
                    try:
                        self.results.get_nowait()
                        self.dropped_results += 1
                    except queue.Empty:
                        pass
                    try:
                        self.results.put_nowait(r)
                    except queue.Full:
                        self.dropped_results += 1
        elif kind == "worker_error":
            err = msg["v"]
            logger.error(
                "fleet worker %s failed on task %r:\n%s",
                err.get("worker_id"),
                err.get("task"),
                err.get("traceback", err.get("error")),
            )
            telemetry.record_event(
                "worker_error",
                worker_id=err.get("worker_id"),
                error=err.get("error"),
            )
            self.worker_errors.put(err)
        else:
            logger.warning("server: unknown message kind %r", kind)

    def stop(self) -> None:
        self._stop.set()
        self.hub.close()
        for s in self._server_socks:
            try:
                s.close()
            except OSError:
                pass


# ---------------------------------------------------------------------------
# clusters


class LocalCluster:
    """Gathers as local processes over pipes (parity: ``WorkerCluster``,
    ``hpc/worker.py:241-258``) — doubles as the multi-node simulator.

    ``max_restarts``: elastic recovery, beyond the reference (whose fleet
    simply forgot dead workers — SURVEY.md §5).  When > 0, a supervisor
    thread respawns a gather that dies unexpectedly — same worker-id range,
    fresh pipe registered with the server — up to ``max_restarts`` times
    across the cluster.  The ``QueueHub`` already drops the dead pipe; the
    learner sees at most a brief throughput dip.  0 (default) keeps the
    fail-fast behavior (errors surface via ``server.worker_errors``).
    """

    def __init__(
        self,
        server: WorkerServer,
        config: FleetConfig,
        runner: EpisodeRunner,
        mp_context: Optional[str] = None,
        max_restarts: int = 0,
    ) -> None:
        self.server = server
        self.config = config
        self.runner = runner
        # fork-after-JAX can deadlock in XLA's thread pools; when the
        # parent holds a JAX runtime and no context was requested, start()
        # auto-selects spawn (runners must be picklable, e.g.
        # GenerationRunner over module-level fns)
        self.mp_context = mp_context
        self.max_restarts = max_restarts
        self.restarts = 0
        self.procs: List[mp.Process] = []
        self._spans: List[Tuple[int, int]] = []  # (base_worker_id, n) per gather
        self._ctx = None
        self._stopping = threading.Event()
        self._supervisor: Optional[threading.Thread] = None

    def _spawn(self, slot: int, base: int, n: int) -> None:
        parent, child = self._ctx.Pipe(duplex=True)
        # gathers spawn worker children, so they cannot be daemonic;
        # join() terminates stragglers and their daemonic workers
        proc = self._ctx.Process(
            target=gather_main,
            args=(PipeConnection(child), self.config, self.runner, base, n),
        )
        proc.start()
        child.close()
        self.server.add_gather_connection(PipeConnection(parent))
        if slot < len(self.procs):
            self.procs[slot] = proc
        else:
            self.procs.append(proc)
            self._spans.append((base, n))

    def start(self) -> None:
        from scalerl_tpu.utils.platform import safe_mp_context

        per = self.config.workers_per_gather
        remaining = self.config.num_workers
        self._ctx = mp.get_context(safe_mp_context(self.mp_context))
        for g in range(self.config.num_gathers):
            n = min(per, remaining)
            remaining -= n
            base = self.server.assign_worker_ids(n)
            self._spawn(g, base, n)
        if self.max_restarts > 0:
            self._supervisor = threading.Thread(
                target=self._supervise, name="fleet-supervisor", daemon=True
            )
            self._supervisor.start()

    def _supervise(self) -> None:
        given_up: set = set()
        while not self._stopping.wait(0.5):
            for slot, proc in enumerate(self.procs):
                if (
                    proc.is_alive()
                    or slot in given_up
                    or self._stopping.is_set()
                ):
                    continue
                if proc.exitcode == 0:
                    # clean exit (task source drained): not a failure —
                    # respawning would just burn budget on process churn
                    given_up.add(slot)
                    continue
                if self.restarts >= self.max_restarts:
                    # budget exhausted: surface it the fail-fast way (the
                    # learner polls worker_errors) and keep watching the
                    # OTHER slots rather than abandoning supervision
                    logger.error(
                        "fleet gather %d died (exit %s); restart budget "
                        "exhausted (%d used)",
                        slot, proc.exitcode, self.restarts,
                    )
                    self.server.worker_errors.put(
                        {
                            "worker_id": None,
                            "task": None,
                            "error": (
                                f"gather {slot} died (exit {proc.exitcode}); "
                                f"restart budget exhausted "
                                f"({self.restarts}/{self.max_restarts})"
                            ),
                        }
                    )
                    given_up.add(slot)
                    continue
                self.restarts += 1
                base, n = self._spans[slot]
                logger.warning(
                    "fleet gather %d died (exit %s); respawning workers "
                    "%d..%d (restart %d/%d)",
                    slot, proc.exitcode, base, base + n - 1,
                    self.restarts, self.max_restarts,
                )
                self._spawn(slot, base, n)

    def join(self, timeout: float = 10.0) -> None:
        self._stopping.set()
        if self._supervisor is not None:
            self._supervisor.join(timeout=2.0)
        deadline = time.monotonic() + timeout
        for p in self.procs:
            p.join(timeout=max(0.1, deadline - time.monotonic()))
            if p.is_alive():
                p.terminate()


class RemoteCluster:
    """Remote-host side: entry handshake then socket gathers (parity:
    ``RemoteWorkerCluster.run`` + ``entry``, ``hpc/worker.py:300-341``)."""

    def __init__(
        self,
        config: FleetConfig,
        runner: EpisodeRunner,
        num_workers: Optional[int] = None,
        mp_context: Optional[str] = None,
    ) -> None:
        self.config = config
        self.runner = runner
        self.num_workers = num_workers or config.num_workers
        self.mp_context = mp_context  # see LocalCluster: auto-spawn if JAX in parent
        self.procs: List[mp.Process] = []

    def entry(self) -> Tuple[int, Dict[str, Any]]:
        conn = connect_socket(self.config.server_host, self.config.entry_port)
        try:
            ack = send_recv(
                conn, {"kind": "entry", "num_workers": self.num_workers, "host": ""}
            )
            return int(ack["base_worker_id"]), ack["config"]
        finally:
            conn.close()

    def start(self) -> None:
        import dataclasses

        base, remote_cfg = self.entry()
        # adopt the learner side's fleet policy from the handshake
        config = dataclasses.replace(
            self.config,
            workers_per_gather=int(
                remote_cfg.get("workers_per_gather", self.config.workers_per_gather)
            ),
            worker_port=int(
                remote_cfg.get("worker_port", self.config.worker_port)
            ),
            upload_batch=int(
                remote_cfg.get("upload_batch", self.config.upload_batch)
            ),
            heartbeat_interval_s=float(
                remote_cfg.get(
                    "heartbeat_interval_s", self.config.heartbeat_interval_s
                )
            ),
            heartbeat_timeout_s=float(
                remote_cfg.get(
                    "heartbeat_timeout_s", self.config.heartbeat_timeout_s
                )
            ),
            telemetry_piggyback=bool(
                remote_cfg.get(
                    "telemetry_piggyback", self.config.telemetry_piggyback
                )
            ),
            extra={**self.config.extra, **remote_cfg.get("extra", {})},
        )
        from scalerl_tpu.utils.platform import safe_mp_context

        per = config.workers_per_gather
        remaining = self.num_workers
        offset = 0
        ctx = mp.get_context(safe_mp_context(self.mp_context))
        while remaining > 0:
            n = min(per, remaining)
            proc = ctx.Process(
                target=_remote_gather_main,
                args=(
                    self.config.server_host,
                    config.worker_port,
                    config,
                    self.runner,
                    base + offset,
                    n,
                ),
            )
            proc.start()
            self.procs.append(proc)
            remaining -= n
            offset += n

    def join(self, timeout: float = 10.0) -> None:
        deadline = time.monotonic() + timeout
        for p in self.procs:
            p.join(timeout=max(0.1, deadline - time.monotonic()))
            if p.is_alive():
                p.terminate()


def _remote_gather_main(host, port, config, runner, base, n) -> None:
    conn = connect_socket(host, port)
    # one attempt per call: Gather._replace_server_conn owns the capped
    # exponential backoff schedule and the max_reconnects budget
    reconnect = lambda: connect_socket(host, port, retries=1)  # noqa: E731
    gather_main(conn, config, runner, base, n, reconnect=reconnect)
