"""Episode generation for fleet workers.

Parity target: ``Generator`` (``scalerl/hpc/generation.py:16-183``) — turn
-based multi-player rollouts with legal-action masking, per-player discounted
returns, and episodes shipped as compressed fixed-size chunks.

TPU-shaped differences: steps are accumulated into *fixed-shape* numpy
chunks (padded, with an explicit ``length``) so the learner host can stack
them straight into ``[T, B]`` device batches (SURVEY.md §7 "dynamic episode
lengths vs static shapes"); masking uses an additive ``-inf`` mask + stable
softmax rather than the reference's ``+1e32`` legal-logit trick
(``generation.py:109-118``).  Compression happens at the transport layer
(``FleetConfig.compress_uplink``), not with per-episode bz2.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Protocol, Sequence

import numpy as np


class TurnBasedEnv(Protocol):
    """Minimal turn-based multi-player env protocol (HandyRL-style)."""

    def reset(self, seed: Optional[int] = None) -> None: ...
    def players(self) -> Sequence[int]: ...
    def turn(self) -> int: ...
    def terminal(self) -> bool: ...
    def observation(self, player: int) -> np.ndarray: ...
    def legal_actions(self, player: int) -> Sequence[int]: ...
    def play(self, action: int) -> None: ...
    def outcome(self) -> Dict[int, float]: ...


# PolicyFn: (weights, observation, player) -> action logits [num_actions]
PolicyFn = Callable[[Any, np.ndarray, int], np.ndarray]


def masked_softmax(logits: np.ndarray, legal: Sequence[int]) -> np.ndarray:
    """Probabilities over all actions with illegal ones exactly zero."""
    mask = np.full(logits.shape, -np.inf, dtype=np.float32)
    mask[list(legal)] = 0.0
    z = logits.astype(np.float32) + mask
    z -= z[list(legal)].max()
    e = np.where(np.isneginf(z), 0.0, np.exp(z))
    return e / e.sum()


def discounted_returns(
    rewards: np.ndarray, gamma: float, block: int = 64
) -> np.ndarray:
    """Per-step discounted return (reference ``generation.py:143-147``),
    vectorized.

    The reverse recursion ``acc = r_t + gamma * acc`` is a scaled prefix
    sum: within a window, ``out_t = (sum_{u>=t} r_u * gamma^u) / gamma^t``.
    Dividing by ``gamma^t`` underflows float64 for long horizons at small
    gamma, so the episode is processed in blocks of ``block`` steps from
    the end — each block is one vectorized reverse cumsum in float64 (with
    the carry from later blocks folded in as ``gamma^(n-t) * acc``), and
    ``gamma^block`` stays comfortably inside the float64 range for any
    realistic discount.  Exact (modulo float64 rounding) match to the old
    Python loop, without the per-step host loop a worker pays on every
    episode.
    """
    r = np.asarray(rewards, dtype=np.float64)
    T = len(r)
    if T == 0:
        return np.zeros(0, dtype=np.float32)
    if gamma == 0.0:
        return r.astype(np.float32)
    if gamma == 1.0:
        return np.cumsum(r[::-1])[::-1].astype(np.float32)
    out = np.empty(T, dtype=np.float64)
    acc = 0.0
    for end in range(T, 0, -block):
        start = max(end - block, 0)
        x = r[start:end]
        n = len(x)
        w = np.power(float(gamma), np.arange(n))  # gamma^t within the block
        s = np.cumsum((x * w)[::-1])[::-1]  # sum_{u>=t} x_u * gamma^u
        out[start:end] = s / w + acc * np.power(
            float(gamma), np.arange(n, 0, -1)
        )
        acc = out[start]
    return out.astype(np.float32)


class EpisodeGenerator:
    """Runs one turn-based episode and emits fixed-shape padded chunks."""

    def __init__(
        self,
        env: TurnBasedEnv,
        policy_fn: PolicyFn,
        num_actions: int,
        gamma: float = 1.0,
        chunk_len: int = 64,
        temperature: float = 1.0,
    ) -> None:
        self.env = env
        self.policy_fn = policy_fn
        self.num_actions = num_actions
        self.gamma = gamma
        self.chunk_len = chunk_len
        self.temperature = temperature

    def generate(
        self, weights: Any, seed: Optional[int] = None, greedy: bool = False
    ) -> Dict[str, Any]:
        rng = np.random.default_rng(seed)
        env = self.env
        env.reset(seed=seed)
        obs_l: List[np.ndarray] = []
        act_l: List[int] = []
        probs_l: List[np.ndarray] = []
        player_l: List[int] = []
        while not env.terminal():
            player = env.turn()
            obs = np.asarray(env.observation(player))
            legal = env.legal_actions(player)
            logits = self.policy_fn(weights, obs, player)
            probs = masked_softmax(logits / max(self.temperature, 1e-6), legal)
            if greedy:
                action = int(np.argmax(probs))
            else:
                action = int(rng.choice(self.num_actions, p=probs))
            env.play(action)
            obs_l.append(obs)
            act_l.append(action)
            probs_l.append(probs)
            player_l.append(player)
        outcome = env.outcome()
        T = len(act_l)
        players = np.asarray(player_l, dtype=np.int32)
        # per-player reward stream: outcome at that player's last move,
        # discounted back through *their own* moves
        returns = np.zeros(T, dtype=np.float32)
        for p, score in outcome.items():
            idx = np.nonzero(players == p)[0]
            if len(idx) == 0:
                continue
            r = np.zeros(len(idx), dtype=np.float32)
            r[-1] = float(score)
            returns[idx] = discounted_returns(r, self.gamma)
        episode = {
            "obs": np.stack(obs_l) if obs_l else np.zeros((0,), np.float32),
            "action": np.asarray(act_l, dtype=np.int32),
            "probs": np.stack(probs_l) if probs_l else np.zeros((0,), np.float32),
            "player": players,
            "returns": returns,
            "length": T,
            "outcome": {int(k): float(v) for k, v in outcome.items()},
        }
        return {"chunks": self._chunk(episode), "length": T,
                "outcome": episode["outcome"]}

    def _chunk(self, episode: Dict[str, Any]) -> List[Dict[str, Any]]:
        """Split into fixed-shape, zero-padded chunks of ``chunk_len``."""
        T = episode["length"]
        chunks = []
        for start in range(0, max(T, 1), self.chunk_len):
            end = min(start + self.chunk_len, T)
            n = end - start
            chunk: Dict[str, Any] = {"start": start, "length": n}
            for key in ("obs", "action", "probs", "player", "returns"):
                arr = episode[key][start:end]
                if n < self.chunk_len:
                    pad = [(0, self.chunk_len - n)] + [(0, 0)] * (arr.ndim - 1)
                    arr = np.pad(arr, pad)
                chunk[key] = arr
            chunks.append(chunk)
        return chunks


class GenerationRunner:
    """Fleet ``EpisodeRunner`` running turn-based generation
    (``role='rollout'``) or greedy evaluation (``role='eval'``), mirroring
    the reference's ``role=='g'``/``'e'`` split (``hpc/worker.py:108-116``).

    A class (not a closure) so it pickles across ``spawn`` process
    boundaries when ``env_fn``/``policy_fn`` are module-level callables;
    the lazily-built :class:`EpisodeGenerator` is excluded from the pickle.
    """

    def __init__(
        self,
        env_fn: Callable[[], TurnBasedEnv],
        policy_fn: PolicyFn,
        num_actions: int,
        gamma: float = 1.0,
        chunk_len: int = 64,
    ) -> None:
        self.env_fn = env_fn
        self.policy_fn = policy_fn
        self.num_actions = num_actions
        self.gamma = gamma
        self.chunk_len = chunk_len
        self._gen: Any = None

    def __getstate__(self):
        state = dict(self.__dict__)
        state["_gen"] = None
        return state

    def __call__(
        self, task: Dict[str, Any], weights: Any, worker_id: int
    ) -> Dict[str, Any]:
        if self._gen is None:
            self._gen = EpisodeGenerator(
                self.env_fn(),
                self.policy_fn,
                self.num_actions,
                gamma=self.gamma,
                chunk_len=self.chunk_len,
            )
        greedy = task.get("role") == "eval"
        out = self._gen.generate(weights, seed=task.get("seed"), greedy=greedy)
        out["role"] = task.get("role", "rollout")
        return out


def make_generation_runner(
    env_fn: Callable[[], TurnBasedEnv],
    policy_fn: PolicyFn,
    num_actions: int,
    gamma: float = 1.0,
    chunk_len: int = 64,
) -> GenerationRunner:
    """Factory kept for API stability; see :class:`GenerationRunner`."""
    return GenerationRunner(env_fn, policy_fn, num_actions, gamma, chunk_len)
