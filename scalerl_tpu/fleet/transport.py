"""Connection primitives for the actor fleet: sockets and process pipes.

Parity target: ``PickledConnection`` + the socket/pipe helpers of
``scalerl/hpc/connection.py:12-204``.  Same capability surface — blocking
framed send/recv over TCP, listen/accept/connect with retry, and N-process
pipe fan-out — but every payload goes through the flat binary codec
(``framing.py``) instead of pickle, so the same bytes flow over DCN sockets
and local pipes.
"""

from __future__ import annotations

import multiprocessing as mp
import socket
import time
from typing import Any, Callable, List, Optional, Tuple

from scalerl_tpu.fleet.framing import (
    _LEN,
    ProtocolError,
    pack_message,
    recv_frame,
    send_frame,
    unpack_message,
)
from scalerl_tpu.runtime import chaos


class Connection:
    """Uniform duplex message connection (codec-framed)."""

    def send(self, msg: Any, compress: bool = False) -> None:
        raise NotImplementedError

    def recv(self, timeout: Optional[float] = None) -> Any:
        raise NotImplementedError

    def poll(self, timeout: float = 0.0) -> bool:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError

    def fileno(self) -> int:
        raise NotImplementedError


class SocketConnection(Connection):
    def __init__(self, sock: socket.socket, chaos_site: str = "sock") -> None:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.sock = sock
        self.chaos_site = chaos_site

    def send(self, msg: Any, compress: bool = False) -> None:
        data = pack_message(msg, compress=compress)
        inj = chaos.active()
        if inj is None:
            send_frame(self.sock, data)
            return
        frames, kill = inj.frame_faults(data, site=self.chaos_site)
        for f in frames:
            send_frame(self.sock, f)
        if kill is not None:
            # mid-frame peer death: the length prefix promises the full
            # frame, the bytes stop half-way, then the link dies — the peer
            # sees ConnectionError("peer closed mid-frame")
            try:
                self.sock.sendall(_LEN.pack(len(data)) + kill)
            finally:
                self.close()
            raise ProtocolError("chaos: peer killed mid-frame")

    def recv(self, timeout: Optional[float] = None) -> Any:
        # timeout applies only to frame *arrival*: once the length prefix
        # starts, reads block to completion — a mid-frame timeout would
        # discard consumed bytes and desynchronize the stream
        if timeout is not None and not self.poll(timeout):
            raise TimeoutError("socket recv timed out")
        return unpack_message(recv_frame(self.sock))

    def poll(self, timeout: float = 0.0) -> bool:
        import select

        r, _, _ = select.select([self.sock], [], [], timeout)
        return bool(r)

    def close(self) -> None:
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()

    def fileno(self) -> int:
        return self.sock.fileno()


class PipeConnection(Connection):
    """mp.Pipe end speaking the same codec (bytes over the pipe)."""

    def __init__(self, conn, chaos_site: str = "pipe") -> None:
        self.conn = conn
        self.chaos_site = chaos_site

    def send(self, msg: Any, compress: bool = False) -> None:
        data = pack_message(msg, compress=compress)
        inj = chaos.active()
        if inj is None:
            self.conn.send_bytes(data)
            return
        frames, kill = inj.frame_faults(data, site=self.chaos_site)
        for f in frames:
            self.conn.send_bytes(f)
        if kill is not None:
            # pipes frame at message level, so "mid-frame" is a truncated
            # message followed by a dead fd
            try:
                self.conn.send_bytes(kill)
            finally:
                self.close()
            raise ProtocolError("chaos: peer killed mid-frame")

    def recv(self, timeout: Optional[float] = None) -> Any:
        if timeout is not None and not self.conn.poll(timeout):
            raise TimeoutError("pipe recv timed out")
        return unpack_message(self.conn.recv_bytes())

    def poll(self, timeout: float = 0.0) -> bool:
        return self.conn.poll(timeout)

    def close(self) -> None:
        self.conn.close()

    def fileno(self) -> int:
        return self.conn.fileno()


def send_recv(conn: Connection, msg: Any) -> Any:
    conn.send(msg)
    return conn.recv()


def wait_readable(
    conns: List[Connection], timeout: float = 0.05
) -> Tuple[List[Connection], List[Connection]]:
    """One ``select`` over all connections: (readable, dead).

    O(1) sweep regardless of fleet size — per-connection ``poll`` loops pay
    ``timeout`` per *idle* connection.  Closed/invalid fds come back in
    ``dead`` for the caller to drop.
    """
    import select

    by_fd = {}
    dead: List[Connection] = []
    for c in conns:
        try:
            by_fd[c.fileno()] = c
        except (OSError, ValueError):
            dead.append(c)
    if not by_fd:
        if not dead:
            time.sleep(timeout)
        return [], dead
    try:
        r, _, _ = select.select(list(by_fd), [], [], timeout)
    except (OSError, ValueError):
        # some fd went bad between fileno() and select: probe individually
        ready = []
        for fd, c in list(by_fd.items()):
            try:
                rr, _, _ = select.select([fd], [], [], 0)
            except (OSError, ValueError):
                dead.append(c)
                continue
            ready.extend(rr)
        r = ready
    return [by_fd[fd] for fd in r], dead


# ---------------------------------------------------------------------------
# bring-up helpers


def listen_socket(port: int, host: str = "", backlog: int = 128) -> socket.socket:
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    sock.bind((host, port))
    sock.listen(backlog)
    return sock


def accept_connection(server_sock: socket.socket, timeout: Optional[float] = None) -> SocketConnection:
    server_sock.settimeout(timeout)
    try:
        sock, _addr = server_sock.accept()
        return SocketConnection(sock)
    finally:
        server_sock.settimeout(None)


def connect_socket(
    host: str,
    port: int,
    retries: int = 30,
    delay: float = 0.2,
    backoff_cap: Optional[float] = None,
) -> SocketConnection:
    """Connect with retry — fleet bring-up order is not deterministic.

    ``backoff_cap``: when set, the retry delay grows exponentially from
    ``delay`` up to the cap (``supervisor.exp_backoff``) instead of staying
    fixed — the reconnect-after-server-loss schedule, where hammering a
    recovering learner at a fixed high rate helps nobody.
    """
    from scalerl_tpu.runtime import telemetry
    from scalerl_tpu.runtime.supervisor import exp_backoff

    last: Optional[Exception] = None
    for attempt in range(retries):
        try:
            sock = socket.create_connection((host, port), timeout=10.0)
            sock.settimeout(None)
            if attempt:
                # bring-up visibility: how many dials a connection cost is
                # the earliest signal of a flapping learner/NAT
                telemetry.get_registry().counter("transport.connect_retries").inc(
                    attempt
                )
                telemetry.record_event(
                    "connect_retried", host=host, port=port, attempts=attempt + 1
                )
            return SocketConnection(sock)
        except OSError as e:  # server not up yet
            last = e
            time.sleep(
                exp_backoff(attempt, delay, backoff_cap)
                if backoff_cap is not None
                else delay
            )
    telemetry.record_event(
        "connect_failed", host=host, port=port, attempts=retries
    )
    raise ConnectionError(f"could not connect to {host}:{port}") from last


def open_worker_pipes(
    n: int,
    target: Callable[..., None],
    args_fn: Callable[[int], Tuple],
    ctx: Optional[mp.context.BaseContext] = None,
) -> Tuple[List[PipeConnection], List[mp.Process]]:
    """Spawn ``n`` worker processes, each holding one end of a duplex pipe.

    Parity: ``open_multiprocessing_connections``
    (``scalerl/hpc/connection.py:179-204``).  ``args_fn(i)`` builds the
    worker's extra args; the worker ``target`` receives
    ``(pipe_connection, *args_fn(i))``.

    When no ``ctx`` is given and JAX is live in this process, workers
    start via spawn (``target``/args must then be picklable) — see
    ``utils.platform.safe_mp_context``.
    """
    if ctx is None:
        from scalerl_tpu.utils.platform import safe_mp_context

        ctx = mp.get_context(safe_mp_context(None))
    conns: List[PipeConnection] = []
    procs: List[mp.Process] = []
    for i in range(n):
        parent, child = ctx.Pipe(duplex=True)
        proc = ctx.Process(
            target=_pipe_worker_main,
            args=(target, child, args_fn(i)),
            daemon=True,
        )
        proc.start()
        child.close()
        conns.append(PipeConnection(parent))
        procs.append(proc)
    return conns, procs


def _pipe_worker_main(target, child_conn, extra_args) -> None:
    target(PipeConnection(child_conn), *extra_args)
