"""Prioritized SEQUENCE replay for R2D2: whole [T+1] chunks as units.

Where ``data/replay.py`` stores transitions, this buffer stores fixed-
length trajectory chunks — each with the recurrent core state the actor
ENTERED the chunk with (Kapturowski et al. 2019 "stored state") — and
holds one priority per sequence.  Everything is an HBM-resident pytree
with static shapes: inserts are batched dynamic-slice writes, sampling is
the same proportional prefix-sum machinery as transition PER
(``ops/pallas_per.py``), and priority updates are scatter writes.  The
reference has no sequence replay (its replay layer is transition-only,
``scalerl/data/replay_buffer.py``).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from flax import struct

from scalerl_tpu.ops.pallas_per import proportional_sample


@struct.dataclass
class SequenceReplayState:
    storage: Dict[str, jnp.ndarray]  # field -> [capacity, T1, ...]
    core: Tuple  # per-layer (c, h): [capacity, core_dim]
    priorities: jnp.ndarray  # [capacity] f32, 0 = empty slot
    pos: jnp.ndarray  # next write cursor
    size: jnp.ndarray  # filled count


def seq_init(
    field_shapes: Dict[str, Tuple[Tuple[int, ...], Any]],
    core_shapes: Tuple[Tuple[int, ...], ...],
    capacity: int,
) -> SequenceReplayState:
    """``field_shapes``: name -> (per-sequence shape incl. time axis, dtype);
    ``core_shapes``: per-LSTM-layer (core_dim,) shapes (c and h alike)."""
    storage = {
        name: jnp.zeros((capacity,) + tuple(shape), dtype)
        for name, (shape, dtype) in field_shapes.items()
    }
    core = tuple(
        (
            jnp.zeros((capacity,) + tuple(s), jnp.float32),
            jnp.zeros((capacity,) + tuple(s), jnp.float32),
        )
        for s in core_shapes
    )
    return SequenceReplayState(
        storage=storage,
        core=core,
        priorities=jnp.zeros(capacity, jnp.float32),
        pos=jnp.zeros((), jnp.int32),
        size=jnp.zeros((), jnp.int32),
    )


@partial(jax.jit, donate_argnums=(0,))
def seq_add(
    state: SequenceReplayState,
    batch: Dict[str, jnp.ndarray],  # field -> [B, T1, ...]
    core: Tuple,  # per-layer (c[B, dim], h[B, dim])
    priorities: jnp.ndarray,  # [B]
) -> SequenceReplayState:
    """Insert B sequences at the ring cursor (wrapping)."""
    capacity = state.priorities.shape[0]
    B = priorities.shape[0]
    idx = (state.pos + jnp.arange(B)) % capacity

    storage = {
        name: arr.at[idx].set(batch[name]) for name, arr in state.storage.items()
    }
    new_core = tuple(
        (c.at[idx].set(bc), h.at[idx].set(bh))
        for (c, h), (bc, bh) in zip(state.core, core)
    )
    return SequenceReplayState(
        storage=storage,
        core=new_core,
        priorities=state.priorities.at[idx].set(priorities),
        pos=(state.pos + B) % capacity,
        size=jnp.minimum(state.size + B, capacity),
    )


@partial(jax.jit, static_argnums=(2,), static_argnames=("method",))
def seq_sample(
    state: SequenceReplayState,
    key: jax.Array,
    batch_size: int,
    alpha: float = 0.6,
    beta: float = 0.4,
    method: str = "auto",
) -> Tuple[Dict[str, jnp.ndarray], Tuple, jnp.ndarray, jnp.ndarray]:
    """Proportional sample of ``batch_size`` sequences.

    Returns (fields [B, T1, ...], core (c,h)[B,...] per layer,
    indices [B], importance weights [B] normalized by their max —
    the PER convention, ``scalerl/data/replay_buffer.py:370-381``).

    ``method``: the ``ops/pallas_per`` search implementation.  Long-lived
    callers (the R2D2 trainers) resolve ``"auto"`` at construction via
    ``resolve_sample_method`` and pass the concrete method, so env-var /
    backend changes after the first trace cannot be silently ignored.
    """
    scaled = jnp.power(state.priorities, alpha)  # empty slots: 0^a = 0
    total = jnp.sum(scaled)
    u = jax.random.uniform(key, (batch_size,))
    # stratified targets over the live mass
    targets = (jnp.arange(batch_size) + u) / batch_size * total
    idx = proportional_sample(scaled, targets, method=method)

    probs = scaled[idx] / jnp.maximum(total, 1e-9)
    n = jnp.maximum(state.size.astype(jnp.float32), 1.0)
    weights = jnp.power(n * jnp.maximum(probs, 1e-9), -beta)
    weights = weights / jnp.maximum(jnp.max(weights), 1e-9)

    fields = {name: arr[idx] for name, arr in state.storage.items()}
    core = tuple((c[idx], h[idx]) for c, h in state.core)
    return fields, core, idx, weights


@partial(jax.jit, donate_argnums=(0,))
def seq_update_priorities(
    state: SequenceReplayState, idx: jnp.ndarray, priorities: jnp.ndarray
) -> SequenceReplayState:
    return state.replace(
        priorities=state.priorities.at[idx].set(jnp.maximum(priorities, 1e-6))
    )


def seq_export(state: SequenceReplayState) -> Dict[str, Any]:
    """The buffer's full occupancy as a host-numpy tree — storage fields,
    recurrent core state, priorities, and both cursors — for the
    preemption ledger (``genrl/ledger.py``).  Everything returned is
    codec-v2 encodable (numpy arrays, tuples, dicts) and round-trips
    bit-exact through :func:`seq_import`: a resumed learner samples the
    SAME distribution its predecessor would have."""
    host = jax.device_get(
        {
            "storage": dict(state.storage),
            "core": state.core,
            "priorities": state.priorities,
        }
    )
    # cursors ride as plain ints: codec-v2 widens 0-d arrays to shape (1,),
    # which would break the scalar contract on import
    host["pos"] = int(state.pos)
    host["size"] = int(state.size)
    return host


def seq_import(host: Dict[str, Any]) -> SequenceReplayState:
    """Inverse of :func:`seq_export`: rebuild the HBM-resident pytree from
    a restored ledger tree (one batched host->device upload per leaf)."""
    return SequenceReplayState(
        storage={k: jnp.asarray(v) for k, v in host["storage"].items()},
        core=tuple(
            (jnp.asarray(c), jnp.asarray(h)) for c, h in host["core"]
        ),
        priorities=jnp.asarray(host["priorities"]),
        pos=jnp.asarray(host["pos"], jnp.int32).reshape(()),
        size=jnp.asarray(host["size"], jnp.int32).reshape(()),
    )


def seq_update_priorities_keep_empty(
    state: SequenceReplayState, idx: jnp.ndarray, priorities: jnp.ndarray
) -> SequenceReplayState:
    """Priority write-back that cannot resurrect empty slots.

    ``priorities == 0`` marks a never-written slot (the ``seq_init``
    contract). Sharded sampling can draw such a slot before its ring block
    fills and zero-weights it so the loss ignores it — but a plain
    ``seq_update_priorities`` would then floor the slot's priority at 1e-6,
    pulling the all-zeros garbage sequence INTO the distribution for every
    later sample. Used by both the sharded replay class and the mesh-fused
    R2D2 iteration (not jitted here: callers embed it in their own jit/
    shard_map programs).
    """
    live = state.priorities[idx] > 0
    eff = jnp.where(live, jnp.maximum(priorities, 1e-6), 0.0)
    return state.replace(priorities=state.priorities.at[idx].set(eff))
