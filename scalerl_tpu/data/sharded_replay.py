"""Mesh-sharded prioritized replay: pod-scale Ape-X / R2D2 memory in HBM.

``BASELINE.md``'s Ape-X row is "replay sharded across TPU HBM — TPU pod
slice" (reference capability: ``scalerl/algorithms/apex/memory.py:11-138``
feeding DDP learner replicas).  The single-device buffers
(``data/prioritized.py`` / ``data/sequence_replay.py``) replicate their
state under pjit, so pod-scale capacity would overflow one chip's HBM.
Here the big planes shard over the mesh's ``dp``/``fsdp`` axes:

- **transitions** (Ape-X): the ENV-LANE axis shards — the actor batch is
  already lane-blocked, so inserts land on the shard that owns the lane;
- **sequences** (R2D2): the CAPACITY ring shards into ``S`` blocks.

Placement vs. semantics: inserts and priority write-backs run as ordinary
jitted global programs over sharded arrays — GSPMD lowers them to
shard-local masked scatters (indices are replicated scalars/vectors), so
the state VALUES are bit-identical to the unsharded buffers.  Only
*sampling* changes algorithmically (a global flat cumsum + searchsorted
would all-gather the whole priority plane): it runs under ``shard_map``,
each shard drawing ``B/S`` samples from its LOCAL ``p^alpha`` mass with
stratified targets, then normalizing GLOBALLY — priority mass and valid
counts by ``psum``, the importance-weight max by ``pmax``.

Sampling semantics (two-level stratified): the per-draw probability of
slot ``i`` on shard ``s`` is ``q_i = (1/S) * p_i / M_s``; importance
weights use exactly ``q_i``, so the PER estimator stays unbiased even when
shard masses ``M_s`` diverge, and as priorities mix (``M_s -> M/S``) the
distribution converges to the exact global ``p_i / M``.  This is the same
trade the reference's Ape-X makes with its per-actor buffers, with the
bias correction done exactly instead of ignored.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from scalerl_tpu.data.prioritized import (
    PrioritizedState,
    per_add,
    per_add_with_priorities,
    per_init,
    per_update_priorities,
)
from scalerl_tpu.data.replay import _logical_start, gather_transitions, transition_spec
from scalerl_tpu.data.sequence_replay import (
    SequenceReplayState,
    seq_add,
    seq_init,
    seq_update_priorities_keep_empty,
)
from scalerl_tpu.ops.pallas_per import proportional_sample


def replay_shard_axes(mesh) -> Tuple[str, ...]:
    """The mesh axes replay shards over: dp and fsdp (where present)."""
    return tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)


def _shard_count(mesh, axes: Tuple[str, ...]) -> int:
    n = 1
    for a in axes:
        n *= mesh.shape[a]
    return n


def _shard_index(axes: Tuple[str, ...], mesh) -> jnp.ndarray:
    """Linearized shard index inside shard_map (row-major over ``axes``)."""
    idx = jnp.zeros((), jnp.int32)
    for a in axes:
        idx = idx * mesh.shape[a] + jax.lax.axis_index(a)
    return idx


# ---------------------------------------------------------------------------
# transitions (Ape-X): env-lane axis sharded


class ShardedPrioritizedReplay:
    """Lane-sharded transition PER over a device mesh.

    API mirrors ``PrioritizedReplayBuffer`` (save_to_memory /
    add_with_priorities / sample / update_priorities), so ``ApexTrainer``
    swaps it in when a mesh is active.  ``num_envs`` must divide by the
    mesh's dp*fsdp extent; lanes are blocked contiguously per shard.
    """

    def __init__(
        self,
        obs_shape: Tuple[int, ...],
        capacity: int,
        mesh,
        num_envs: int,
        obs_dtype: jnp.dtype = jnp.float32,
        alpha: float = 0.6,
        n_step: int = 1,
        gamma: float = 0.99,
        extra_fields: Optional[Dict[str, Tuple[Tuple[int, ...], jnp.dtype]]] = None,
        action_shape: Tuple[int, ...] = (),
        action_dtype: jnp.dtype = jnp.int32,
        sample_method: str = "auto",
    ) -> None:
        from scalerl_tpu.ops.pallas_per import resolve_sample_method

        # "auto" resolves NOW (env var / backend at construction), not at
        # first trace of the cached sample program
        self.sample_method = resolve_sample_method(sample_method)
        self.mesh = mesh
        self.axes = replay_shard_axes(mesh)
        if not self.axes:
            raise ValueError(
                f"mesh {mesh.axis_names} has neither a 'dp' nor an 'fsdp' "
                "axis to shard replay lanes over"
            )
        self.n_shards = _shard_count(mesh, self.axes)
        if num_envs % self.n_shards != 0:
            raise ValueError(
                f"num_envs ({num_envs}) must divide by the mesh's dp*fsdp "
                f"extent ({self.n_shards}) to shard the lane axis"
            )
        self.spec = dict(transition_spec(
            obs_shape, obs_dtype, action_dtype=action_dtype,
            action_shape=action_shape, include_boundary=n_step > 1,
        ))
        if extra_fields:
            self.spec.update(extra_fields)
        self.capacity = capacity
        self.num_envs = num_envs
        self.alpha = alpha
        self.n_step = n_step
        self.gamma = gamma

        def state_spec(x):
            # [capacity, num_envs, ...] planes shard on the lane axis;
            # pos/size/max_priority scalars replicate
            if getattr(x, "ndim", 0) >= 2:
                return P(None, self.axes)
            return P()

        state = per_init(self.spec, capacity, num_envs)
        self._state_spec = jax.tree_util.tree_map(state_spec, state)
        self._state_sh = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), self._state_spec
        )
        self.state = jax.device_put(state, self._state_sh)

        lane_sh = NamedSharding(mesh, P(self.axes))

        def step_sh(x):
            return NamedSharding(mesh, P(self.axes, *([None] * (x.ndim - 1))))

        # add/update are ordinary global programs over sharded state: GSPMD
        # lowers the replicated-index scatters to shard-local writes, so
        # state values match the unsharded buffer exactly
        self._add = jax.jit(per_add, donate_argnums=0)
        self._add_prio = jax.jit(per_add_with_priorities, donate_argnums=0)
        self._update = jax.jit(per_update_priorities, donate_argnums=0)
        self._lane_sh = lane_sh
        self._step_sh = step_sh
        self._sample_cache: Dict[int, Any] = {}

    def __len__(self) -> int:
        return int(self.state.replay.size) * self.num_envs

    def _coerce_step(self, step: Dict[str, Any]) -> Dict[str, jnp.ndarray]:
        step = {k: jnp.asarray(v) for k, v in step.items()}
        if "boundary" in self.spec:
            step.setdefault("boundary", step["done"])
        else:
            step.pop("boundary", None)
        out = {}
        for k, v in step.items():
            want = (self.num_envs,) + tuple(self.spec[k][0])
            if v.shape != want:
                v = v.reshape(want)
            out[k] = jax.device_put(v.astype(self.spec[k][1]), self._step_sh(v))
        return out

    def save_to_memory(self, obs, next_obs, action, reward, done, boundary=None) -> None:
        step = {"obs": obs, "next_obs": next_obs, "action": action,
                "reward": reward, "done": done}
        if boundary is not None:
            step["boundary"] = boundary
        self.state = self._add(self.state, self._coerce_step(step))

    def add_with_priorities(self, step: Dict[str, Any], priorities) -> None:
        p = jax.device_put(
            jnp.maximum(jnp.asarray(priorities, jnp.float32), 1e-6), self._lane_sh
        )
        self.state = self._add_prio(self.state, self._coerce_step(step), p)

    def update_priorities(self, indices, priorities) -> None:
        self.state = self._update(
            self.state, jnp.asarray(indices), jnp.asarray(priorities, jnp.float32)
        )

    # -- sampling ------------------------------------------------------
    def _build_sample(self, batch_size: int):
        if batch_size % self.n_shards != 0:
            raise ValueError(
                f"batch_size ({batch_size}) must divide by the replay shard "
                f"count ({self.n_shards})"
            )
        b_local = batch_size // self.n_shards
        axes = self.axes
        mesh = self.mesh
        n_shards = self.n_shards
        num_envs = self.num_envs
        n_step, gamma, alpha = self.n_step, self.gamma, self.alpha
        method = self.sample_method  # resolved at construction, pinned here

        def local_sample(state: PrioritizedState, key, beta):
            # state leaves here are the LOCAL blocks: [capacity, envs/S, ...]
            shard = _shard_index(axes, mesh)
            key = jax.random.fold_in(key, shard)
            capacity, local_envs = state.priorities.shape
            start = _logical_start(state.replay, capacity)
            size = state.replay.size

            logical_prio = jnp.roll(state.priorities, -start, axis=0)
            valid = (jnp.arange(capacity) < jnp.maximum(size - n_step + 1, 1))[:, None]
            p = jnp.where(valid, logical_prio, 0.0) ** alpha
            p = jnp.where(valid, jnp.maximum(p, 1e-12), 0.0)
            flat_p = p.reshape(-1)
            m_local = jnp.sum(flat_p)

            u = jax.random.uniform(key, (b_local,))
            targets = (jnp.arange(b_local) + u) / b_local * m_local
            flat_logical = proportional_sample(flat_p, targets, method=method)

            # per-draw probability under the two-level scheme
            q = flat_p[flat_logical] / jnp.maximum(m_local, 1e-12) / n_shards
            n_valid_local = jnp.sum(valid) * local_envs
            n_valid = jax.lax.psum(n_valid_local, axes).astype(jnp.float32)
            weights = (jnp.maximum(n_valid, 1.0) * jnp.maximum(q, 1e-12)) ** (-beta)
            wmax = jax.lax.pmax(jnp.max(weights), axes)
            weights = weights / jnp.maximum(wmax, 1e-12)

            logical = flat_logical // local_envs
            env_local = flat_logical % local_envs
            batch = gather_transitions(state.replay, logical, env_local, n_step, gamma)
            # rebase the physical index from local to GLOBAL lane numbering
            row0 = batch["indices"] // local_envs
            env_l = batch["indices"] % local_envs
            batch["indices"] = row0 * num_envs + shard * local_envs + env_l
            batch["weights"] = weights
            return batch

        # out: every leaf is [b_local, ...] per shard -> global [B, ...];
        # specs mirror gather_transitions' return structure (standard fields
        # + n_steps/indices + pass-through extras, no boundary) + weights
        def field_spec(name: str) -> P:
            return P(axes, *([None] * len(self.spec[name][0])))

        out_specs = {
            "obs": field_spec("obs"),
            "next_obs": field_spec("next_obs"),
            "action": field_spec("action"),
            "reward": P(axes),
            "done": P(axes),
            "n_steps": P(axes),
            "indices": P(axes),
            "weights": P(axes),
        }
        standard = {"obs", "next_obs", "action", "reward", "done", "boundary"}
        for name in self.spec:
            if name not in standard:
                out_specs[name] = field_spec(name)

        fn = shard_map(
            local_sample,
            mesh=mesh,
            in_specs=(self._state_spec, P(), P()),
            out_specs=out_specs,
            check_rep=False,
        )
        return jax.jit(fn)

    def sample(self, batch_size: int, beta: float = 0.4, key: Optional[jax.Array] = None):
        if key is None:
            key = jax.random.PRNGKey(np.random.randint(0, 2**31 - 1))
        fn = self._sample_cache.get(batch_size)
        if fn is None:
            fn = self._sample_cache[batch_size] = self._build_sample(batch_size)
        return fn(self.state, key, jnp.float32(beta))


# ---------------------------------------------------------------------------
# sequences (R2D2): capacity ring sharded


def seq_sample_sharded_local(
    state: SequenceReplayState,
    key: jax.Array,
    b_local: int,
    *,
    axes: Tuple[str, ...],
    n_shards: int,
    local_capacity: int,
    alpha: float = 0.6,
    beta: float = 0.4,
    global_size: Optional[jnp.ndarray] = None,
    method: str = "auto",
):
    """Per-shard sequence sample; call INSIDE ``shard_map`` over ``axes``.

    ``state`` leaves are the local capacity blocks ``[capacity/S, ...]``
    (``pos``/``size`` replicated).  Returns ``(fields, core, idx, weights)``
    with ``idx`` rebased to GLOBAL slot numbering; weights are globally
    normalized (``psum`` mass semantics via exact per-draw ``q``, ``pmax``
    for the max-weight divisor).  Factored out so the fused device-R2D2
    iteration can embed it in its own shard_map (``trainer/r2d2_device.py``).

    ``global_size``: total live sequences across all shards for the IS
    weight's ``N``.  Default ``state.size`` — correct when the cursor walks
    the GLOBAL ring (``ShardedSequenceReplay``); pass ``psum(size, axes)``
    when each shard keeps an independent local ring (fused loop).

    ``method``: long-lived callers pass the concrete search method they
    resolved at construction (``resolve_sample_method``), so env-var /
    backend changes after the first trace are not silently ignored.
    """
    shard = jnp.zeros((), jnp.int32)
    for a in axes:
        shard = shard * jax.lax.psum(1, a) + jax.lax.axis_index(a)
    key = jax.random.fold_in(key, shard)

    scaled = jnp.power(state.priorities, alpha)  # empty slots: 0^a = 0
    m_local = jnp.sum(scaled)
    u = jax.random.uniform(key, (b_local,))
    targets = (jnp.arange(b_local) + u) / b_local * m_local
    idx = proportional_sample(scaled, targets, method=method)

    q = scaled[idx] / jnp.maximum(m_local, 1e-9) / n_shards
    size = state.size if global_size is None else global_size
    n = jnp.maximum(size.astype(jnp.float32), 1.0)
    weights = jnp.power(n * jnp.maximum(q, 1e-9), -beta)
    # a shard whose block the ring hasn't reached yet (or an empty slot at a
    # cumsum edge) has zero mass there: its draws are garbage rows. Zero
    # their IS weights — the weighted loss then ignores them — and keep them
    # out of the global max normalization, instead of letting the 1e-9 floor
    # win the pmax and crush every real sample's weight (review r4).
    weights = jnp.where(q > 0, weights, 0.0)
    wmax = jax.lax.pmax(jnp.max(weights), axes)
    weights = weights / jnp.maximum(wmax, 1e-9)

    fields = {name: arr[idx] for name, arr in state.storage.items()}
    core = tuple((c[idx], h[idx]) for c, h in state.core)
    return fields, core, shard * local_capacity + idx, weights


class ShardedSequenceReplay:
    """Capacity-sharded sequence PER over a device mesh (R2D2 at pod scale).

    Same surface as the ``seq_*`` functional API via methods: ``add`` /
    ``sample`` / ``update_priorities``.  The ring cursor walks the GLOBAL
    capacity, so inserts sweep shard blocks in turn (values identical to
    the unsharded ring); sampling draws ``B/S`` per shard.
    """

    def __init__(
        self,
        field_shapes: Dict[str, Tuple[Tuple[int, ...], Any]],
        core_shapes: Tuple[Tuple[int, ...], ...],
        capacity: int,
        mesh,
        alpha: float = 0.6,
        beta: float = 0.4,
        sample_method: str = "auto",
    ) -> None:
        from scalerl_tpu.ops.pallas_per import resolve_sample_method

        # construction-time resolution (see PrioritizedReplayBuffer)
        self.sample_method = resolve_sample_method(sample_method)
        self.mesh = mesh
        self.axes = replay_shard_axes(mesh)
        if not self.axes:
            raise ValueError(
                f"mesh {mesh.axis_names} has neither a 'dp' nor an 'fsdp' "
                "axis to shard sequence capacity over"
            )
        self.n_shards = _shard_count(mesh, self.axes)
        if capacity % self.n_shards != 0:
            raise ValueError(
                f"capacity ({capacity}) must divide by the mesh's dp*fsdp "
                f"extent ({self.n_shards}) to shard the ring"
            )
        self.capacity = capacity
        self.alpha = alpha
        self.beta = beta

        def state_spec(x):
            if getattr(x, "ndim", 0) >= 1:
                return P(self.axes, *([None] * (x.ndim - 1)))
            return P()

        state = seq_init(field_shapes, core_shapes, capacity)
        self._state_spec = jax.tree_util.tree_map(state_spec, state)
        self._state_sh = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), self._state_spec
        )
        self.state = jax.device_put(state, self._state_sh)
        # global programs over sharded state (see module docstring)
        self._add = jax.jit(seq_add, donate_argnums=0)
        # keep-empty write-back: zero-weight garbage draws from unreached
        # shard blocks must not resurrect empty slots into the distribution
        self._update = jax.jit(seq_update_priorities_keep_empty, donate_argnums=0)
        self._sample_cache: Dict[int, Any] = {}

    def __len__(self) -> int:
        return int(self.state.size)

    def add(self, batch: Dict[str, jnp.ndarray], core: Tuple, priorities) -> None:
        self.state = self._add(
            self.state, batch, core, jnp.asarray(priorities, jnp.float32)
        )

    def update_priorities(self, idx, priorities) -> None:
        self.state = self._update(
            self.state, jnp.asarray(idx), jnp.asarray(priorities, jnp.float32)
        )

    def _build_sample(self, batch_size: int):
        if batch_size % self.n_shards != 0:
            raise ValueError(
                f"batch_size ({batch_size}) must divide by the replay shard "
                f"count ({self.n_shards})"
            )
        b_local = batch_size // self.n_shards
        axes, n_shards = self.axes, self.n_shards
        local_capacity = self.capacity // self.n_shards
        alpha, beta = self.alpha, self.beta

        method = self.sample_method

        def local(state, key):
            return seq_sample_sharded_local(
                state, key, b_local,
                axes=axes, n_shards=n_shards, local_capacity=local_capacity,
                alpha=alpha, beta=beta, method=method,
            )

        # fields/core: [b_local, T1/dim, ...] -> sharded dim 0; idx/weights 1-D
        fields_spec = {
            name: P(axes, *([None] * (arr.ndim - 1)))
            for name, arr in self.state.storage.items()
        }
        core_spec = tuple((P(axes, None), P(axes, None)) for _ in self.state.core)
        out_specs = (fields_spec, core_spec, P(axes), P(axes))

        fn = shard_map(
            local,
            mesh=self.mesh,
            in_specs=(self._state_spec, P()),
            out_specs=out_specs,
            check_rep=False,
        )
        return jax.jit(fn)

    def sample(self, batch_size: int, key: Optional[jax.Array] = None):
        if key is None:
            key = jax.random.PRNGKey(np.random.randint(0, 2**31 - 1))
        fn = self._sample_cache.get(batch_size)
        if fn is None:
            fn = self._sample_cache[batch_size] = self._build_sample(batch_size)
        return fn(self.state, key)
