"""Prioritized experience replay with device-side proportional sampling.

Capability parity with the reference's ``PrioritizedReplayBuffer`` +
segment trees (``scalerl/data/replay_buffer.py:276-381``,
``scalerl/data/segment_tree.py``) and the Ape-X duplicate
(``scalerl/algorithms/apex/memory.py:11-138``), re-designed for XLA:

Segment trees are pointer-chasing and XLA-hostile (SURVEY.md §7).  Instead,
stratified proportional sampling is a masked ``cumsum`` over the priority
plane followed by a vectorized ``searchsorted`` — O(capacity) streaming work
that XLA vectorizes and fuses, instead of O(log n) *sequential* descents per
sample.  Priority updates are pure scatters, so the learner can update
priorities inside its jitted train step with no host round-trip.

Priorities are stored raw; the ``alpha`` exponent is applied at sample time
(equivalent to the reference storing ``p**alpha``), and importance weights
use the standard ``(N * P)^-beta / max`` normalization
(``replay_buffer.py:370-381``).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from scalerl_tpu.data.replay import (
    ReplayState,
    Spec,
    _logical_start,
    gather_transitions,
    replay_add,
    replay_init,
    transition_spec,
)


@struct.dataclass
class PrioritizedState:
    replay: ReplayState
    priorities: jnp.ndarray  # [capacity, num_envs] raw (un-exponentiated)
    max_priority: jnp.ndarray  # float32 scalar


def per_init(spec: Spec, capacity: int, num_envs: int) -> PrioritizedState:
    return PrioritizedState(
        replay=replay_init(spec, capacity, num_envs),
        priorities=jnp.zeros((capacity, num_envs), jnp.float32),
        max_priority=jnp.ones((), jnp.float32),
    )


def per_add(state: PrioritizedState, step) -> PrioritizedState:
    """Add one vector step; new transitions get the current max priority."""
    pos = state.replay.pos
    replay = replay_add(state.replay, step)
    priorities = state.priorities.at[pos].set(state.max_priority)
    return state.replace(replay=replay, priorities=priorities)


def per_add_with_priorities(
    state: PrioritizedState,
    step,
    priorities: jnp.ndarray,  # [num_envs] raw priorities for this row
) -> PrioritizedState:
    """Add one vector step with caller-supplied initial priorities.

    The Ape-X protocol: *actors* compute initial TD-error priorities for
    their own transitions (``apex/worker.py:59-79``), so new rows enter the
    distribution at their true priority instead of max.
    """
    pos = state.replay.pos
    replay = replay_add(state.replay, step)
    priorities = jnp.maximum(priorities.astype(jnp.float32), 1e-6)
    new_prio = state.priorities.at[pos].set(priorities)
    new_max = jnp.maximum(state.max_priority, jnp.max(priorities))
    return state.replace(replay=replay, priorities=new_prio, max_priority=new_max)


def per_sample(
    state: PrioritizedState,
    key: jax.Array,
    batch_size: int,
    alpha: jnp.ndarray,
    beta: jnp.ndarray,
    n_step: int = 1,
    gamma: float = 0.99,
    method: str = "auto",
) -> Dict[str, jnp.ndarray]:
    """Stratified proportional sample; returns transitions + ``weights``.

    The distribution is ``p_i^alpha`` over valid logical rows (those with a
    full n-step window).  ``method`` picks the search implementation
    (``ops/pallas_per.py``): ``auto`` (default) resolves to the Pallas
    kernel on TPU and the hierarchical XLA search elsewhere; ``cumsum`` is
    SURVEY.md §7's plan A, ``hierarchical`` the two-level XLA search that
    avoids materializing the full-capacity cumsum, ``pallas`` the TPU
    kernel with scalar-prefetched block DMA.
    """
    from scalerl_tpu.ops.pallas_per import proportional_sample

    capacity, num_envs = state.priorities.shape
    start = _logical_start(state.replay, capacity)
    size = state.replay.size

    # Priorities in logical order: roll so row 0 = oldest.
    logical_prio = jnp.roll(state.priorities, -start, axis=0)
    # window at L reads rows L..L+n_step-1 -> L <= size - n_step inclusive
    valid = (jnp.arange(capacity) < jnp.maximum(size - n_step + 1, 1))[:, None]
    p = jnp.where(valid, logical_prio, 0.0) ** alpha
    p = jnp.where(valid, jnp.maximum(p, 1e-12), 0.0)
    flat_p = p.reshape(-1)
    total = jnp.sum(flat_p)

    # Stratified uniforms: one per bucket.
    u = jax.random.uniform(key, (batch_size,))
    targets = (jnp.arange(batch_size) + u) / batch_size * total
    flat_logical = proportional_sample(flat_p, targets, method=method)

    probs = flat_p[flat_logical] / jnp.maximum(total, 1e-12)
    n_valid = jnp.maximum(jnp.sum(valid) * num_envs, 1).astype(jnp.float32)
    weights = (n_valid * jnp.maximum(probs, 1e-12)) ** (-beta)
    weights = weights / jnp.maximum(jnp.max(weights), 1e-12)

    logical = flat_logical // num_envs
    envs = flat_logical % num_envs
    batch = gather_transitions(state.replay, logical, envs, n_step, gamma)
    batch["weights"] = weights
    # batch["indices"] (from gather_transitions) is the flat PHYSICAL slot:
    # stable across interleaved inserts, so a priority update that races
    # adds still writes the rows it sampled (a stale write to an
    # overwritten row is benign — the OpenAI-baselines contract)
    return batch


def per_update_priorities(
    state: PrioritizedState,
    flat_physical: jnp.ndarray,  # [B] as returned in batch["indices"]
    priorities: jnp.ndarray,  # [B] new raw priorities (e.g. |td| + eps)
    method: str = "xla",
) -> PrioritizedState:
    """Scatter new priorities at the sampled PHYSICAL slots.

    ``batch["indices"]`` is physical (see ``per_sample``), so this stays
    correct even when inserts landed between sample and update — the
    failure mode a logical-index contract would have had.

    ``method="pallas"`` routes the scatter through the fused in-place
    kernel (``ops/pallas_per.update_priorities_blocks``): one block DMA
    per updated slot instead of a full-plane XLA scatter pass; selected by
    ``RLArguments.use_pallas`` at buffer construction.
    """
    capacity, num_envs = state.priorities.shape
    priorities = jnp.maximum(priorities, 1e-6)
    if method == "pallas":
        from scalerl_tpu.ops.pallas_per import update_priorities_blocks

        # the priority plane is C-order [capacity, num_envs], so the flat
        # physical index addresses its ravel directly
        new_flat, _ = update_priorities_blocks(
            state.priorities.reshape(-1), flat_physical, priorities,
            method="pallas",
        )
        new_prio = new_flat.reshape(capacity, num_envs)
    else:
        rows = flat_physical // num_envs
        envs = flat_physical % num_envs
        new_prio = state.priorities.at[rows, envs].set(priorities)
    new_max = jnp.maximum(state.max_priority, jnp.max(priorities))
    return state.replace(priorities=new_prio, max_priority=new_max)


class PrioritizedReplayBuffer:
    """Host-side wrapper mirroring the reference PER API
    (``sample(batch_size, beta)`` + ``update_priorities``,
    ``replay_buffer.py:319-351``)."""

    def __init__(
        self,
        obs_shape: Tuple[int, ...],
        capacity: int,
        num_envs: int = 1,
        obs_dtype: jnp.dtype = jnp.float32,
        alpha: float = 0.6,
        n_step: int = 1,
        gamma: float = 0.99,
        extra_fields: Optional[Dict[str, Tuple[Tuple[int, ...], jnp.dtype]]] = None,
        sample_method: str = "auto",
        update_method: str = "auto",
        action_shape: Tuple[int, ...] = (),
        action_dtype: jnp.dtype = jnp.int32,
    ) -> None:
        self.spec = dict(transition_spec(
            obs_shape, obs_dtype, action_dtype=action_dtype,
            action_shape=action_shape, include_boundary=n_step > 1,
        ))
        if extra_fields:
            self.spec.update(extra_fields)
        self.capacity = capacity
        self.num_envs = num_envs
        self.alpha = alpha
        self.n_step = n_step
        self.gamma = gamma
        # resolve "auto" NOW (env var / backend at construction), not at
        # first trace — a SCALERL_PER_METHOD change after tracing would
        # otherwise be silently ignored by the cached program
        from scalerl_tpu.ops.pallas_per import (
            resolve_sample_method,
            resolve_update_method,
        )

        self.sample_method = resolve_sample_method(sample_method)
        self.update_method = resolve_update_method(update_method)
        self.state = per_init(self.spec, capacity, num_envs)
        self._add = jax.jit(per_add, donate_argnums=0)
        self._add_prio = jax.jit(per_add_with_priorities, donate_argnums=0)
        # alpha/beta are *traced* args: beta follows a per-step schedule and
        # making it static would recompile the sampler on every train step
        self._sample = jax.jit(
            per_sample, static_argnames=("batch_size", "n_step", "gamma", "method")
        )
        self._update = jax.jit(
            per_update_priorities, donate_argnums=0, static_argnames=("method",)
        )

    def __len__(self) -> int:
        return int(self.state.replay.size) * self.num_envs

    def _coerce_step(self, step: Dict[str, jnp.ndarray]) -> Dict[str, jnp.ndarray]:
        step = {k: jnp.asarray(v) for k, v in step.items()}
        if "boundary" in self.spec:
            step.setdefault("boundary", step["done"])
        else:
            step.pop("boundary", None)  # inert at n_step=1; spec has no plane
        for k, v in step.items():
            want = (self.num_envs,) + tuple(self.spec[k][0])
            if v.shape != want:
                step[k] = v.reshape(want)
        return step

    def save_to_memory(self, obs, next_obs, action, reward, done, boundary=None) -> None:
        step = {"obs": obs, "next_obs": next_obs, "action": action, "reward": reward, "done": done}
        if boundary is not None:
            step["boundary"] = boundary
        self.state = self._add(self.state, self._coerce_step(step))

    def add_with_priorities(self, step: Dict[str, jnp.ndarray], priorities) -> None:
        """Add one vector step (any spec fields) with actor-computed
        priorities (the Ape-X insert path)."""
        self.state = self._add_prio(
            self.state, self._coerce_step(step), jnp.asarray(priorities, jnp.float32)
        )

    def sample(self, batch_size: int, beta: float = 0.4, key: Optional[jax.Array] = None):
        if key is None:
            key = jax.random.PRNGKey(np.random.randint(0, 2**31 - 1))
        return self._sample(
            self.state,
            key,
            batch_size=batch_size,
            alpha=jnp.float32(self.alpha),
            beta=jnp.float32(beta),
            n_step=self.n_step,
            gamma=self.gamma,
            method=self.sample_method,
        )

    def update_priorities(self, indices, priorities) -> None:
        self.state = self._update(
            self.state, jnp.asarray(indices), jnp.asarray(priorities, jnp.float32),
            method=self.update_method,
        )
