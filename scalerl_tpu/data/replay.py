"""Uniform + n-step experience replay as a static-shape HBM ring buffer.

Capability parity with the reference's ``ReplayBuffer`` /
``MultiStepReplayBuffer`` (``scalerl/data/replay_buffer.py:10-273``),
re-designed for XLA:

- Storage is a pytree of ``[capacity, num_envs, ...]`` arrays living in HBM
  (the reference keeps a Python ``deque`` of numpy tuples on the host and
  pays a host->device copy per learner batch).
- ``add`` writes one vector-env step with modular indexing
  (``lax.rem``-style ring semantics); ``sample`` gathers on device.
- The n-step fold that ``MultiStepReplayBuffer._get_n_step_info``
  (``replay_buffer.py:230-273``) performs incrementally with per-env deques
  happens at *sample time* as a static unrolled fold over the gathered
  ``[B, n]`` window — no separate accumulator state, no host math.

Everything is a pure function over an explicit ``ReplayState`` so it can sit
inside jit/pjit; the ``ReplayBuffer`` class is a thin host-side convenience
wrapper holding the state and jitted methods.
"""

from __future__ import annotations

from typing import Dict, Mapping, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

# name -> (per-env trailing shape, dtype)
Spec = Mapping[str, Tuple[Tuple[int, ...], jnp.dtype]]


def transition_spec(
    obs_shape: Tuple[int, ...],
    obs_dtype: jnp.dtype = jnp.float32,
    action_dtype: jnp.dtype = jnp.int32,
    action_shape: Tuple[int, ...] = (),
    include_boundary: bool = False,
) -> Dict[str, Tuple[Tuple[int, ...], jnp.dtype]]:
    """The standard (obs, next_obs, action, reward, done) transition layout.

    ``done`` is the bootstrap mask: TERMINATIONS only (a truncated episode
    still bootstraps from its last next_obs).

    ``include_boundary`` adds an episode-boundary plane (term | trunc) that
    stops the n-step reward fold so a window never folds rewards across a
    TimeLimit reset (advisor r3: truncation-ended envs like Pendulum would
    otherwise leak returns across episodes at n_steps > 1). Buffers enable
    it iff n_step > 1 — at n_step = 1 the single-row window makes boundary
    information inert, so storing it would duplicate ``done``. Writers that
    don't supply it get boundary = done (exact for termination-only envs).
    """
    spec = {
        "obs": (tuple(obs_shape), obs_dtype),
        "next_obs": (tuple(obs_shape), obs_dtype),
        "action": (tuple(action_shape), action_dtype),
        "reward": ((), jnp.float32),
        "done": ((), jnp.bool_),
    }
    if include_boundary:
        spec["boundary"] = ((), jnp.bool_)
    return spec


@struct.dataclass
class ReplayState:
    storage: Dict[str, jnp.ndarray]  # each [capacity, num_envs, ...]
    pos: jnp.ndarray  # int32 scalar: next write row
    size: jnp.ndarray  # int32 scalar: number of valid rows


def replay_init(spec: Spec, capacity: int, num_envs: int) -> ReplayState:
    storage = {
        name: jnp.zeros((capacity, num_envs) + tuple(shape), dtype)
        for name, (shape, dtype) in spec.items()
    }
    return ReplayState(
        storage=storage,
        pos=jnp.zeros((), jnp.int32),
        size=jnp.zeros((), jnp.int32),
    )


def replay_add(state: ReplayState, step: Mapping[str, jnp.ndarray]) -> ReplayState:
    """Write one vector step (each field ``[num_envs, ...]``) at the head."""
    capacity = next(iter(state.storage.values())).shape[0]
    storage = {
        name: arr.at[state.pos].set(step[name].astype(arr.dtype))
        for name, arr in state.storage.items()
    }
    return ReplayState(
        storage=storage,
        pos=(state.pos + 1) % capacity,
        size=jnp.minimum(state.size + 1, capacity),
    )


def replay_add_chunk(state: ReplayState, chunk: Mapping[str, jnp.ndarray]) -> ReplayState:
    """Write a ``[T, num_envs, ...]`` chunk via a scan of single-step adds."""

    def body(s, step):
        return replay_add(s, step), None

    state, _ = jax.lax.scan(body, state, dict(chunk))
    return state


def _logical_start(state: ReplayState, capacity: int) -> jnp.ndarray:
    """Physical row of the logically-oldest entry."""
    return jnp.where(state.size == capacity, state.pos, 0)


def _gather_window(
    arr: jnp.ndarray, rows: jnp.ndarray, envs: jnp.ndarray
) -> jnp.ndarray:
    """arr[rows, envs] for ``[B]`` (or ``[B, n]``) row/env index arrays."""
    return arr[rows, envs]


def n_step_fold(
    rewards: jnp.ndarray,  # [B, n]
    dones: jnp.ndarray,  # [B, n] bool: terminations (bootstrap mask)
    gamma: float,
    boundaries: jnp.ndarray | None = None,  # [B, n] bool: term | trunc
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Fold an n-step window into (reward, done, last_index).

    The reward at the first episode boundary is included; steps after it are
    masked (exactly ``MultiStepReplayBuffer._get_n_step_info``,
    ``replay_buffer.py:230-273``).  ``last_index`` is the offset whose
    ``next_obs`` bootstraps the return (first boundary, else n-1).

    ``boundaries`` (term | trunc) bounds the fold window; ``dones``
    (terminations only) decides whether the realized window's end kills the
    bootstrap. With ``boundaries=None`` the two coincide — correct when every
    episode ends by termination.
    """
    n = rewards.shape[1]
    if boundaries is None:
        boundaries = dones
    else:
        # a termination is always an episode boundary; OR-ing here makes the
        # boundary ⊇ done invariant unbreakable by writers that store only
        # the truncation flag
        boundaries = boundaries | dones
    boundsf = boundaries.astype(rewards.dtype)
    # alive[:, k] = survived steps 0..k-1
    alive = jnp.cumprod(1.0 - boundsf, axis=1)
    alive = jnp.concatenate([jnp.ones_like(alive[:, :1]), alive[:, :-1]], axis=1)
    gammas = gamma ** jnp.arange(n, dtype=rewards.dtype)
    reward = jnp.sum(rewards * alive * gammas[None, :], axis=1)
    any_bound = jnp.any(boundaries, axis=1)
    first_bound = jnp.argmax(boundaries, axis=1)
    last_index = jnp.where(any_bound, first_bound, n - 1)
    # termination iff the realized window ends on a terminal row (a window
    # cut by truncation keeps its bootstrap)
    done = jnp.take_along_axis(dones, last_index[:, None], axis=1)[:, 0] & any_bound
    return reward, done, last_index


def gather_transitions(
    state: ReplayState,
    logical: jnp.ndarray,  # [B] logical row indices (0 = oldest)
    envs: jnp.ndarray,  # [B] env column indices
    n_step: int = 1,
    gamma: float = 0.99,
) -> Dict[str, jnp.ndarray]:
    """Gather (possibly n-step) transitions at given logical (row, env) pairs."""
    capacity, num_envs = next(iter(state.storage.values())).shape[:2]
    start = _logical_start(state, capacity)
    offs = jnp.arange(n_step)
    rows = (start + logical[:, None] + offs[None, :]) % capacity  # [B, n]
    rewards = _gather_window(state.storage["reward"], rows, envs[:, None])
    dones = _gather_window(state.storage["done"], rows, envs[:, None])
    bounds = (
        _gather_window(state.storage["boundary"], rows, envs[:, None])
        if "boundary" in state.storage
        else None
    )
    reward_n, done_n, last_idx = n_step_fold(rewards, dones, gamma, bounds)

    row0 = rows[:, 0]
    row_last = jnp.take_along_axis(rows, last_idx[:, None], axis=1)[:, 0]
    batch = {
        "obs": state.storage["obs"][row0, envs],
        "action": state.storage["action"][row0, envs],
        "reward": reward_n,
        "next_obs": state.storage["next_obs"][row_last, envs],
        "done": done_n,
        "n_steps": (last_idx + 1).astype(jnp.int32),
        # flat PHYSICAL index (row-major over [row, env]): physical rows
        # don't shift when later adds advance the logical start, so the
        # index stays addressable across interleaved inserts (the PER
        # priority-update contract, data/prioritized.py)
        "indices": row0 * num_envs + envs,
    }
    # Extra storage fields (beyond the standard five) pass through, gathered
    # at the window head; a stored field may override a computed key — e.g.
    # Ape-X actors store pre-folded transitions whose realized ``n_steps``
    # must survive sampling (the buffer then runs with n_step=1).
    standard = {"obs", "next_obs", "action", "reward", "done", "boundary"}
    for name, arr in state.storage.items():
        if name not in standard:
            batch[name] = arr[row0, envs]
    return batch


def replay_sample(
    state: ReplayState,
    key: jax.Array,
    batch_size: int,
    n_step: int = 1,
    gamma: float = 0.99,
) -> Dict[str, jnp.ndarray]:
    """Uniformly sample ``batch_size`` (possibly n-step) transitions on device.

    Returns fields obs/action/reward/next_obs/done (+``indices``: flat
    PHYSICAL ``row0 * num_envs + env`` slots of the window head, the
    contract ``gather_transitions`` documents and ``data/prioritized.py``
    keys its priority updates on).
    """
    num_envs = next(iter(state.storage.values())).shape[1]
    # valid logical rows leave room for the n-step window: a window starting
    # at L reads rows L..L+n_step-1, so L <= size - n_step (inclusive).
    # Callers must warm up past n_step rows before sampling.
    max_l = jnp.maximum(state.size - n_step + 1, 1)
    k1, k2 = jax.random.split(key)
    logical = jax.random.randint(k1, (batch_size,), 0, max_l)
    envs = jax.random.randint(k2, (batch_size,), 0, num_envs)
    return gather_transitions(state, logical, envs, n_step, gamma)


class ReplayBuffer:
    """Host-side convenience wrapper mirroring the reference's buffer API
    (``save_to_memory`` / ``sample``, ``replay_buffer.py:77-129``)."""

    def __init__(
        self,
        obs_shape: Tuple[int, ...],
        capacity: int,
        num_envs: int = 1,
        obs_dtype: jnp.dtype = jnp.float32,
        n_step: int = 1,
        gamma: float = 0.99,
        device: Optional[jax.Device] = None,
        action_shape: Tuple[int, ...] = (),
        action_dtype: jnp.dtype = jnp.int32,
    ) -> None:
        self.spec = transition_spec(
            obs_shape, obs_dtype, action_dtype=action_dtype,
            action_shape=action_shape, include_boundary=n_step > 1,
        )
        self.capacity = capacity
        self.num_envs = num_envs
        self.n_step = n_step
        self.gamma = gamma
        self.state = replay_init(self.spec, capacity, num_envs)
        if device is not None:
            self.state = jax.device_put(self.state, device)
        self._add = jax.jit(replay_add, donate_argnums=0)
        self._add_chunk = jax.jit(replay_add_chunk, donate_argnums=0)
        self._sample = jax.jit(
            replay_sample, static_argnames=("batch_size", "n_step", "gamma")
        )

    def __len__(self) -> int:
        return int(self.state.size) * self.num_envs

    @property
    def num_transitions(self) -> int:
        return len(self)

    def save_to_memory(self, obs, next_obs, action, reward, done, boundary=None) -> None:
        """Add one vector step (accepts numpy or jax arrays; [num_envs, ...]).

        ``boundary`` is the episode-boundary flag (term | trunc) bounding the
        n-step fold; defaults to ``done`` (exact for termination-only envs).
        """
        step = {
            "obs": jnp.atleast_1d(jnp.asarray(obs)),
            "next_obs": jnp.atleast_1d(jnp.asarray(next_obs)),
            "action": jnp.atleast_1d(jnp.asarray(action)),
            "reward": jnp.atleast_1d(jnp.asarray(reward)),
            "done": jnp.atleast_1d(jnp.asarray(done)),
        }
        if "boundary" in self.spec:
            step["boundary"] = jnp.atleast_1d(
                jnp.asarray(done if boundary is None else boundary)
            )
        # allow single-env calls without the env axis
        for k, v in step.items():
            want = (self.num_envs,) + tuple(self.spec[k][0])
            if v.shape != want:
                step[k] = v.reshape(want)
        self.state = self._add(self.state, step)

    def save_chunk(self, **chunk) -> None:
        """Add a ``[T, ...]`` transition chunk in one device call.

        Callers feeding single-transition streams (e.g. fleet episode
        uploads) should batch into *fixed-size* chunks so this compiles
        once; varying T recompiles per length.
        """
        step = {k: jnp.asarray(v) for k, v in chunk.items()}
        if "boundary" in self.spec:
            step.setdefault("boundary", step["done"])
        else:
            step.pop("boundary", None)  # inert at n_step=1; spec has no plane
        T = next(iter(step.values())).shape[0]
        for k, v in step.items():
            want = (T, self.num_envs) + tuple(self.spec[k][0])
            if v.shape != want:
                step[k] = v.reshape(want)
        self.state = self._add_chunk(self.state, step)

    def sample(self, batch_size: int, key: Optional[jax.Array] = None) -> Dict[str, jnp.ndarray]:
        if key is None:
            key = jax.random.PRNGKey(np.random.randint(0, 2**31 - 1))
        return self._sample(
            self.state, key, batch_size=batch_size, n_step=self.n_step, gamma=self.gamma
        )
