"""One ``sample()`` facade over the replay variants.

Parity target: ``Sampler`` (``scalerl/data/sampler.py:10-72``), which selects
standard / PER / n-step / distributed-DataLoader sampling at construction.
The TPU equivalent of the "distributed DataLoader" path (sharded sampling
feeding DDP ranks, ``data/replay_data.py:8-26``) is per-host independent
sampling feeding a pjit'd learner — each host samples its local buffer and
the mesh shards the batch axis — so it needs no special case here beyond
each host constructing its own Sampler.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from scalerl_tpu.data.prioritized import PrioritizedReplayBuffer
from scalerl_tpu.data.replay import ReplayBuffer


class Sampler:
    def __init__(
        self,
        obs_shape: Tuple[int, ...],
        capacity: int,
        num_envs: int = 1,
        obs_dtype: jnp.dtype = jnp.float32,
        use_per: bool = False,
        per_alpha: float = 0.6,
        n_step: int = 1,
        gamma: float = 0.99,
        action_shape=(),
        action_dtype: jnp.dtype = jnp.int32,
        use_pallas: bool = False,
    ) -> None:
        self.use_per = use_per
        self.n_step = n_step
        if use_per:
            # use_pallas (RLArguments.use_pallas): pin both PER halves to
            # the Pallas kernels (interpreter mode off-TPU) instead of the
            # backend-resolved "auto"
            self.buffer = PrioritizedReplayBuffer(
                obs_shape,
                capacity,
                num_envs=num_envs,
                obs_dtype=obs_dtype,
                alpha=per_alpha,
                n_step=n_step,
                gamma=gamma,
                action_shape=tuple(action_shape),
                action_dtype=action_dtype,
                sample_method="pallas" if use_pallas else "auto",
                update_method="pallas" if use_pallas else "auto",
            )
        else:
            self.buffer = ReplayBuffer(
                obs_shape,
                capacity,
                num_envs=num_envs,
                obs_dtype=obs_dtype,
                n_step=n_step,
                gamma=gamma,
                action_shape=tuple(action_shape),
                action_dtype=action_dtype,
            )

    def __len__(self) -> int:
        return len(self.buffer)

    def add(self, obs, next_obs, action, reward, done, boundary=None) -> None:
        self.buffer.save_to_memory(obs, next_obs, action, reward, done, boundary=boundary)

    def sample(
        self,
        batch_size: int,
        beta: float = 0.4,
        key: Optional[jax.Array] = None,
    ) -> Dict[str, jnp.ndarray]:
        if self.use_per:
            return self.buffer.sample(batch_size, beta=beta, key=key)
        return self.buffer.sample(batch_size, key=key)

    def update_priorities(self, indices, priorities) -> None:
        if self.use_per:
            self.buffer.update_priorities(indices, priorities)
