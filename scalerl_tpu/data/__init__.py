from scalerl_tpu.data.replay import (  # noqa: F401
    ReplayBuffer,
    ReplayState,
    replay_add,
    replay_init,
    replay_sample,
)
from scalerl_tpu.data.prioritized import (  # noqa: F401
    PrioritizedReplayBuffer,
    PrioritizedState,
    per_add,
    per_init,
    per_sample,
    per_update_priorities,
)
from scalerl_tpu.data.sampler import Sampler  # noqa: F401
from scalerl_tpu.data.trajectory import Trajectory, TrajectorySpec  # noqa: F401
