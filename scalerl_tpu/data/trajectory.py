"""The universal time-major trajectory format: ``[T+1, B, ...]``.

SURVEY.md §7 adopts the reference IMPALA buffer layout
(``impala_atari.py:122-151``: per-buffer ``{obs, reward, done, action,
logits, baseline}`` tensors of length T+1, plus an initial RNN-state pool at
``:108-120``) as the single trajectory format for every actor-learner
algorithm, replacing the reference's variable-length episode lists
(``parallel_dqn.py:233-255``) which cannot have static shapes.

``Trajectory`` is a pytree (flax.struct), so a whole rollout chunk moves
host<->device as one transfer and threads through jit/pjit/scan unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct


@struct.dataclass
class Trajectory:
    """One rollout chunk, time-major ``[T+1, B, ...]``.

    Row convention (matches the reference's env_output layout, where the
    stored action/reward are *model inputs* at each row,
    ``impala_atari.py:186-205`` + ``utils/atari_model.py`` last-action feed):

    - ``obs[t]``: observation at step t.
    - ``action[t]``: the action that *led to* ``obs[t]`` (last-action
      semantics; ``action[0]`` carries in from the previous chunk).  The
      action *taken at* ``obs[t]`` is therefore ``action[t+1]``.
    - ``reward[t]`` / ``done[t]``: consequences of ``action[t]`` (i.e. of the
      step into ``obs[t]``); both are model inputs at row t.
    - ``logits[t]``: behavior-policy logits at ``obs[t]`` (V-trace input).
    - ``core_state``: recurrent state entering row 0 (empty for FF models).

    So the T valid transitions are
    ``(obs[t], action[t+1]) -> reward[t+1], done[t+1], obs[t+1]``.
    """

    obs: jnp.ndarray
    action: jnp.ndarray
    reward: jnp.ndarray
    done: jnp.ndarray
    logits: jnp.ndarray
    core_state: Any = ()

    @property
    def unroll_length(self) -> int:
        return self.obs.shape[0] - 1

    @property
    def batch_size(self) -> int:
        return self.obs.shape[1]


@dataclass(frozen=True)
class TrajectorySpec:
    """Static description of a trajectory chunk; builds zero pytrees and
    host staging buffers."""

    unroll_length: int  # T
    batch_size: int  # B
    obs_shape: Tuple[int, ...]
    num_actions: int
    obs_dtype: Any = jnp.uint8
    core_state_shapes: Tuple[Tuple[int, ...], ...] = ()  # per-leaf [B,...] shapes

    def zeros(self) -> Trajectory:
        T1 = self.unroll_length + 1
        B = self.batch_size
        return Trajectory(
            obs=jnp.zeros((T1, B) + tuple(self.obs_shape), self.obs_dtype),
            action=jnp.zeros((T1, B), jnp.int32),
            reward=jnp.zeros((T1, B), jnp.float32),
            done=jnp.ones((T1, B), jnp.bool_),
            logits=jnp.zeros((T1, B, self.num_actions), jnp.float32),
            core_state=tuple(
                (jnp.zeros(s, jnp.float32), jnp.zeros(s, jnp.float32))
                for s in self.core_state_shapes
            ),
        )

    def host_zeros(self) -> Dict[str, np.ndarray]:
        """Numpy staging buffers (one rollout slot) for the host actor plane.

        Recurrent core-state leaves are flat ``core_{i}_{c|h}`` keys with a
        leading batch axis (they describe row 0 only, so no time axis);
        ``RolloutQueue.get_batch`` concatenates them on axis 0 while the
        time-major fields concatenate on axis 1.
        """
        T1 = self.unroll_length + 1
        B = self.batch_size
        out = {
            "obs": np.zeros((T1, B) + tuple(self.obs_shape), np.dtype(jnp.dtype(self.obs_dtype).name)),
            "action": np.zeros((T1, B), np.int32),
            "reward": np.zeros((T1, B), np.float32),
            "done": np.ones((T1, B), bool),
            "logits": np.zeros((T1, B, self.num_actions), np.float32),
        }
        for i, s in enumerate(self.core_state_shapes):
            out[f"core_{i}_c"] = np.zeros(s, np.float32)
            out[f"core_{i}_h"] = np.zeros(s, np.float32)
        return out


def batch_to_trajectory(batch: Dict[str, np.ndarray]) -> Trajectory:
    """Assemble a host batch dict (RolloutQueue output) into a Trajectory."""
    core = []
    i = 0
    while f"core_{i}_c" in batch:
        core.append((jnp.asarray(batch[f"core_{i}_c"]), jnp.asarray(batch[f"core_{i}_h"])))
        i += 1
    return Trajectory(
        obs=jnp.asarray(batch["obs"]),
        action=jnp.asarray(batch["action"]),
        reward=jnp.asarray(batch["reward"]),
        done=jnp.asarray(batch["done"]),
        logits=jnp.asarray(batch["logits"]),
        core_state=tuple(core),
    )


def stack_trajectories(trajs: list) -> Trajectory:
    """Stack single-env trajectories along the batch axis (device-side concat),
    the equivalent of the reference learner's ``torch.stack(dim=1)`` batching
    (``impala_atari.py:246-252``)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.concatenate(xs, axis=1), *trajs)
