"""IMPACT's circular surrogate buffer (arxiv 1912.00167, §3.1).

A small ring of whole trajectory chunks sitting between the async actor
plane and the learner: each inserted chunk carries ``replay_times`` use
credits, ``add`` overwrites the oldest slot, and ``sample`` round-robins
over slots that still have credits — so every chunk participates in (up
to) K learner updates instead of one, and the updates mix chunks of
different ages.  That is the whole sample-efficiency mechanism; the
*stability* half (the clipped target-network surrogate that makes K>1
replays safe) lives in ``agents/impact.py``.

Host-side and jax-free by design: chunks are stored by reference (device
or host pytrees both fine — the learn step's ``shard_batch`` re-places
them per use), and the structure is plain counters, so it drops into the
existing host actor-learner planes without touching the device path.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional


class CircularTrajectoryBuffer:
    """Ring of trajectory chunks with per-chunk replay credits.

    ``capacity``: slots (chunks) retained; ``replay_times``: use credits a
    chunk is born with.  ``sample`` consumes one credit from the next slot
    (cursor order, skipping spent slots); when every retained chunk is
    spent — the learner outran the actors — the freshest chunk is returned
    anyway (and counted in ``overdraws``), matching IMPACT's non-blocking
    learner.
    """

    def __init__(self, capacity: int, replay_times: int) -> None:
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if replay_times < 1:
            raise ValueError(f"replay_times must be >= 1, got {replay_times}")
        self.capacity = capacity
        self.replay_times = replay_times
        self._chunks: List[Any] = []
        self._credits: List[int] = []
        self._write = 0  # next slot to overwrite
        self._read = 0  # round-robin sample cursor
        self._latest: Optional[int] = None
        self.inserted = 0
        self.sampled = 0
        self.overdraws = 0

    def __len__(self) -> int:
        return len(self._chunks)

    def add(self, chunk: Any) -> None:
        """Insert a chunk with fresh credits, overwriting the oldest slot
        once the ring is full (its unspent credits are forfeited — the
        circular-eviction semantics that bound staleness)."""
        if len(self._chunks) < self.capacity:
            self._latest = len(self._chunks)
            self._chunks.append(chunk)
            self._credits.append(self.replay_times)
        else:
            self._latest = self._write
            self._chunks[self._write] = chunk
            self._credits[self._write] = self.replay_times
            self._write = (self._write + 1) % self.capacity
        self.inserted += 1

    def sample(self) -> Any:
        """Next chunk with remaining credits (round-robin); falls back to
        the freshest chunk when everything is spent."""
        if not self._chunks:
            raise ValueError("sample() on an empty CircularTrajectoryBuffer")
        n = len(self._chunks)
        for _ in range(n):
            idx = self._read
            self._read = (self._read + 1) % n
            if self._credits[idx] > 0:
                self._credits[idx] -= 1
                self.sampled += 1
                return self._chunks[idx]
        self.overdraws += 1
        self.sampled += 1
        assert self._latest is not None
        return self._chunks[self._latest]

    def stats(self) -> Dict[str, int]:
        return {
            "size": len(self._chunks),
            "credits": sum(self._credits),
            "inserted": self.inserted,
            "sampled": self.sampled,
            "overdraws": self.overdraws,
        }
