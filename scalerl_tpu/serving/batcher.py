"""Dynamic batcher for the centralized inference plane.

SEED RL's core observation (PAPER.md bibliography; Podracer's Sebulba split,
arxiv 2104.06272) is that acting inference belongs on the accelerator next
to the learner, served to thin env-shell workers in *batches*: one hot model,
thousands of env lanes, no per-worker weight copies.  The batcher here is the
admission half of that server:

- **flush on size OR deadline** — a flush fires the moment ``max_batch``
  lanes are pending, or when the *oldest* pending request has waited
  ``max_wait_s`` (the latency/occupancy trade every serving system tunes);
- **bucketed static shapes** — flushed batches are padded up to a fixed
  bucket ladder so the jitted serve function compiles once per bucket and
  never retraces on ragged arrival patterns (graftlint JG003's hazard,
  designed out rather than linted out);
- **bounded admission with explicit load-shedding** — at ``max_pending``
  queued requests new arrivals are *shed* (counted, reported to the caller)
  instead of growing an unbounded queue whose depth silently becomes
  latency and policy lag.  Same ``max_pending``/``shed_total`` vocabulary
  as the fleet's ``QueueHub`` and the trainers' ``RolloutQueue``.

jax-free by design: requests are host numpy; the server owns the device.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, List, Optional, Tuple

from collections import deque

from scalerl_tpu.runtime import telemetry

# The pow2 ladder lives in utils/buckets.py (ISSUE 11: one definition for
# the serving lanes axis AND the genrl time axis); re-exported here so the
# serving plane's public names keep working.
from scalerl_tpu.utils.buckets import bucket_for, default_buckets  # noqa: F401


@dataclass
class ServingConfig:
    """Knobs for the inference server + dynamic batcher.

    ``max_pending`` follows the fleet-wide bounded-admission vocabulary
    (``FleetConfig.max_pending``): 0 disables shedding (unbounded queue,
    the pre-serving behavior of every other queue in the codebase).
    """

    max_batch: int = 64          # flush the moment this many lanes pend
    max_wait_s: float = 0.005    # ... or when the oldest request waited this
    max_pending: int = 256       # bounded admission: requests, not lanes
    buckets: Tuple[int, ...] = ()  # () -> power-of-two ladder to max_batch
    seed: int = 0                # serve-fn sampling key seed
    # liveness plane for socket clients (0 = off; serving links are
    # short-RPC, the client's request timeout is the primary detector)
    heartbeat_interval_s: float = 0.0

    def resolved_buckets(self) -> Tuple[int, ...]:
        return tuple(self.buckets) or default_buckets(self.max_batch)

    @classmethod
    def from_args(cls, args: Any) -> "ServingConfig":
        """Build from an ``RLArguments``-style object (serve_* fields)."""
        return cls(
            max_batch=int(getattr(args, "serve_max_batch", 64)),
            max_wait_s=float(getattr(args, "serve_max_wait_ms", 5.0)) / 1e3,
            max_pending=int(getattr(args, "serve_max_pending", 256)),
            seed=int(getattr(args, "seed", 0)),
        )


@dataclass
class ServingRequest:
    """One pending act request: a [B, ...] slab of env lanes plus the reply
    route (opaque to the batcher — the server demuxes)."""

    conn: Any
    req_id: Any
    lanes: int
    payload: Dict[str, Any]
    t_enqueue: float = field(default_factory=time.monotonic)
    # propagated trace context (runtime/tracing.py) when the client's act
    # request carried one — the server emits queue-wait/flush spans off it
    trace: Any = None


class DynamicBatcher:
    """Thread-safe pending-request queue with flush-on-size-or-deadline.

    Producers call :meth:`submit` (the server's admission pump); ONE
    consumer thread calls :meth:`next_batch` (the flush loop).  Shedding
    happens at submit time so a rejected request is answered immediately —
    the client retries or falls back locally instead of waiting on a queue
    that can only grow.
    """

    def __init__(self, config: ServingConfig) -> None:
        self.config = config
        self.buckets = config.resolved_buckets()
        self._cond = threading.Condition()
        self._pending: Deque[ServingRequest] = deque()
        self._pending_lanes = 0
        self._closed = False
        self.shed_total = 0
        self.submitted_total = 0
        telemetry.get_registry().bind("serving.batcher", self.stats)

    def submit(self, req: ServingRequest) -> bool:
        """Admit one request; False = shed (queue at ``max_pending``)."""
        with self._cond:
            if self._closed:
                return False
            if (
                self.config.max_pending > 0
                and len(self._pending) >= self.config.max_pending
            ):
                self.shed_total += 1
                telemetry.get_registry().counter("serving.shed_total").inc()
                return False
            self.submitted_total += 1
            self._pending.append(req)
            self._pending_lanes += req.lanes
            self._cond.notify()
            return True

    def next_batch(self, poll_s: float = 0.05) -> Optional[List[ServingRequest]]:
        """Block until a flush is due; returns the FIFO request batch
        (None once closed and drained).  A flush takes whole requests up to
        ``max_batch`` lanes — a request is never split across flushes."""
        with self._cond:
            while True:
                if self._pending:
                    if self._pending_lanes >= self.config.max_batch:
                        return self._take_locked()
                    deadline = self._pending[0].t_enqueue + self.config.max_wait_s
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return self._take_locked()
                    self._cond.wait(timeout=min(remaining, poll_s))
                elif self._closed:
                    return None
                else:
                    self._cond.wait(timeout=poll_s)

    def poll_batch(
        self, max_lanes: Optional[int] = None
    ) -> Optional[List[ServingRequest]]:
        """Non-blocking flush: the continuous-batching admission pump.

        Returns a FIFO request batch the moment a flush is *due* — pending
        lanes can fill ``max_lanes`` (capacity-triggered, the size half of
        the flush predicate) or the oldest pending request has waited
        ``max_wait_s`` (the deadline half) — else ``None`` immediately.
        ``max_lanes`` caps the batch (defaults to ``max_batch``); the
        caller passes its free-lane count so admission never over-commits.
        Same whole-request / never-split contract as :meth:`next_batch`.
        """
        with self._cond:
            if not self._pending:
                return None
            limit = self.config.max_batch if max_lanes is None else max_lanes
            if limit <= 0:
                return None
            due = self._pending_lanes >= limit or (
                time.monotonic()
                >= self._pending[0].t_enqueue + self.config.max_wait_s
            )
            if not due:
                return None
            if self._pending[0].lanes > limit:
                # the head request alone overflows the caller's free lanes:
                # not admissible yet (unlike the serving flush, admission
                # has a hard lane budget — no oversize bucket to grow into)
                return None
            return self._take_locked(limit)

    def _take_locked(
        self, max_lanes: Optional[int] = None
    ) -> List[ServingRequest]:
        limit = self.config.max_batch if max_lanes is None else max_lanes
        batch: List[ServingRequest] = []
        lanes = 0
        while self._pending:
            nxt = self._pending[0]
            if batch and lanes + nxt.lanes > limit:
                break
            batch.append(self._pending.popleft())
            lanes += nxt.lanes
        self._pending_lanes -= lanes
        return batch

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def stats(self) -> Dict[str, int]:
        with self._cond:
            return {
                "pending_requests": len(self._pending),
                "pending_lanes": self._pending_lanes,
                "shed_total": self.shed_total,
                "submitted_total": self.submitted_total,
            }
