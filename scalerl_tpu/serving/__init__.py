"""Centralized inference plane: SEED-style batched serving on the learner host.

One hot jitted policy on device (:class:`InferenceServer`), thin env-shell
workers streaming observations to it over the codec-v2 fleet transport
(:class:`RemotePolicyClient`), dynamic batching with bucketed static shapes
(:class:`DynamicBatcher`), bounded admission with explicit load shedding,
and generation-tagged parameters feeding V-trace's behavior-policy
correction and a staleness gauge.  The SLO-aware front door
(:class:`ServingRouter`) fans that wire over N replicas with circuit-
breaker health tracking, prefix-affinity + power-of-two-choices routing,
at-least-once re-dispatch, and rolling weight rollout.  docs/DISTRIBUTED.md
"Centralized inference plane" has the wire shape, knob tables, and the SLO
row; §5 there covers the front door.
"""

from scalerl_tpu.serving.batcher import (
    DynamicBatcher,
    ServingConfig,
    ServingRequest,
    bucket_for,
    default_buckets,
)
from scalerl_tpu.serving.client import (
    PendingReply,
    RemotePolicyClient,
    ServingUnavailable,
)
from scalerl_tpu.serving.router import (
    ReplicaHandle,
    ReplicaHealth,
    RouterConfig,
    RouterTierExecutor,
    ServingRouter,
    connect_replica,
)
from scalerl_tpu.serving.server import InferenceServer


def local_pair(chaos_site: str = "serve_pipe"):
    """An in-process duplex connection pair (client_end, server_end) for
    same-host serving (the trainer's ``actor_mode='serving'`` wiring) —
    both ends speak the codec, so the wire shape matches sockets exactly
    and the chaos injector can fault the link under the ``serve`` site
    prefix like any other transport."""
    import multiprocessing as mp

    from scalerl_tpu.fleet.transport import PipeConnection

    a, b = mp.Pipe(duplex=True)
    return (
        PipeConnection(a, chaos_site=chaos_site),
        PipeConnection(b, chaos_site=chaos_site),
    )


__all__ = [
    "DynamicBatcher",
    "InferenceServer",
    "PendingReply",
    "RemotePolicyClient",
    "ReplicaHandle",
    "ReplicaHealth",
    "RouterConfig",
    "RouterTierExecutor",
    "ServingConfig",
    "ServingRequest",
    "ServingRouter",
    "ServingUnavailable",
    "bucket_for",
    "connect_replica",
    "default_buckets",
    "local_pair",
]
