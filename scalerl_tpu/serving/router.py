"""SLO-aware serving front door: health-checked routing over N replicas.

One ``InferenceServer`` is a single point and a single chip; SEED RL
(PAPER.md bibliography) frames the learner as just one client of a
centralized inference *fleet*, and MindSpeed RL (arxiv 2507.19017)
separates tiers precisely so each tier can fail, drain, and upgrade
independently.  :class:`ServingRouter` is that front door: jax-free, it
speaks the existing ``RemotePolicyClient`` wire on the client side (codec
v2 — ``act``/``core_init`` in, ``act_result``/``core_init`` out) and fans
requests over N replica links, adding exactly two frame kinds of its own
(``router_hello`` membership and ``health``/``health_result``).

The robustness contract, assembled from four prior planes:

- **per-replica health rides existing machinery** — heartbeat liveness
  from the replica's ``QueueHub`` (the router answers pings like any
  client; silence past the health timeout is a death verdict), p95 /
  shed / pending depth off the ``health`` poll — feeding a **circuit
  breaker** (:class:`ReplicaHealth`): ``eject_after`` consecutive
  errors/sheds eject a replica from rotation; capped-``exp_backoff``
  probes (decorrelated jitter — a dead replica must not synchronize its
  probers) let ONE live request through per window, and a served probe
  re-admits;
- **prefix-affinity routing first** — the prompt's leading block (the
  ``affinity`` wire field when present, else the leading
  ``affinity_bytes`` of the obs slab) is rendezvous-hashed over routable
  replicas, so group/agentic traffic keeps landing where its shared-prefix
  KV pages (PR 14) live; when the affinity target is overloaded (beyond
  ``spill_load_factor`` x mean in-flight) or unroutable, **power-of-two-
  choices** on in-flight load takes over;
- **at-least-once re-dispatch under first-reply-wins dedup** (the PR 4
  idiom): every in-flight request on a dead replica is re-sent to a
  healthy one; the pending-table pop is the dedup point, so a late
  duplicate answer is *counted* (``router.duplicate_replies``), never
  double-delivered — a replica kill costs a retry, not a lost or
  double-served request.  A request that exhausts its ``hedge_budget``
  of retries gets an explicit shed, so every admitted request is answered
  exactly once: by a replica, a retry, or a shed;
- **rolling weight rollout** — the PR 9 drain protocol applied to
  servers: :meth:`rollout` drains one replica at a time (no new routes ->
  wait out in-flight -> ``push_params`` through the shared
  ``ParamSnapshotPlane`` -> re-admit), a **max-generation-skew guard**
  keeps laggard replicas out of rotation until a catch-up push, and the
  client-side ``max()`` fold keeps the generation clients observe
  monotonic mid-rollout;
- **capacity control** — ``runtime/autoscaler.py``'s serving-tier rule
  drives replica count off the router's aggregate p95
  (``router_signal_source`` + :class:`RouterTierExecutor`).

docs/DISTRIBUTED.md §5 has the routing policy, the health/eject/probe
state machine, the rolling-rollout sequence, and the failure matrix;
docs/OBSERVABILITY.md lists the ``router.*`` instruments.
"""

from __future__ import annotations

import itertools
import random
import threading
import time
import zlib
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

import numpy as np

from scalerl_tpu.fleet.hub import QueueHub
from scalerl_tpu.fleet.transport import (
    Connection,
    SocketConnection,
    accept_connection,
    listen_socket,
)
from scalerl_tpu.runtime import telemetry, tracing
from scalerl_tpu.runtime.supervisor import (
    LivenessTracker,
    exp_backoff,
    is_heartbeat,
    make_pong,
)
from scalerl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# chaos site prefix for router<->client links (sites=route scopes faults to
# the front door; replica links keep the serving plane's serve_* sites)
ROUTE_CHAOS_SITE = "route_sock"

# replica health states (the breaker's vocabulary; docs/DISTRIBUTED.md §5)
HEALTHY = "healthy"
DRAINING = "draining"
EJECTED = "ejected"

# breaker state -> gauge code (``router.breaker.<replica>``): a replay
# verdict correlates a p99 spike against this timeline numerically.
# 0 = closed (healthy, in rotation), 1 = open (ejected), 2 = probing
# (one trial in flight), 3 = draining (rollout/scale-down)
BREAKER_CODES = {HEALTHY: 0.0, EJECTED: 1.0, DRAINING: 3.0}
BREAKER_PROBING = 2.0


@dataclass
class RouterConfig:
    """Knobs for the front door's breaker, routing, and rollout."""

    # circuit breaker: consecutive errors/sheds on one replica before it is
    # ejected from rotation (successes reset the streak)
    eject_after: int = 3
    # capped-exp_backoff probe schedule for ejected replicas; jitter is ON
    # here by default — probing is exactly the synchronized-storm path the
    # decorrelated draw exists for (determinism-pinned tests inject rng)
    probe_backoff_s: float = 0.05
    probe_backoff_cap_s: float = 2.0
    probe_jitter: bool = True
    # retries per request beyond the first dispatch (shed/error/death all
    # consume one); exhausted -> explicit shed to the client
    hedge_budget: int = 2
    # leading obs bytes hashed into the prefix-affinity key when the act
    # frame carries no explicit "affinity" field
    affinity_bytes: int = 64
    # the affinity target spills to power-of-two-choices when its in-flight
    # load exceeds this multiple of the mean across routable replicas
    spill_load_factor: float = 2.0
    # a replica whose generation lags the fleet max by more than this is
    # held out of rotation until a catch-up push (mid-rollout guard)
    max_gen_skew: int = 1
    # health poll cadence over replica links (0 = off; request outcomes
    # still feed the breaker).  A replica silent past health_timeout_s
    # (default 4x interval) is declared dead.
    health_interval_s: float = 0.0
    health_timeout_s: float = 0.0
    # graceful-drain bound for rollout()/remove_replica(): in-flight
    # stragglers past this are re-dispatched instead of wedging the drain
    drain_timeout_s: float = 5.0
    # client-side hub plumbing (same vocabulary as ServingConfig)
    hub_maxsize: int = 1024
    max_pending: int = 0
    client_heartbeat_s: float = 0.0
    seed: int = 0

    def resolved_health_timeout(self) -> float:
        return self.health_timeout_s or 4.0 * self.health_interval_s


class ReplicaHealth:
    """The per-replica circuit breaker: a pure state machine over request
    outcomes, unit-testable with an injected clock.

    States: HEALTHY (in rotation) -> EJECTED (``eject_after`` consecutive
    failures, or a death verdict via :meth:`force_eject`) -> probe window
    (one live request allowed once ``probe_at`` passes) -> HEALTHY on a
    served probe, or re-ejected with a longer capped backoff on a failed
    one.  DRAINING (rollout/scale-down) is routable never, re-admitted
    explicitly.  Not thread-safe by itself — the router serializes
    transitions under its lock.
    """

    def __init__(
        self,
        eject_after: int = 3,
        probe_backoff_s: float = 0.05,
        probe_backoff_cap_s: float = 2.0,
        jitter: bool = True,
        rng: Any = None,
    ) -> None:
        self.eject_after = max(int(eject_after), 1)
        self.probe_backoff_s = probe_backoff_s
        self.probe_backoff_cap_s = probe_backoff_cap_s
        self.jitter = jitter
        self.rng = rng
        self.state = HEALTHY
        self.consecutive_failures = 0
        self.ejections = 0       # lifetime count; also the backoff attempt
        self.probe_at = 0.0
        self.probing = False     # one trial request in flight

    def record_ok(self) -> bool:
        """A served request: resets the failure streak; a served *probe*
        re-admits.  Returns True exactly on the EJECTED->HEALTHY edge."""
        self.consecutive_failures = 0
        if self.state == EJECTED:
            self.state = HEALTHY
            self.probing = False
            self.ejections = 0  # a recovered replica earns a fresh schedule
            return True
        return False

    def record_failure(self, now: Optional[float] = None) -> bool:
        """A shed/error outcome.  Returns True exactly when this failure
        ejects (or re-ejects, for a failed probe) the replica."""
        now = time.monotonic() if now is None else now
        if self.state == EJECTED:
            if self.probing:  # the probe request itself failed: back off more
                self._eject(now)
                return True
            return False
        self.consecutive_failures += 1
        if self.state == HEALTHY and self.consecutive_failures >= self.eject_after:
            self._eject(now)
            return True
        return False

    def force_eject(self, now: Optional[float] = None) -> None:
        """Death verdict (link lost / liveness timeout): eject immediately
        regardless of streak."""
        self._eject(time.monotonic() if now is None else now)

    def _eject(self, now: float) -> None:
        self.state = EJECTED
        self.probing = False
        self.consecutive_failures = 0
        delay = exp_backoff(
            self.ejections,
            self.probe_backoff_s,
            self.probe_backoff_cap_s,
            jitter=self.jitter,
            rng=self.rng,
        )
        self.ejections += 1
        self.probe_at = now + delay

    def mark_draining(self) -> None:
        self.state = DRAINING
        self.probing = False

    def readmit(self) -> None:
        """Explicit re-admission (rollout push done / operator action)."""
        self.state = HEALTHY
        self.probing = False
        self.consecutive_failures = 0

    def routable(self, now: Optional[float] = None) -> bool:
        """In rotation?  An EJECTED replica becomes routable for exactly
        ONE request per probe window (the trial the breaker re-admits on)."""
        if self.state == HEALTHY:
            return True
        if self.state == DRAINING:
            return False
        now = time.monotonic() if now is None else now
        if not self.probing and now >= self.probe_at:
            self.probing = True
            return True
        return False


class ReplicaHandle:
    """One replica as the router sees it: the wire link, the optional
    in-process control handle (``server`` — anything with ``push_params``,
    the rollout path), and the in-flight ledger."""

    def __init__(self, name: str, conn: Connection, server: Any = None) -> None:
        self.name = name
        self.conn = conn
        self.server = server
        self.alive = True
        self.generation = 0
        # the learner incarnation whose params this replica serves (set by
        # the router's own pushes): generations only compare within the
        # epoch-qualified order (epoch, generation)
        self.epoch = 0
        self.p95_ms = 0.0
        self.shed_total = 0
        self.pending = 0
        self.host = ""
        self._send_lock = threading.Lock()
        self._inflight: Set[int] = set()
        self._inflight_lock = threading.Lock()

    def send(self, msg: Dict[str, Any]) -> None:
        with self._send_lock:
            self.conn.send(msg)

    def begin(self, rid: int) -> None:
        with self._inflight_lock:
            self._inflight.add(rid)

    def end(self, rid: int) -> None:
        with self._inflight_lock:
            self._inflight.discard(rid)

    def inflight_count(self) -> int:
        with self._inflight_lock:
            return len(self._inflight)

    def take_inflight(self) -> List[int]:
        """Snapshot-and-clear the ledger (the re-dispatch sweep)."""
        with self._inflight_lock:
            rids, self._inflight = list(self._inflight), set()
        return rids


def connect_replica(server: Any, name: str) -> ReplicaHandle:
    """Wire an in-process ``InferenceServer`` behind the router: a codec
    pipe pair, the server end registered on its hub, the client end held
    by the router — the bench/chaos topology (socket replicas hand the
    router a pre-dialed :class:`ReplicaHandle` instead)."""
    from scalerl_tpu.serving import local_pair

    router_end, server_end = local_pair()
    server.add_connection(server_end)
    return ReplicaHandle(name, router_end, server=server)


class _Pending:
    """One admitted request: the reply route back to the client plus the
    retry ledger.  ``rid`` (the router's monotonic id) is the wire ``req``
    on replica links; ``client_req`` is restored on the way back."""

    __slots__ = (
        "rid", "client", "client_req", "msg", "kind", "affinity",
        "attempts", "t_admit", "trace", "replica",
    )

    def __init__(self, rid, client, client_req, msg, kind, affinity, trace):
        self.rid = rid
        self.client = client
        self.client_req = client_req
        self.msg = msg
        self.kind = kind
        self.affinity = affinity
        self.attempts = 0
        self.t_admit = time.monotonic()
        self.trace = trace
        self.replica: Optional[str] = None


class ServingRouter:
    """The front door: client hub in, N health-tracked replica links out.

    jax-free by design — the router runs wherever the clients are (the
    learner host, an edge pop, a test) and must never pay a device or a
    jax import.  See the module docstring for the full contract.
    """

    def __init__(
        self,
        replicas: Optional[List[ReplicaHandle]] = None,
        config: Optional[RouterConfig] = None,
    ) -> None:
        self.config = config or RouterConfig()
        self._rng = random.Random(self.config.seed)
        self._rids = itertools.count(1)
        self._pending: Dict[int, _Pending] = {}
        self._lock = threading.RLock()
        self.replicas: List[ReplicaHandle] = []
        self._health: Dict[str, ReplicaHealth] = {}
        self._liveness = LivenessTracker()
        self._reader_threads: Dict[str, threading.Thread] = {}
        self._last_push: Optional[
            Tuple[Any, Optional[int], int]
        ] = None
        # newest learner epoch ever rolled out through this router: a
        # rollout from an OLDER epoch (a zombie pre-restart learner racing
        # its restarted successor) is refused, so rolling restarts can
        # never re-serve a stale generation
        self.learner_epoch = 0
        self.stale_rollouts = 0
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._listen_sock = None
        # exact-accounting ledger: admitted == answered + shed + orphaned
        # once quiesced — the chaos e2e's acceptance equation
        self.admitted = 0
        self.answered = 0
        self.shed = 0
        self.retries = 0
        self.redispatches = 0
        self.duplicate_replies = 0
        self.orphaned = 0
        self.ejections = 0
        self.readmissions = 0
        self.rollouts = 0
        reg = telemetry.get_registry()
        # digest backend: aggregate_p95_ms() is the autoscaler's capacity
        # signal — it must hold its relative-error bound at front-door
        # request counts, which the reservoir backend cannot (ISSUE 20)
        self._lat_hist = reg.histogram("router.latency_s", backend="digest")
        self._req_meter = reg.meter("router.requests_per_s")
        self._req_counter = reg.counter("router.requests")
        self._retry_counter = reg.counter("router.retries")
        self._redispatch_counter = reg.counter("router.redispatches")
        self._shed_counter = reg.counter("router.sheds")
        self._dup_counter = reg.counter("router.duplicate_replies")
        self._eject_counter = reg.counter("router.ejections")
        self._readmit_counter = reg.counter("router.readmissions")
        reg.bind("router", self.stats)
        self.hub = QueueHub(
            maxsize=self.config.hub_maxsize,
            heartbeat_interval=self.config.client_heartbeat_s,
            max_pending=self.config.max_pending,
            on_disconnect=self._on_client_gone,
        )
        for r in replicas or ():
            self.add_replica(r)

    # -- membership -----------------------------------------------------
    def add_replica(self, replica: ReplicaHandle) -> None:
        """Admit a replica: announce membership (``router_hello``), start
        its reader, put it in rotation."""
        with self._lock:
            if any(r.name == replica.name for r in self.replicas):
                raise ValueError(f"duplicate replica name {replica.name!r}")
            self.replicas.append(replica)
            self._health[replica.name] = ReplicaHealth(
                eject_after=self.config.eject_after,
                probe_backoff_s=self.config.probe_backoff_s,
                probe_backoff_cap_s=self.config.probe_backoff_cap_s,
                jitter=self.config.probe_jitter,
                rng=self._rng,
            )
        self._export_breaker(replica.name)
        self._liveness.beat(replica.name)
        t = threading.Thread(
            target=self._replica_loop, args=(replica,),
            name=f"router-replica-{replica.name}", daemon=True,
        )
        self._reader_threads[replica.name] = t
        t.start()
        try:
            replica.send({"kind": "router_hello", "req": f"hello:{replica.name}"})
        except (ConnectionError, OSError, ValueError):
            self._on_replica_down(replica, "hello failed")
        # a late-joining replica adopts the newest rolled-out snapshot
        # (epoch-qualified) BEFORE taking traffic — otherwise the skew /
        # epoch guards would hold it out of rotation forever anyway
        self._catch_up(replica)
        telemetry.record_event("router_replica_added", replica=replica.name)

    def remove_replica(
        self, name: str, drain: bool = True
    ) -> Optional[ReplicaHandle]:
        """Drain a replica out of rotation and drop its link; returns the
        handle so the owner (the tier executor) can stop the process."""
        with self._lock:
            replica = next((r for r in self.replicas if r.name == name), None)
        if replica is None:
            return None
        health = self._health[name]
        health.mark_draining()
        self._export_breaker(name)
        if drain:
            self._await_drain(replica)
        with self._lock:
            replica.alive = False
            self.replicas = [r for r in self.replicas if r.name != name]
        self._redispatch_inflight(replica)
        try:
            replica.conn.close()
        except Exception:  # noqa: BLE001 — teardown
            pass
        self._liveness.forget(name)
        telemetry.record_event("router_replica_removed", replica=name)
        return replica

    # -- bring-up -------------------------------------------------------
    def start(self, listen_port: Optional[int] = None) -> None:
        self._threads = [
            threading.Thread(target=self._client_loop, name="router-admit",
                             daemon=True),
        ]
        if self.config.health_interval_s > 0:
            self._threads.append(
                threading.Thread(target=self._health_loop,
                                 name="router-health", daemon=True)
            )
        if listen_port is not None:
            self._listen_sock = listen_socket(listen_port)
            self._threads.append(
                threading.Thread(
                    target=self._accept_loop, args=(self._listen_sock,),
                    name="router-accept", daemon=True,
                )
            )
        for t in self._threads:
            t.start()

    def add_client(self, conn: Connection) -> None:
        """Register an in-process or pre-accepted client link."""
        self.hub.add_connection(conn)

    def stop(self) -> None:
        self._stop.set()
        if self._listen_sock is not None:
            try:
                self._listen_sock.close()
            except OSError:
                pass
        self.hub.close()
        for replica in list(self.replicas):
            try:
                replica.conn.close()
            except Exception:  # noqa: BLE001 — teardown
                pass
        for t in list(self._threads) + list(self._reader_threads.values()):
            t.join(timeout=3.0)

    def _accept_loop(self, sock) -> None:
        while not self._stop.is_set():
            try:
                conn = accept_connection(sock, timeout=0.5)
            except (TimeoutError, OSError):
                continue
            if isinstance(conn, SocketConnection):
                conn.chaos_site = ROUTE_CHAOS_SITE
            self.hub.add_connection(conn)

    def _on_client_gone(self, conn: Connection) -> None:
        """A client link dropped: orphan its pendings so late replies are
        counted instead of sent down a dead pipe."""
        with self._lock:
            for p in self._pending.values():
                if p.client is conn:
                    p.client = None

    # -- admission + routing --------------------------------------------
    def _client_loop(self) -> None:
        import queue as queue_mod

        while not self._stop.is_set():
            try:
                conn, msg = self.hub.recv(timeout=0.2)
            except queue_mod.Empty:
                continue
            try:
                self._admit(conn, msg)
            except Exception:  # noqa: BLE001 — a bad request must not kill the front door
                logger.exception(
                    "router: failed handling %r",
                    msg.get("kind") if isinstance(msg, dict) else msg,
                )

    def _admit(self, conn: Connection, msg: Dict[str, Any]) -> None:
        kind = msg.get("kind")
        if kind not in ("act", "core_init"):
            logger.warning("router: unknown message kind %r", kind)
            return
        rid = next(self._rids)
        p = _Pending(
            rid=rid,
            client=conn,
            client_req=msg.get("req"),
            msg=msg,
            kind=kind,
            affinity=self._affinity_key(msg),
            trace=tracing.extract(msg),
        )
        with self._lock:
            self.admitted += 1
            self._pending[rid] = p
        self._req_counter.inc()
        self._req_meter.mark()
        self._dispatch(p)

    def _affinity_key(self, msg: Dict[str, Any]) -> Optional[int]:
        """The placement key: an explicit ``affinity`` field wins (agentic
        callers tag a conversation); else the leading bytes of the obs slab
        — the prompt's first blocks, so identical prefixes hash together."""
        if "affinity" in msg:
            return zlib.crc32(str(msg["affinity"]).encode())
        obs = msg.get("obs")
        if obs is None:
            return None
        arr = np.ascontiguousarray(np.asarray(obs))
        head = arr.tobytes()[: self.config.affinity_bytes]
        return zlib.crc32(head) if head else None

    def _route(
        self, p: _Pending, exclude: Set[str] = frozenset()
    ) -> Optional[ReplicaHandle]:
        now = time.monotonic()
        with self._lock:
            fleet_max = max((r.generation for r in self.replicas), default=0)
            eligible = [
                r for r in self.replicas
                if r.name not in exclude and r.alive
                # mid-rollout laggards are held out until caught up
                and fleet_max - r.generation <= self.config.max_gen_skew
                # a pushable replica still on a pre-restart learner epoch
                # serves stale weights by definition — held out until
                # _catch_up rolls it forward (wire-only replicas track
                # generations through their own reports instead)
                and (r.server is None or r.epoch >= self.learner_epoch)
            ]
            # probe-due ejected replicas take the next request as their ONE
            # trial per window — the flag is consumed here, exactly when the
            # request is actually routed to them
            for r in eligible:
                h = self._health[r.name]
                if h.state == EJECTED and not h.probing and now >= h.probe_at:
                    h.probing = True
                    # the open->probing edge of the breaker timeline: a
                    # gauge write + flight event, both host-side and cheap
                    self._export_breaker(r.name)
                    telemetry.record_event("router_probe", replica=r.name)
                    return r
            candidates = [
                r for r in eligible
                if self._health[r.name].state == HEALTHY
            ]
        if not candidates:
            return None
        if len(candidates) == 1:
            return candidates[0]
        loads = [r.inflight_count() for r in candidates]
        if p.affinity is not None:
            # rendezvous (highest-random-weight) hash: stable under replica
            # churn — adding/removing one replica only remaps the keys that
            # belonged to it, so prefix pages stay where they were
            best_i = max(
                range(len(candidates)),
                key=lambda i: zlib.crc32(
                    f"{p.affinity}|{candidates[i].name}".encode()
                ),
            )
            mean = sum(loads) / len(loads)
            if loads[best_i] <= self.config.spill_load_factor * max(mean, 1.0):
                return candidates[best_i]
        # power-of-two-choices on in-flight load (affinity target overloaded
        # or no affinity key): two random candidates, take the idler one
        i, j = self._rng.sample(range(len(candidates)), 2)
        return candidates[i] if loads[i] <= loads[j] else candidates[j]

    def _dispatch(self, p: _Pending, exclude: Set[str] = frozenset()) -> None:
        replica = self._route(p, exclude)
        if replica is None:
            self._give_up(p, "no routable replica")
            return
        p.replica = replica.name
        replica.begin(p.rid)
        fwd = dict(p.msg)
        fwd["req"] = p.rid
        try:
            replica.send(fwd)
        except (ConnectionError, OSError, ValueError):
            self._on_replica_down(replica, "send failed")

    def _give_up(self, p: _Pending, why: str) -> None:
        """Explicit shed back to the client — the exactly-once terminal for
        a request no replica could serve."""
        reply_kind = "act_result" if p.kind == "act" else p.kind
        with self._lock:
            self._pending.pop(p.rid, None)
            # exactly one terminal bucket per admitted request: a shed is
            # DELIVERED; a client that vanished first counts as orphaned
            if p.client is not None:
                self.shed += 1
            else:
                self.orphaned += 1
        if p.client is not None:
            self._shed_counter.inc()
            self.hub.send(
                p.client,
                {"kind": reply_kind, "req": p.client_req, "shed": True},
            )
        telemetry.record_event("router_shed", why=why, kind=p.kind)

    def _retry(self, p: _Pending, from_name: str, why: str) -> None:
        """Re-dispatch an un-answered request (its pending entry is already
        popped); exhausting the hedge budget sheds explicitly instead."""
        if p.attempts >= self.config.hedge_budget:
            self._give_up(p, f"hedge budget exhausted ({why})")
            return
        p.attempts += 1
        self.retries += 1
        self._retry_counter.inc()
        with self._lock:
            self._pending[p.rid] = p
        self._dispatch(p, exclude={from_name})

    # -- the replica side -----------------------------------------------
    def _replica_loop(self, replica: ReplicaHandle) -> None:
        while not self._stop.is_set() and replica.alive:
            try:
                msg = replica.conn.recv(timeout=0.2)
            except TimeoutError:
                continue
            except (ConnectionError, EOFError, OSError, ValueError):
                if self._stop.is_set():
                    return  # router teardown, not a replica death
                # includes ProtocolError: desynchronized stream = dead link
                self._on_replica_down(replica, "link lost")
                return
            self._liveness.beat(replica.name)
            if is_heartbeat(msg):
                # the replica hub's liveness plane: answer pings so silence
                # verdicts never fire against a healthy router
                if isinstance(msg, dict) and msg.get("kind") == "ping":
                    try:
                        replica.send(make_pong(msg))
                    except (ConnectionError, OSError):
                        self._on_replica_down(replica, "pong failed")
                        return
                continue
            if not isinstance(msg, dict):
                continue
            kind = msg.get("kind")
            if kind == "health_result":
                self._on_health(replica, msg)
            elif kind == "router_hello":
                replica.host = str(msg.get("host", ""))
                replica.generation = max(
                    replica.generation, int(msg.get("gen", 0))
                )
            else:
                self._on_reply(replica, msg)

    def _on_reply(self, replica: ReplicaHandle, msg: Dict[str, Any]) -> None:
        rid = msg.get("req")
        replica.end(rid)
        with self._lock:
            p = self._pending.pop(rid, None)
        if p is None:
            # first-reply-wins dedup: a re-dispatched request was already
            # answered elsewhere (or shed) — count, never double-deliver
            with self._lock:
                self.duplicate_replies += 1
            self._dup_counter.inc()
            return
        health = self._health[replica.name]
        if msg.get("shed"):
            if health.record_failure():
                self._note_ejection(replica, "shed streak")
            self._retry(p, replica.name, "shed")
            return
        if "error" in msg:
            if health.record_failure():
                self._note_ejection(replica, "error streak")
            self._retry(p, replica.name, "error")
            return
        if health.record_ok():
            self._note_readmission(replica)
        replica.generation = max(
            replica.generation, int(msg.get("gen", replica.generation))
        )
        now = time.monotonic()
        self._lat_hist.observe(max(now - p.t_admit, 0.0))
        with self._lock:
            if p.client is None:
                self.orphaned += 1
                return
            self.answered += 1
        if p.trace is not None:
            tracing.record_span(
                "router.route", parent=p.trace, t_start=p.t_admit,
                t_end=now, kind="serving", replica=replica.name,
                attempts=p.attempts,
            )
        out = dict(msg)
        out["req"] = p.client_req
        self.hub.send(p.client, out)

    def _note_ejection(self, replica: ReplicaHandle, why: str) -> None:
        self.ejections += 1
        self._eject_counter.inc()
        self._export_breaker(replica.name)
        telemetry.record_event("router_eject", replica=replica.name, why=why)
        logger.warning("router: ejected replica %s (%s)", replica.name, why)

    def _note_readmission(self, replica: ReplicaHandle) -> None:
        self.readmissions += 1
        self._readmit_counter.inc()
        self._export_breaker(replica.name)
        telemetry.record_event("router_readmit", replica=replica.name)
        logger.info("router: re-admitted replica %s", replica.name)
        self._catch_up(replica)

    def _export_breaker(self, name: str) -> None:
        """Export one replica's breaker state as a gauge
        (``router.breaker.<replica>``; see :data:`BREAKER_CODES`).  Called
        on every transition — a replay verdict lines p99 spikes up against
        this timeline plus the eject/readmit/probe/rollout flight events."""
        h = self._health.get(name)
        if h is None:
            return
        code = (
            BREAKER_PROBING if (h.state == EJECTED and h.probing)
            else BREAKER_CODES.get(h.state, 0.0)
        )
        telemetry.get_registry().gauge(f"router.breaker.{name}").set(code)

    def breaker_states(self) -> Dict[str, str]:
        """The per-replica breaker state, human vocabulary (``probing``
        refines ``ejected`` while the trial request is in flight)."""
        with self._lock:
            return {
                name: ("probing" if (h.state == EJECTED and h.probing)
                       else h.state)
                for name, h in self._health.items()
                if any(r.name == name for r in self.replicas)
            }

    def _on_replica_down(self, replica: ReplicaHandle, why: str) -> None:
        """Death verdict: eject, close, and re-dispatch every in-flight
        request — at-least-once, the dedup pop above keeps it exactly-once
        at the client."""
        with self._lock:
            if not replica.alive:
                return
            replica.alive = False
        self._health[replica.name].force_eject()
        self._note_ejection(replica, why)
        try:
            replica.conn.close()
        except Exception:  # noqa: BLE001 — link already broken
            pass
        telemetry.record_event(
            "router_replica_down", replica=replica.name, why=why
        )
        self._redispatch_inflight(replica)

    def _redispatch_inflight(self, replica: ReplicaHandle) -> None:
        for rid in replica.take_inflight():
            with self._lock:
                p = self._pending.pop(rid, None)
            if p is None:
                continue
            self.redispatches += 1
            self._redispatch_counter.inc()
            self._retry(p, replica.name, "replica down")

    # -- health plane ---------------------------------------------------
    def _health_loop(self) -> None:
        timeout = self.config.resolved_health_timeout()
        while not self._stop.wait(self.config.health_interval_s):
            now = time.monotonic()
            for replica in list(self.replicas):
                if not replica.alive:
                    continue
                last = self._liveness.last_seen(replica.name)
                if last is not None and now - last > timeout:
                    self._on_replica_down(replica, "health timeout")
                    continue
                try:
                    replica.send(
                        {"kind": "health", "req": f"health:{replica.name}"}
                    )
                except (ConnectionError, OSError, ValueError):
                    self._on_replica_down(replica, "health send failed")

    def _on_health(self, replica: ReplicaHandle, msg: Dict[str, Any]) -> None:
        replica.p95_ms = float(msg.get("p95_ms", replica.p95_ms))
        replica.shed_total = int(msg.get("shed_total", replica.shed_total))
        replica.pending = int(msg.get("pending", replica.pending))
        replica.host = str(msg.get("host", replica.host))
        replica.generation = max(
            replica.generation, int(msg.get("gen", replica.generation))
        )

    # -- rolling weight rollout -----------------------------------------
    def _await_drain(self, replica: ReplicaHandle) -> None:
        deadline = time.monotonic() + self.config.drain_timeout_s
        while replica.inflight_count() > 0 and time.monotonic() < deadline:
            time.sleep(0.002)

    def rollout(
        self,
        params: Any,
        learner_step: Optional[int] = None,
        learner_epoch: Optional[int] = None,
    ) -> int:
        """Rolling weight rollout: one replica at a time, drain -> push ->
        re-admit — in-flight traffic keeps flowing through the others, and
        the ``max_gen_skew`` guard bounds how far the fleet can diverge
        mid-roll.  Returns the fleet's max generation after the roll.

        ``learner_epoch`` (when the caller rides the preemption-tolerant
        plane) orders rollouts ACROSS learner restarts: a push from an
        older epoch than the newest ever seen is a zombie pre-restart
        learner racing its successor and is refused outright — the
        epoch-qualified order (epoch, generation) is what "never serve a
        stale generation through a rolling restart" means."""
        if learner_epoch is not None:
            epoch = int(learner_epoch)
            if epoch < self.learner_epoch:
                self.stale_rollouts += 1
                telemetry.record_event(
                    "router_stale_rollout",
                    epoch=epoch,
                    current=self.learner_epoch,
                )
                logger.warning(
                    "router: refused rollout from stale learner epoch %d "
                    "(current %d)", epoch, self.learner_epoch,
                )
                return max(
                    (r.generation for r in self.replicas if r.alive),
                    default=0,
                )
            self.learner_epoch = epoch
        self._last_push = (params, learner_step, self.learner_epoch)
        self.rollouts += 1
        for replica in list(self.replicas):
            if not replica.alive or replica.server is None:
                continue
            health = self._health[replica.name]
            in_rotation = health.state == HEALTHY
            if in_rotation:
                health.mark_draining()
                self._export_breaker(replica.name)
                # the rollout phase timeline: drain -> push -> readmit per
                # replica, so a replay verdict can correlate a latency
                # spike with exactly which phase the fleet was in
                telemetry.record_event(
                    "router_rollout_phase", replica=replica.name,
                    phase="drain", rollout=self.rollouts,
                )
                self._await_drain(replica)
                # stragglers past the drain bound re-dispatch (the replica
                # may be wedged; at-least-once covers the race where it
                # still answers)
                self._redispatch_inflight(replica)
            telemetry.record_event(
                "router_rollout_phase", replica=replica.name, phase="push",
                rollout=self.rollouts,
            )
            gen = replica.server.push_params(params, learner_step=learner_step)
            replica.generation = max(replica.generation, int(gen))
            replica.epoch = max(replica.epoch, self.learner_epoch)
            if in_rotation:
                # an EJECTED replica gets the push (generations stay
                # aligned) but NOT a free pass back into rotation — only
                # its probe can re-admit it
                health.readmit()
                self._export_breaker(replica.name)
                telemetry.record_event(
                    "router_rollout_phase", replica=replica.name,
                    phase="readmit", rollout=self.rollouts,
                )
            telemetry.record_event(
                "router_rollout", replica=replica.name, gen=replica.generation
            )
        fleet_max = max(
            (r.generation for r in self.replicas if r.alive), default=0
        )
        return fleet_max

    def _catch_up(self, replica: ReplicaHandle) -> None:
        """A re-admitted (or late-joining) laggard gets the newest
        rolled-out params: pushes repeat until its epoch-qualified
        (epoch, generation) reaches the fleet max, so the skew guard
        releases it back into rotation — a replica that slept through a
        learner restart cannot re-enter serving pre-restart weights."""
        if replica.server is None or self._last_push is None:
            return
        params, step, epoch = self._last_push
        with self._lock:
            fleet_max = max((r.generation for r in self.replicas), default=0)
        while (replica.epoch, replica.generation) < (epoch, fleet_max):
            gen = replica.server.push_params(params, learner_step=step)
            replica.generation = max(replica.generation, int(gen))
            replica.epoch = max(replica.epoch, epoch)

    # -- observability ---------------------------------------------------
    def replica_count(self) -> int:
        with self._lock:
            return sum(1 for r in self.replicas if r.alive)

    def healthy_count(self) -> int:
        now = time.monotonic()
        with self._lock:
            return sum(
                1 for r in self.replicas
                if r.alive and self._health[r.name].state == HEALTHY
            )

    def aggregate_p95_ms(self) -> float:
        """The tier's end-to-end p95 (router admit -> client reply), the
        autoscaler's capacity signal — retries and failover included, which
        per-replica p95s structurally cannot see."""
        return self._lat_hist.quantile(0.95) * 1e3

    def slo(self) -> Dict[str, float]:
        h = self._lat_hist
        return {
            "p50_ms": h.quantile(0.50) * 1e3,
            "p95_ms": h.quantile(0.95) * 1e3,
            "p99_ms": h.quantile(0.99) * 1e3,
            "requests": self.admitted,
        }

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            inflight = len(self._pending)
            gens = [r.generation for r in self.replicas if r.alive]
            epochs = [r.epoch for r in self.replicas if r.alive]
        return {
            "admitted": self.admitted,
            "answered": self.answered,
            "shed": self.shed,
            "retries": self.retries,
            "redispatches": self.redispatches,
            "duplicate_replies": self.duplicate_replies,
            "orphaned": self.orphaned,
            "ejections": self.ejections,
            "readmissions": self.readmissions,
            "rollouts": self.rollouts,
            "inflight": inflight,
            "replicas": len(gens),
            "healthy": self.healthy_count(),
            "generation_max": max(gens, default=0),
            "generation_min": min(gens, default=0),
            "learner_epoch": self.learner_epoch,
            "epoch_min": min(epochs, default=0),
            "stale_rollouts": self.stale_rollouts,
            "breaker": self.breaker_states(),
        }


class RouterTierExecutor:
    """The autoscaler executor over the router's replica fleet: scale-up
    spawns a replica through ``replica_factory`` (returning a wired
    :class:`ReplicaHandle`), scale-down drains the newest one — same
    duck-typed surface (``worker_count``/``scale_up``/``scale_down``) as
    the actor fleet's ``ClusterExecutor``."""

    def __init__(
        self,
        router: ServingRouter,
        replica_factory: Callable[[int], ReplicaHandle],
        stop_replica: Optional[Callable[[ReplicaHandle], None]] = None,
    ) -> None:
        self.router = router
        self._factory = replica_factory
        self._stop_replica = stop_replica
        self._spawned = itertools.count(len(router.replicas))

    def worker_count(self) -> int:
        return self.router.replica_count()

    def scale_up(self, n: int) -> None:
        for _ in range(n):
            self.router.add_replica(self._factory(next(self._spawned)))

    def scale_down(self, n: int) -> None:
        # newest-first drain: the longest-lived replicas hold the warmest
        # prefix caches, so churn costs the least affinity
        for _ in range(n):
            with self.router._lock:
                live = [r for r in self.router.replicas if r.alive]
            if not live:
                return
            handle = self.router.remove_replica(live[-1].name)
            if handle is not None and self._stop_replica is not None:
                self._stop_replica(handle)
