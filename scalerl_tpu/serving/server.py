"""Batched TPU inference server: one hot model serving thin env shells.

The SEED-RL inversion of the actor plane (ROADMAP "millions-of-users
shape"; Podracer's Sebulba split, arxiv 2104.06272): instead of every fleet
worker holding its own policy copy, ONE jitted policy lives on the learner
host's accelerator and workers stream observations to it over the existing
codec-v2 fleet transport.  The server owns:

- a **dynamic batcher** (``batcher.py``): flush on ``max_batch`` lanes OR
  the ``max_wait_s`` deadline, padded to bucketed static shapes so XLA
  compiles once per bucket and never retraces;
- a **JG001-clean flush hot loop**: per flush, exactly ONE explicit
  batched host->device upload of the stacked request batch and ONE
  explicit batched device->host read of the outputs, armed with
  ``steady_state_guard()`` once a bucket's first (compiling) flush is done
  — a stray implicit transfer anywhere in the loop raises at the line
  that did it;
- **generation-tagged parameters**: the learner pushes fresh weights via
  :meth:`push_params` (a device-side snapshot copy + monotonic generation
  bump — the ``ParameterServer.push(to_host=False)`` idiom); every reply
  carries the generation that actually served it, so each transition
  records its behavior-policy version (IMPALA's off-policy lag made
  explicit, arxiv 1802.01561) and the staleness gauge can report lag in
  learner steps;
- **bounded admission**: at ``max_pending`` queued requests new arrivals
  are shed with an immediate reply instead of aging in an unbounded queue
  (``serving.shed_total``), and the client decides to retry or fall back
  to local inference;
- **SLO telemetry**: ``serving.latency_s`` (p50/p95/p99),
  ``serving.batch_occupancy``, ``serving.requests_per_s``, shed/flush
  counters — all on the process registry, exported like every other plane.

Wire protocol (dicts over ``fleet.transport.Connection``, codec v2):

    client->server  {"kind": "act", "req": r, "obs": [B,...],
                     "last_action": [B], "reward": [B], "done": [B],
                     "core": ((c, h), ...)}
                    {"kind": "core_init", "req": r, "batch": B}
    server->client  {"kind": "act_result", "req": r, "action": [B],
                     "logits": [B, A], "core": ((c, h), ...), "gen": g}
                    {"kind": "act_result", "req": r, "shed": True}
                    {"kind": "core_init", "req": r, "core": ...}

Under a dp×mp-sharded learner (``parallel/logical.py``) the pushed params
may be mesh-sharded jax arrays; the jitted serve step consumes them in
place and the trainer's mesh ``dispatch_guard`` (passed at construction)
serializes the multi-device dispatch against the learner's (JG002).
"""

from __future__ import annotations

import threading
import time
from contextlib import nullcontext
from typing import Any, Callable, Dict, List, Optional

import jax
import numpy as np

from scalerl_tpu.fleet.hub import QueueHub
from scalerl_tpu.fleet.transport import (
    Connection,
    SocketConnection,
    accept_connection,
    listen_socket,
)
from scalerl_tpu.runtime import telemetry, tracing
from scalerl_tpu.runtime.dispatch import steady_state_guard
from scalerl_tpu.runtime.param_server import ParamSnapshotPlane
from scalerl_tpu.serving.batcher import (
    DynamicBatcher,
    ServingConfig,
    ServingRequest,
    bucket_for,
)
from scalerl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# chaos sites: serving links are FaultInjector frame-fault sites like every
# other transport link; the "serve" prefix lets a plan scope faults to the
# inference plane (SCALERL_CHAOS "sites=serve")
SERVE_CHAOS_SITE = "serve_sock"

# module seams: tests monkeypatch these to count host transfers and assert
# the one-upload-one-read-per-flush invariant
_device_put = jax.device_put
_device_get = jax.device_get


def _make_serve_fn(model) -> Callable:
    """The batched acting step over the uniform recurrent-policy signature
    — identical math to ``PolicyValueAgent._setup``'s act, rebuilt here so
    the server can hold generation-tagged param snapshots instead of the
    agent's live train state."""

    def serve(params, obs, last_action, reward, done, core_state, key):
        out, new_core = model.apply(
            params, obs[None], last_action[None], reward[None], done[None],
            core_state,
        )
        logits = out.policy_logits[0]
        action = jax.random.categorical(key, logits, axis=-1)
        return action, logits, new_core

    return serve


def _live_param_shardings(agent) -> Any:
    """The learner's per-leaf param ``NamedSharding`` pytree, when the
    agent trains on a mesh with model parallelism (``mp > 1``).

    ``PolicyValueAgent.enable_mesh`` hangs the full train-state sharding
    off the parallel learn fn (``make_parallel_learn_fn``'s
    ``.state_sharding``); the params subtree of that layout is exactly how
    the serve fn should consume pushed snapshots.  Pure-dp meshes return
    None — batch sharding doesn't apply to inference-side params, and the
    unsharded serve path stays byte-identical to the pre-mesh behavior.
    """
    mesh = getattr(agent, "mesh", None)
    if mesh is None or mesh.shape.get("mp", 1) <= 1:
        return None
    state_sharding = getattr(
        getattr(agent, "_learn", None), "state_sharding", None
    )
    return getattr(state_sharding, "params", None)


def _pad_lanes(arr: np.ndarray, bucket: int) -> np.ndarray:
    """Zero-pad a [B, ...] host array up to [bucket, ...]."""
    n = arr.shape[0]
    if n == bucket:
        return arr
    pad = [(0, bucket - n)] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad)


class InferenceServer(ParamSnapshotPlane):
    """Owns one hot jitted policy on device; serves batched act requests.

    ``agent``: any policy-value agent exposing ``.model`` (uniform
    recurrent signature) and ``.get_weights()`` — the initial parameter
    snapshot.  ``dispatch_guard``: a zero-arg context-manager factory
    entered around every device dispatch; the serving trainer passes its
    mesh dispatch guard so the flush thread's programs cannot interleave
    multi-device enqueues with the learner's (graftlint JG002).
    """

    def __init__(
        self,
        agent,
        config: Optional[ServingConfig] = None,
        dispatch_guard: Optional[Callable[[], Any]] = None,
        hub_maxsize: int = 1024,
        param_shardings: Any = None,
    ) -> None:
        self.config = config or ServingConfig()
        self._model = agent.model
        self._serve = jax.jit(_make_serve_fn(agent.model))
        self._dispatch_guard = dispatch_guard or nullcontext
        # mp-sharded learners serve from their LIVE mesh layout: every
        # pushed snapshot is re-placed into the learner's per-leaf
        # NamedShardings, so the jitted serve fn compiles ONE sharded
        # program (GSPMD splits the heads/mlp/vocab matmuls over mp)
        # instead of gathering the policy onto one chip.  mp=1 keeps the
        # unsharded path: param_shardings stays None and snapshots serve
        # wherever the copy landed (ROADMAP serving-headroom item).
        self._param_shardings = (
            param_shardings
            if param_shardings is not None
            else _live_param_shardings(agent)
        )
        # snapshot distribution rides the shared ParamSnapshotPlane idiom
        # (runtime/param_server.py): monotonic generation, device-side
        # copy through the _place hook, bounded gen -> learner-step map
        self._init_param_plane(agent.get_weights())
        self._key = jax.random.PRNGKey(self.config.seed)
        self.batcher = DynamicBatcher(self.config)
        self.hub = QueueHub(
            maxsize=hub_maxsize,
            heartbeat_interval=self.config.heartbeat_interval_s,
            max_pending=self.config.max_pending,
        )
        # a bucket's first flush compiles (host constants legitimately
        # materialize on device); every later flush at that bucket runs
        # under the transfer guard — the JG001 runtime enforcement
        self._warm_buckets: set = set()
        reg = telemetry.get_registry()
        # digest backend: the SLO quantiles must stay honest at unbounded
        # request counts — a 256-sample reservoir's p99 is reservoir bias,
        # not a tail (runtime/attribution.LatencyDigest, ISSUE 20)
        self._lat_hist = reg.histogram("serving.latency_s", backend="digest")
        self._occ_hist = reg.histogram("serving.batch_occupancy")
        self._req_meter = reg.meter("serving.requests_per_s")
        self._req_counter = reg.counter("serving.requests")
        self._flush_counter = reg.counter("serving.flushes")
        self._stale_gauge = reg.gauge("serving.staleness")
        reg.bind(
            "serving.server",
            lambda: {
                "generation": self.generation,
                "connections": self.hub.connection_count(),
                "warm_buckets": len(self._warm_buckets),
            },
        )
        self.flushes = 0
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._listen_sock = None

    def _place(self, snapshot):
        """ParamSnapshotPlane placement hook: re-place a snapshot into the
        learner's live NamedShardings (a device->device reshard at worst,
        never a host transfer — so the serve fn never recompiles against a
        stray placement and never serves an unsharded gather of an
        mp-sharded policy); identity on the mp=1 unsharded path.  Applied
        to full-precision pushes AND the dequant-on-read of a
        ``push_params(quantize=...)`` snapshot (the non-learner replica
        path).  Callers with a live mesh wrap ``push_params`` in their
        dispatch guard."""
        if self._param_shardings is None:
            return snapshot
        return jax.device_put(snapshot, self._param_shardings)

    def observe_staleness(self, served_generation: int) -> float:
        """Lag (in learner steps) between the newest pushed params and the
        generation that served a transition; sets the staleness gauges
        (the plane-local ``serving.staleness`` and the unified
        ``staleness``, one definition everywhere — docs/OBSERVABILITY.md).
        The learner calls this when it consumes a batch, closing the loop:
        generation tags on the acting side become a lag measurement on the
        learning side (the quantity V-trace's rho/c clips absorb)."""
        lag = self.staleness_steps(served_generation)
        self._stale_gauge.set(lag)
        telemetry.observe_staleness(lag, plane="serving")
        return lag

    def slo(self) -> Dict[str, float]:
        """Latency SLO summary in milliseconds (p50/p95/p99) plus mean
        batch occupancy — the dashboard row docs/DISTRIBUTED.md tables."""
        h = self._lat_hist
        occ = self._occ_hist.read()
        return {
            "p50_ms": h.quantile(0.50) * 1e3,
            "p95_ms": h.quantile(0.95) * 1e3,
            "p99_ms": h.quantile(0.99) * 1e3,
            "requests": self._req_counter.value,
            "batch_occupancy_mean": occ["mean"],
        }

    # -- bring-up -------------------------------------------------------
    def start(self, listen_port: Optional[int] = None) -> None:
        self._threads = [
            threading.Thread(target=self._admit_loop, name="serve-admit",
                             daemon=True),
            threading.Thread(target=self._flush_loop, name="serve-flush",
                             daemon=True),
        ]
        if listen_port is not None:
            self._listen_sock = listen_socket(listen_port)
            self._threads.append(
                threading.Thread(
                    target=self._accept_loop, args=(self._listen_sock,),
                    name="serve-accept", daemon=True,
                )
            )
        for t in self._threads:
            t.start()

    def add_connection(self, conn: Connection) -> None:
        """Register an in-process or pre-accepted client link."""
        self.hub.add_connection(conn)

    def stop(self) -> None:
        self._stop.set()
        self.batcher.close()
        if self._listen_sock is not None:
            try:
                self._listen_sock.close()
            except OSError:
                pass
        self.hub.close()
        for t in self._threads:
            t.join(timeout=3.0)

    def _accept_loop(self, sock) -> None:
        while not self._stop.is_set():
            try:
                conn = accept_connection(sock, timeout=0.5)
            except (TimeoutError, OSError):
                continue
            if isinstance(conn, SocketConnection):
                # serving links are chaos-injectable like any transport
                # link, under their own site prefix (sites=serve)
                conn.chaos_site = SERVE_CHAOS_SITE
            self.hub.add_connection(conn)

    # -- admission ------------------------------------------------------
    def _admit_loop(self) -> None:
        import queue as queue_mod

        while not self._stop.is_set():
            try:
                conn, msg = self.hub.recv(timeout=0.2)
            except queue_mod.Empty:
                continue
            try:
                self._admit(conn, msg)
            except Exception:  # noqa: BLE001 — a bad request must not kill admission
                logger.exception("serving: failed handling %r",
                                 msg.get("kind") if isinstance(msg, dict) else msg)

    def _admit(self, conn: Connection, msg: Dict[str, Any]) -> None:
        kind = msg.get("kind")
        if kind == "act":
            obs = np.asarray(msg["obs"])
            req = ServingRequest(
                conn=conn,
                req_id=msg.get("req"),
                lanes=int(obs.shape[0]),
                trace=tracing.extract(msg),
                payload={
                    "obs": obs,
                    "last_action": np.asarray(msg["last_action"], np.int32),
                    "reward": np.asarray(msg["reward"], np.float32),
                    "done": np.asarray(msg["done"], bool),
                    "core": msg.get("core") or (),
                },
            )
            if not self.batcher.submit(req):
                # explicit load shed: answered NOW so the client can retry
                # or fall back locally instead of timing out on silence
                self.hub.send(
                    conn, {"kind": "act_result", "req": req.req_id, "shed": True}
                )
        elif kind == "core_init":
            B = int(msg["batch"])
            with self._dispatch_guard():
                core = _device_get(self._model.initial_state(B))  # cold path
            self.hub.send(
                conn, {"kind": "core_init", "req": msg.get("req"), "core": core}
            )
        elif kind == "health":
            # the router's health poll: SLO quantiles + queue/shed state off
            # instruments that already exist — no device traffic, safe at
            # any load (docs/DISTRIBUTED.md §5 state machine)
            self.hub.send(conn, self._health_reply(msg))
        elif kind == "router_hello":
            # front-door membership announce: ack with identity/generation
            # so the router pins both before the first act lands
            logger.info("serving: router membership announce (%r)",
                        msg.get("req"))
            self.hub.send(
                conn,
                {
                    "kind": "router_hello",
                    "req": msg.get("req"),
                    "gen": self.generation,
                    "host": telemetry.host_id(),
                },
            )
        else:
            logger.warning("serving: unknown message kind %r", kind)

    def _health_reply(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        s = self.slo()
        q = self.batcher.stats()
        return {
            "kind": "health_result",
            "req": msg.get("req"),
            "gen": self.generation,
            "host": telemetry.host_id(),
            "p50_ms": s["p50_ms"],
            "p95_ms": s["p95_ms"],
            "requests": s["requests"],
            "pending": q["pending_requests"],
            "shed_total": q["shed_total"] + self.hub.shed_total,
        }

    # -- the flush hot loop --------------------------------------------
    def _flush_loop(self) -> None:
        while not self._stop.is_set():
            batch = self.batcher.next_batch(poll_s=0.05)
            if batch is None:
                return  # batcher closed
            try:
                self._flush(batch)
            except Exception as e:  # noqa: BLE001 — answer, then keep serving
                logger.exception("serving: flush failed")
                for req in batch:
                    self.hub.send(
                        req.conn,
                        {"kind": "act_result", "req": req.req_id,
                         "error": repr(e)},
                    )

    def _assemble(
        self, batch: List[ServingRequest], bucket: int
    ) -> Dict[str, Any]:
        """Stack requests into ONE [bucket, ...] host pytree (pure numpy —
        no device traffic; the single upload happens in ``_flush``)."""
        cat = {
            k: np.concatenate([r.payload[k] for r in batch], axis=0)
            for k in ("obs", "last_action", "reward", "done")
        }
        host = {k: _pad_lanes(v, bucket) for k, v in cat.items()}
        cores = [r.payload["core"] for r in batch]
        if cores and len(cores[0]):
            host["core"] = tuple(
                tuple(
                    _pad_lanes(
                        np.concatenate([np.asarray(c[i][j]) for c in cores],
                                       axis=0),
                        bucket,
                    )
                    for j in range(2)
                )
                for i in range(len(cores[0]))
            )
        else:
            host["core"] = ()
        return host

    def _flush(self, batch: List[ServingRequest]) -> None:
        lanes = sum(r.lanes for r in batch)
        bucket = bucket_for(lanes, self.batcher.buckets)
        t_flush0 = time.monotonic()
        host = self._assemble(batch, bucket)
        params, gen = self._snapshot_params()
        # steady state is per bucket: the first flush at a shape compiles
        # (constants legitimately materialize); every later one is guarded
        guard = (
            steady_state_guard() if bucket in self._warm_buckets
            else nullcontext()
        )
        with guard:
            with self._dispatch_guard():
                self._key, sub = jax.random.split(self._key)
                # ONE explicit batched host->device upload per flush
                dev = _device_put(
                    (host["obs"], host["last_action"], host["reward"],
                     host["done"], host["core"])
                )
                action, logits, core = self._serve(params, *dev, sub)
                # ... and ONE explicit batched device->host read
                out = _device_get((action, logits, core))
        self._warm_buckets.add(bucket)
        self.flushes += 1
        self._flush_counter.inc()
        self._occ_hist.observe(lanes / max(bucket, 1))
        self._reply(batch, out, gen, t_flush0, bucket)

    def _reply(
        self,
        batch: List[ServingRequest],
        out,
        gen: int,
        t_flush0: float = 0.0,
        bucket: int = 0,
    ) -> None:
        """Demux the flushed [bucket, ...] outputs back to per-request
        slices; every reply is tagged with the generation that served it
        (an in-flight push bumps ``self.generation`` but never this tag)."""
        host_action, host_logits, host_core = out
        offset = 0
        now = time.monotonic()
        for req in batch:
            sl = slice(offset, offset + req.lanes)
            offset += req.lanes
            core_slice = tuple(
                (np.asarray(c)[sl], np.asarray(h)[sl]) for c, h in host_core
            )
            self._lat_hist.observe(max(now - req.t_enqueue, 0.0))
            self._req_counter.inc()
            self._req_meter.mark()
            if req.trace is not None:
                # lifecycle edges off stamps the flush already took:
                # batcher dwell, then the whole assemble+device round trip
                # (one span per FLUSH membership, never per lane)
                tracing.record_span(
                    "serve.queue_wait", parent=req.trace,
                    t_start=req.t_enqueue, t_end=t_flush0, kind="serving",
                )
                tracing.record_span(
                    "serve.flush", parent=req.trace, t_start=t_flush0,
                    t_end=now, kind="serving", lanes=req.lanes,
                    bucket=bucket, gen=gen,
                )
            self.hub.send(
                req.conn,
                {
                    "kind": "act_result",
                    "req": req.req_id,
                    "action": np.asarray(host_action)[sl],
                    "logits": np.asarray(host_logits)[sl],
                    "core": core_slice,
                    "gen": gen,
                },
            )
