"""RemotePolicyClient: the thin env-shell worker's view of the inference plane.

Implements the same acting facade the actor planes already consume
(``act(obs, last_action, reward, done, core_state)`` + ``initial_state``),
but every neural-net forward happens on the central
:class:`~scalerl_tpu.serving.server.InferenceServer` — the worker keeps
only envs and numpy buffers (SEED-RL's thin-actor shape).  jax-free by
design: importing this in a spawned env-shell process costs pennies.

Robustness contract (rides PR 2's vocabulary):

- **pipelined async request/response** over ONE connection: requests carry
  ids, a background reader demuxes replies, so multiple actor threads share
  a single uplink and a request can be in flight while the caller prepares
  the next one (``act_async``/``PendingReply``);
- **reconnect with capped exponential backoff** on a lost/corrupt link
  (``supervisor.exp_backoff``; a chaos bit-flip surfaces as
  ``ProtocolError`` -> the server drops the link -> the client redials and
  resends the in-flight request — at-least-once acting, harmless because
  inference has no side effects);
- **local fallback**: when the reconnect budget is exhausted (or the
  server sheds under load and a fallback policy was provided), the client
  flips to local inference instead of stalling the env loop — the worker
  degrades to the pre-serving topology, it does not die;
- **capped-backoff re-probe out of degraded mode**: a fallen-back client
  periodically redials (one cheap connect attempt per window, never a
  blocking loop) so a recovered or router-re-admitted server gets its
  clients back — degraded mode is a state, not a one-way door.

Every reply carries the parameter ``generation`` that served it; the
client exposes the newest one (``.generation``) so the trainer can record
per-transition behavior-policy versions and a staleness gauge.
"""

from __future__ import annotations

import itertools
import threading
import time
from contextlib import nullcontext
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from scalerl_tpu.fleet.transport import Connection
from scalerl_tpu.runtime import telemetry, tracing
from scalerl_tpu.runtime.supervisor import exp_backoff, is_heartbeat, make_pong
from scalerl_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class ServingUnavailable(ConnectionError):
    """The server is unreachable and no local fallback was configured."""


class PendingReply:
    """A demuxed in-flight request: ``result()`` blocks for the reply."""

    __slots__ = ("req_id", "_event", "_reply", "link_epoch")

    def __init__(self, req_id: int, link_epoch: int) -> None:
        self.req_id = req_id
        self.link_epoch = link_epoch
        self._event = threading.Event()
        self._reply: Optional[Dict[str, Any]] = None

    def deliver(self, reply: Optional[Dict[str, Any]]) -> None:
        self._reply = reply
        self._event.set()

    def done(self) -> bool:
        """Non-blocking: has a reply (or a link-loss verdict) landed?
        Poll-harvest callers (the traffic replay) sweep thousands of these
        without parking a thread per request."""
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> Dict[str, Any]:
        if not self._event.wait(timeout):
            raise TimeoutError(f"no reply for request {self.req_id}")
        if self._reply is None:
            raise ConnectionError("serving link lost while request in flight")
        return self._reply


def _as_core(core) -> Tuple:
    """Normalize a codec-decoded core payload to a tuple of (c, h) pairs."""
    if not core:
        return ()
    return tuple((np.asarray(pair[0]), np.asarray(pair[1])) for pair in core)


class RemotePolicyClient:
    """Acting facade over a serving connection, with reconnect + fallback.

    ``conn``: an established :class:`Connection` (in-process pipe pair or a
    pre-dialed socket).  ``connect``: zero-arg factory producing a fresh
    connection — the reconnect path; without it a lost link goes straight
    to the fallback (in-process pipes cannot be redialed).  ``fallback``:
    an object with the same ``act``/``initial_state`` facade (typically the
    local agent) used when the server is unreachable or sheds.
    """

    # duck-typing marker: trainers skip their mesh dispatch guard around a
    # remote act (it is host IO — holding the mesh lock across a network
    # round trip would serialize the learner against network latency)
    _remote_policy = True

    def __init__(
        self,
        conn: Optional[Connection] = None,
        connect: Optional[Callable[[], Connection]] = None,
        fallback: Any = None,
        request_timeout_s: float = 30.0,
        max_reconnects: int = 5,
        reconnect_backoff_s: float = 0.2,
        reconnect_backoff_cap_s: float = 2.0,
        max_attempts: int = 8,
        dispatch_guard: Optional[Callable[[], Any]] = None,
        reprobe_backoff_s: float = 0.5,
        reprobe_backoff_cap_s: float = 30.0,
        reprobe_jitter: bool = False,
        reprobe_rng: Any = None,
    ) -> None:
        """``dispatch_guard``: context-manager factory entered around the
        LOCAL fallback policy's dispatch (the remote path never needs it);
        serving trainers pass their mesh guard so a degraded client cannot
        interleave multi-device enqueues with the learner.

        ``reprobe_backoff_s``/``reprobe_backoff_cap_s``: the capped
        schedule on which a fallen-back client redials the server
        (``reprobe_backoff_s <= 0`` disables re-probing — the pre-fix
        latch).  ``reprobe_jitter`` opts the schedule into decorrelated
        jitter (``exp_backoff``) so a whole fleet of degraded clients does
        not redial a recovering server in one synchronized storm; default
        off for determinism-pinned tests, ``reprobe_rng`` pins the draw."""
        if conn is None and connect is None:
            raise ValueError("need a connection or a connect factory")
        self._connect = connect
        self._fallback = fallback
        self._guard = dispatch_guard or nullcontext
        self.request_timeout_s = request_timeout_s
        self.max_reconnects = max_reconnects
        self.reconnect_backoff_s = reconnect_backoff_s
        self.reconnect_backoff_cap_s = reconnect_backoff_cap_s
        self.max_attempts = max_attempts
        self.reprobe_backoff_s = reprobe_backoff_s
        self.reprobe_backoff_cap_s = reprobe_backoff_cap_s
        self.reprobe_jitter = reprobe_jitter
        self._reprobe_rng = reprobe_rng
        self.reprobes_used = 0
        self._next_probe_t = 0.0
        self.reconnects_used = 0
        self.fallen_back = False
        self.generation = 0  # newest param generation seen in a reply
        self._ids = itertools.count(1)
        self._send_lock = threading.Lock()
        self._link_lock = threading.Lock()
        self._link_epoch = 0
        self._waiters: Dict[int, PendingReply] = {}
        self._waiters_lock = threading.Lock()
        self._closed = threading.Event()
        self._reg = telemetry.get_registry()
        self._conn = conn if conn is not None else connect()
        self._reader = self._start_reader()

    # -- link plumbing --------------------------------------------------
    def _start_reader(self) -> threading.Thread:
        t = threading.Thread(
            target=self._read_loop,
            args=(self._conn, self._link_epoch),
            name="serve-client-reader",
            daemon=True,
        )
        t.start()
        return t

    def _read_loop(self, conn: Connection, epoch: int) -> None:
        while not self._closed.is_set():
            try:
                msg = conn.recv(timeout=0.2)
            except TimeoutError:
                continue
            except (ConnectionError, EOFError, OSError, ValueError):
                # includes ProtocolError (a chaos bit-flip on the downlink):
                # the stream is desynchronized, fail every in-flight waiter
                # so their attempt loops redial and resend
                self._fail_waiters(epoch)
                return
            if is_heartbeat(msg):
                if isinstance(msg, dict) and msg.get("kind") == "ping":
                    try:
                        with self._send_lock:
                            conn.send(make_pong(msg))
                    except (ConnectionError, OSError):
                        self._fail_waiters(epoch)
                        return
                continue
            if not isinstance(msg, dict):
                continue
            waiter = None
            with self._waiters_lock:
                waiter = self._waiters.pop(msg.get("req"), None)
            if waiter is not None:
                waiter.deliver(msg)
            # replies for abandoned requests (a retried act whose original
            # answer arrived late) are dropped here — harmless duplicates

    def _fail_waiters(self, epoch: int) -> None:
        with self._waiters_lock:
            waiters, self._waiters = dict(self._waiters), {}
        for w in waiters.values():
            if w.link_epoch <= epoch:
                w.deliver(None)

    def _revive_link(self, seen_epoch: int, why: BaseException) -> None:
        """Replace a dead link (one winner; racers adopt the result).

        Exhausted budget or no factory -> flip to the local fallback when
        one exists, else raise :class:`ServingUnavailable`.
        """
        with self._link_lock:
            if self._closed.is_set():
                # shutdown, not failure: callers route to the fallback
                # without flipping the degraded-mode flag or redialing
                raise ServingUnavailable("client closed")
            if self.fallen_back:
                return
            if self._link_epoch != seen_epoch:
                return  # another thread already revived the link
            try:
                self._conn.close()
            except Exception:  # noqa: BLE001 — link already broken
                pass
            last: BaseException = why
            while (
                self._connect is not None
                and self.reconnects_used < self.max_reconnects
            ):
                delay = exp_backoff(
                    self.reconnects_used,
                    self.reconnect_backoff_s,
                    self.reconnect_backoff_cap_s,
                )
                self.reconnects_used += 1
                self._reg.counter("serving_client.reconnects").inc()
                telemetry.record_event(
                    "serving_reconnect",
                    attempt=self.reconnects_used,
                    why=repr(why),
                )
                logger.warning(
                    "serving client: link lost (%r); redialing in %.2fs "
                    "(attempt %d/%d)",
                    why, delay, self.reconnects_used, self.max_reconnects,
                )
                time.sleep(delay)
                try:
                    self._conn = self._connect()
                    self._link_epoch += 1
                    self._reader = self._start_reader()
                    return
                except (ConnectionError, OSError) as e:
                    last = e
            if self._fallback is not None:
                self.fallen_back = True
                self._schedule_reprobe()
                self._reg.counter("serving_client.fallbacks").inc()
                telemetry.record_event("serving_fallback", why=repr(last))
                logger.error(
                    "serving client: server unreachable (%r); falling back "
                    "to LOCAL inference", last,
                )
                return
            raise ServingUnavailable(
                f"inference server unreachable after "
                f"{self.reconnects_used} reconnect attempts"
            ) from last

    def _schedule_reprobe(self) -> None:
        """Arm the next degraded-mode redial on the capped schedule."""
        if self.reprobe_backoff_s <= 0 or self._connect is None:
            self._next_probe_t = float("inf")
            return
        self._next_probe_t = time.monotonic() + exp_backoff(
            self.reprobes_used,
            self.reprobe_backoff_s,
            self.reprobe_backoff_cap_s,
            jitter=self.reprobe_jitter,
            rng=self._reprobe_rng,
        )

    def _maybe_reprobe(self) -> bool:
        """Fallen back + the probe window passed: ONE redial attempt (a
        cheap connect, never a blocking retry loop — the env loop stays on
        the local fallback until a probe lands).  Success re-arms the
        remote path with a fresh reconnect budget; failure re-schedules on
        the capped backoff.  Returns True when remote service resumed."""
        if not self.fallen_back or self._connect is None:
            return False
        if self.reprobe_backoff_s <= 0:
            return False
        if time.monotonic() < self._next_probe_t:
            return False
        with self._link_lock:
            if not self.fallen_back or self._closed.is_set():
                return False
            if time.monotonic() < self._next_probe_t:
                return False  # another thread probed while we waited
            self.reprobes_used += 1
            self._reg.counter("serving_client.reprobes").inc()
            try:
                conn = self._connect()
            except (ConnectionError, OSError) as e:
                self._schedule_reprobe()
                telemetry.record_event(
                    "serving_reprobe", ok=False,
                    attempt=self.reprobes_used, why=repr(e),
                )
                return False
            try:
                self._conn.close()
            except Exception:  # noqa: BLE001 — old link already dead
                pass
            self._conn = conn
            self._link_epoch += 1
            self._reader = self._start_reader()
            self.fallen_back = False
            self.reconnects_used = 0  # recovered link earns a fresh budget
            self._next_probe_t = 0.0
        telemetry.record_event(
            "serving_reprobe", ok=True, attempt=self.reprobes_used
        )
        logger.info(
            "serving client: re-probe succeeded after %d attempt(s); "
            "resuming REMOTE inference", self.reprobes_used,
        )
        return True

    # -- request plumbing ----------------------------------------------
    def _submit(self, msg: Dict[str, Any]) -> PendingReply:
        req_id = next(self._ids)
        msg["req"] = req_id
        with self._link_lock:
            epoch = self._link_epoch
            conn = self._conn
        waiter = PendingReply(req_id, epoch)
        with self._waiters_lock:
            self._waiters[req_id] = waiter
        try:
            with self._send_lock:
                conn.send(msg)
        except (ConnectionError, OSError) as e:
            with self._waiters_lock:
                self._waiters.pop(req_id, None)
            self._revive_link(epoch, e)
            raise ConnectionError("send failed; link revived or fallen back") from e
        return waiter

    def _rpc(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        """Send + wait with redial-and-resend; honors shed replies."""
        shed_seen = 0
        for attempt in range(self.max_attempts):
            if self.fallen_back:
                raise ServingUnavailable("client has fallen back to local")
            if self._closed.is_set():
                raise ServingUnavailable("client closed")
            with self._link_lock:
                epoch = self._link_epoch
            waiter = None
            try:
                waiter = self._submit(dict(msg))
                reply = waiter.result(timeout=self.request_timeout_s)
            except (ConnectionError, TimeoutError, OSError) as e:
                if waiter is not None:  # abandoned: drop the demux slot
                    with self._waiters_lock:
                        self._waiters.pop(waiter.req_id, None)
                self._reg.counter("serving_client.retries").inc()
                self._revive_link(epoch, e)
                continue
            if reply.get("shed"):
                # explicit load shed: bounded admission pushed back — yield
                # briefly so the batcher drains, then retry (the fallback
                # covers sustained overload via shed_to_fallback_after)
                shed_seen += 1
                self._reg.counter("serving_client.sheds").inc()
                if self._fallback is not None and shed_seen >= 3:
                    return {"use_fallback": True}
                time.sleep(0.002 * shed_seen)
                continue
            if "error" in reply:
                self._reg.counter("serving_client.errors").inc()
                raise RuntimeError(f"serving error: {reply['error']}")
            # the req-id demux matched, but verify the frame kind too: a
            # stale or mis-routed reply must not be parsed as a result.
            # "act" requests come back as "act_result"; every other RPC
            # echoes its request kind on the reply
            got = reply.get("kind")
            if got is not None and got not in ("act_result", msg.get("kind")):
                self._reg.counter("serving_client.kind_mismatch").inc()
                continue
            return reply
        if self._fallback is not None:
            return {"use_fallback": True}
        raise ServingUnavailable(
            f"no reply after {self.max_attempts} attempts"
        )

    # -- the acting facade ---------------------------------------------
    def initial_state(self, batch_size: int):
        if self.fallen_back:
            self._maybe_reprobe()
        if self.fallen_back and self._fallback is not None:
            return self._fallback.initial_state(batch_size)
        try:
            reply = self._rpc({"kind": "core_init", "batch": int(batch_size)})
        except ServingUnavailable:
            if self._fallback is None:
                raise
            with self._guard():
                return self._fallback.initial_state(batch_size)
        if reply.get("use_fallback"):
            with self._guard():
                return self._fallback.initial_state(batch_size)
        return _as_core(reply.get("core"))

    def act_async(self, obs, last_action, reward, done, core_state) -> PendingReply:
        """Fire one act request without waiting (pipelined callers)."""
        return self._submit(self._act_msg(obs, last_action, reward, done,
                                          core_state))

    def _act_msg(self, obs, last_action, reward, done, core_state) -> Dict:
        return {
            "kind": "act",
            "obs": np.asarray(obs),
            "last_action": np.asarray(last_action, np.int32),
            "reward": np.asarray(reward, np.float32),
            "done": np.asarray(done, bool),
            "core": tuple(
                (np.asarray(c), np.asarray(h)) for c, h in core_state
            ),
        }

    def act(self, obs, last_action, reward, done, core_state):
        """Central batched inference with the local facade's signature:
        returns ``(action, logits, new_core)`` as host numpy."""
        if self.fallen_back:
            # degraded mode is not a one-way door: past the probe window,
            # one cheap redial per act decides whether remote resumes
            self._maybe_reprobe()
        if not self.fallen_back:
            self._reg.counter("serving_client.requests").inc()
            # head-sampled request trace: the context rides the act frame
            # (the ``trace`` wire key) so the server's queue-wait/flush
            # spans land in the same trace as this end-to-end span
            span = tracing.start_span("serve.request", kind="serving")
            msg = self._act_msg(obs, last_action, reward, done, core_state)
            tracing.inject(msg, span)
            try:
                reply = self._rpc(msg)
            except ServingUnavailable:
                span.end(outcome="unavailable")
                if self._fallback is None:
                    raise
                reply = {"use_fallback": True}
            if not reply.get("use_fallback"):
                # max-fold: mid-rollout a multi-replica front door serves
                # mixed generations; the client-observed one stays monotonic
                self.generation = max(
                    self.generation, int(reply.get("gen", self.generation))
                )
                span.end(gen=self.generation)
                return (
                    np.asarray(reply["action"]),
                    np.asarray(reply["logits"]),
                    _as_core(reply.get("core")),
                )
            span.end(outcome="fallback")
        # degraded mode: local inference on the fallback policy keeps the
        # env loop alive (the pre-serving topology); guarded — under a mesh
        # this is a multi-device dispatch racing the learner's
        with self._guard():
            return self._fallback.act(obs, last_action, reward, done, core_state)

    def close(self) -> None:
        self._closed.set()
        try:
            self._conn.close()
        except Exception:  # noqa: BLE001 — teardown
            pass
        # wake every blocked waiter NOW: the reader may exit via its stop
        # check without ever seeing the closed fd
        self._fail_waiters(self._link_epoch)
