"""Return / advantage computations as ``lax.scan``s over the time axis.

Covers the reference's temporal math:
- per-step discounted returns (``scalerl/hpc/generation.py:143-147`` and the
  A3C rollout return, ``parallel_a3c.py:251-262``) -> ``discounted_returns``;
- n-step reward folding done incrementally by ``MultiStepReplayBuffer``
  (``scalerl/data/replay_buffer.py:230-273``) -> ``n_step_returns`` computes
  the same (reward, n-step-done, index-of-next-state) quantities over a
  whole ``[T, B]`` trajectory in one scan;
- GAE (not in the reference, standard for the A2C runtime) -> ``gae_advantages``.

All functions are time-major ``[T, B]`` and jit/grad-safe.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def discounted_returns(
    rewards: jnp.ndarray,
    discounts: jnp.ndarray,
    bootstrap_value: jnp.ndarray,
) -> jnp.ndarray:
    """R_t = r_t + discount_t * R_{t+1}, seeded with the bootstrap value.

    Args:
      rewards: [T, B].
      discounts: [T, B] (gamma * (1 - done)).
      bootstrap_value: [B].
    """

    def backward(acc, xs):
        r_t, d_t = xs
        acc = r_t + d_t * acc
        return acc, acc

    _, returns = jax.lax.scan(backward, bootstrap_value, (rewards, discounts), reverse=True)
    return returns


def n_step_returns(
    rewards: jnp.ndarray,
    dones: jnp.ndarray,
    values_tpn: jnp.ndarray,
    gamma: float,
    n: int,
) -> jnp.ndarray:
    """Truncated n-step returns with episode-boundary masking.

    With k_eff(t) = min(n, T - t) (the window truncates at the rollout end):

    G_t = sum_{k=0}^{k_eff-1} gamma^k r_{t+k} * prod_{j<k}(1-d_{t+j})
          + gamma^{k_eff} * prod_{j<k_eff}(1-d_{t+j}) * values_tpn[t]

    Args:
      rewards: [T, B].
      dones: [T, B] episode-termination flags.
      values_tpn: [T, B] bootstrap values, ``values_tpn[t] = V(x_{min(t+n, T)})``
        (callers build this by shifting a [T+1] value sequence and clamping the
        index at T); only consumed where no done occurred inside the window.
      gamma: scalar discount.
      n: number of steps.
    """
    T = rewards.shape[0]
    cont = 1.0 - dones.astype(rewards.dtype)

    acc_r = jnp.zeros_like(rewards)
    alive = jnp.ones_like(rewards)
    for k in range(n):
        # reward at t+k (zero past the rollout end), masked by survival
        # through steps t..t+k-1; padding cont with ones keeps the bootstrap
        # alive for the truncated tail (only real dones kill it).
        r_k = jnp.concatenate([rewards[k:], jnp.zeros((k,) + rewards.shape[1:], rewards.dtype)], axis=0)[:T]
        acc_r = acc_r + (gamma**k) * alive * r_k
        c_k = jnp.concatenate([cont[k:], jnp.ones((k,) + cont.shape[1:], cont.dtype)], axis=0)[:T]
        alive = alive * c_k
    k_eff = jnp.minimum(n, T - jnp.arange(T))
    gamma_eff = (gamma ** k_eff).astype(rewards.dtype)
    gamma_eff = gamma_eff.reshape((T,) + (1,) * (rewards.ndim - 1))
    return acc_r + gamma_eff * alive * values_tpn


def gae_advantages(
    rewards: jnp.ndarray,
    discounts: jnp.ndarray,
    values: jnp.ndarray,
    bootstrap_value: jnp.ndarray,
    lambda_: float = 0.95,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Generalized advantage estimation.

    A_t = delta_t + discount_t * lambda * A_{t+1},
    delta_t = r_t + discount_t * V_{t+1} - V_t.

    Returns (advantages [T, B], value targets vs = A + V).
    """
    values_t_plus_1 = jnp.concatenate([values[1:], bootstrap_value[None]], axis=0)
    deltas = rewards + discounts * values_t_plus_1 - values

    def backward(acc, xs):
        delta_t, d_t = xs
        acc = delta_t + d_t * lambda_ * acc
        return acc, acc

    _, advantages = jax.lax.scan(
        backward,
        jnp.zeros_like(bootstrap_value),
        (deltas, discounts),
        reverse=True,
    )
    return advantages, advantages + values
