"""Fused V-trace targets as a single Pallas kernel (scan-free recursion).

The reference implementation (``ops/vtrace.py``) runs the backward-time
recursion ``acc_t = delta_t + discount_t * c_t * acc_{t+1}`` as a
``lax.scan(reverse=True)`` — T sequential XLA loop steps, each paying loop
overhead around a [B]-wide vector op, with the rho/c clipping and the two
delta/advantage passes as separate fused regions around it.  This kernel
fuses the WHOLE computation — exp, clipping, deltas, the backward
recursion, and the policy-gradient advantages — into one Pallas program:
the [T, B] planes live in VMEM end to end and the recursion is a
``fori_loop`` of VPU row ops with no loop-carried HBM traffic.

Numerics: every arithmetic step matches the reference op exactly (same
order, same f32), so the interpret-mode CPU fallback agrees with
``vtrace_from_importance_weights`` to float32 round-off — asserted at
1e-5 in ``tests/test_ops.py``.  Gradients never flow through V-trace (the
reference ``stop_gradient``s its outputs, matching the torch
``no_grad``), so the kernel needs no VJP rule; inputs are detached before
the call to keep AD from tracing into it.

Selection: ``RLArguments.use_pallas`` routes ``agents/impala.py``'s loss
through :func:`vtrace_from_importance_weights_pallas`; ``interpret=None``
auto-resolves to interpreter mode off-TPU so the same flag works in CPU
tests and TPU runs.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp


def _vtrace_kernel(
    log_rhos_ref,
    discounts_ref,
    rewards_ref,
    values_ref,
    bootstrap_ref,
    vs_ref,
    pg_ref,
    acc_scratch,
    rho_clip: Optional[float],
    pg_rho_clip: Optional[float],
    c_clip: float,
):
    """One grid step: the full [T, B] V-trace computation in VMEM."""
    T = log_rhos_ref.shape[0]

    rhos = jnp.exp(log_rhos_ref[:])
    clipped_rhos = jnp.minimum(rho_clip, rhos) if rho_clip is not None else rhos
    cs = jnp.minimum(c_clip, rhos)

    values = values_ref[:]
    boot = bootstrap_ref[0, :]  # [B]
    discounts = discounts_ref[:]
    rewards = rewards_ref[:]

    # V(x_{t+1}) with the bootstrap in the last row.
    values_t_plus_1 = jnp.concatenate([values[1:], boot[None]], axis=0)
    deltas = clipped_rhos * (rewards + discounts * values_t_plus_1 - values)
    disc_cs = discounts * cs

    # Backward recursion, scan-free: rows are read/written through the
    # scratch refs so the time index stays a cheap VMEM dynamic slice.
    acc_scratch[0, :] = deltas
    acc_scratch[1, :] = disc_cs

    def backward(i, acc):
        t = T - 1 - i
        acc = acc_scratch[0, t, :] + acc_scratch[1, t, :] * acc
        vs_ref[t, :] = acc  # vs_minus_v for now; +values below
        return acc

    jax.lax.fori_loop(0, T, backward, jnp.zeros_like(boot))

    vs = vs_ref[:] + values
    vs_ref[:] = vs

    # Policy-gradient advantages: r + gamma * vs_{t+1} - V(x_t).
    vs_t_plus_1 = jnp.concatenate([vs[1:], boot[None]], axis=0)
    if pg_rho_clip is not None:
        clipped_pg_rhos = jnp.minimum(pg_rho_clip, rhos)
    else:
        clipped_pg_rhos = rhos
    pg_ref[:] = clipped_pg_rhos * (rewards + discounts * vs_t_plus_1 - values)


def vtrace_from_importance_weights_pallas(
    log_rhos: jnp.ndarray,
    discounts: jnp.ndarray,
    rewards: jnp.ndarray,
    values: jnp.ndarray,
    bootstrap_value: jnp.ndarray,
    clip_rho_threshold: Optional[float] = 1.0,
    clip_pg_rho_threshold: Optional[float] = 1.0,
    clip_c_threshold: float = 1.0,
    interpret: Optional[bool] = None,
):
    """Drop-in fused replacement for
    ``ops.vtrace.vtrace_from_importance_weights``.

    ``interpret=None`` resolves to ``True`` off-TPU (pure-Python Pallas
    interpreter — the CPU fallback the parity tests run) and ``False`` on
    TPU (compiled Mosaic kernel).
    """
    import jax.experimental.pallas as pl

    from scalerl_tpu.ops.vtrace import VTraceOutput

    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # Gradients never flow through V-trace (outputs are stop_gradient-ed,
    # reference contract) — detach the inputs so AD never needs a VJP rule
    # for the pallas_call.
    log_rhos, discounts, rewards, values, bootstrap_value = map(
        jax.lax.stop_gradient,
        (log_rhos, discounts, rewards, values, bootstrap_value),
    )

    T, B = log_rhos.shape
    f32 = partial(jnp.asarray, dtype=jnp.float32)
    kernel = partial(
        _vtrace_kernel,
        rho_clip=(
            float(clip_rho_threshold) if clip_rho_threshold is not None else None
        ),
        pg_rho_clip=(
            float(clip_pg_rho_threshold)
            if clip_pg_rho_threshold is not None
            else None
        ),
        c_clip=float(clip_c_threshold),
    )
    vs, pg = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((T, B), jnp.float32),
            jax.ShapeDtypeStruct((T, B), jnp.float32),
        ),
        scratch_shapes=[
            # [deltas; discounts*cs] rows for the recursion's dynamic reads
            _vmem_scratch((2, T, B), interpret),
        ],
        interpret=interpret,
    )(
        f32(log_rhos),
        f32(discounts),
        f32(rewards),
        f32(values),
        f32(bootstrap_value)[None, :],  # [1, B]: keep every operand 2D+
    )
    return VTraceOutput(
        vs=jax.lax.stop_gradient(vs),
        pg_advantages=jax.lax.stop_gradient(pg),
    )


def _vmem_scratch(shape, interpret: bool):
    """A VMEM scratch allocation that also works under the interpreter on
    backends without the TPU plugin (plain pltpu.VMEM is fine on both, but
    import it lazily so jax-free consumers never pull Pallas)."""
    from jax.experimental.pallas import tpu as pltpu

    del interpret  # pltpu.VMEM works in both compiled and interpret modes
    return pltpu.VMEM(shape, jnp.float32)
