from scalerl_tpu.ops.losses import (  # noqa: F401
    baseline_loss,
    c51_loss,
    categorical_projection,
    categorical_q_values,
    double_dqn_targets,
    dqn_loss,
    entropy_loss,
    make_support,
    policy_gradient_loss,
)
from scalerl_tpu.ops.pallas_attention import flash_attention  # noqa: F401
from scalerl_tpu.ops.pallas_paged_attention import (  # noqa: F401
    make_paged_attn_fn,
    paged_attention_reference,
    paged_decode_attention,
    resolve_paged_attn,
)
from scalerl_tpu.ops.ring_attention import (  # noqa: F401
    full_attention,
    make_ring_attention_fn,
    ring_attention,
)
from scalerl_tpu.ops.returns import (  # noqa: F401
    discounted_returns,
    gae_advantages,
    n_step_returns,
)
from scalerl_tpu.ops.vtrace import (  # noqa: F401
    VTraceOutput,
    vtrace_from_importance_weights,
    vtrace_from_logits,
)
