"""V-trace off-policy actor-critic targets (IMPALA) as a reverse ``lax.scan``.

Functional parity with the reference's torch implementation
(``scalerl/algorithms/impala/vtrace.py:43-172``):

- ``from_logits`` computes behavior/target action log-probs from logits, then
  defers to ``from_importance_weights`` (reference ``vtrace.py:43-76``).
- ``from_importance_weights`` clips the importance weights (rho-hat, c-hat),
  forms temporal-difference deltas, and runs the reverse-time recursion
  ``acc_t = delta_t + discount_t * c_t * acc_{t+1}`` to get ``vs``
  (reference's Python loop at ``vtrace.py:149-155`` becomes
  ``lax.scan(reverse=True)``), then the clipped policy-gradient advantages
  (``vtrace.py:160-166``).

All inputs are time-major ``[T, B, ...]`` (the universal trajectory layout,
see SURVEY.md §7).  Everything here is pure and jit/vmap/grad-safe; the
caller decides where to ``stop_gradient`` (the reference computes V-trace
under ``torch.no_grad``, so callers should treat the returned targets as
constants — both exported functions apply ``stop_gradient`` to their outputs).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp


class VTraceOutput(NamedTuple):
    vs: jnp.ndarray  # [T, B] V-trace value targets
    pg_advantages: jnp.ndarray  # [T, B] clipped policy-gradient advantages


def action_log_probs(logits: jnp.ndarray, actions: jnp.ndarray) -> jnp.ndarray:
    """log pi(a|s) from unnormalised logits, any leading batch dims."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    return jnp.take_along_axis(logp, actions[..., None], axis=-1).squeeze(-1)


def vtrace_from_importance_weights(
    log_rhos: jnp.ndarray,
    discounts: jnp.ndarray,
    rewards: jnp.ndarray,
    values: jnp.ndarray,
    bootstrap_value: jnp.ndarray,
    clip_rho_threshold: Optional[float] = 1.0,
    clip_pg_rho_threshold: Optional[float] = 1.0,
    clip_c_threshold: float = 1.0,
    impl: str = "scan",
) -> VTraceOutput:
    """Compute V-trace targets from log importance weights.

    Args:
      log_rhos: [T, B] log(pi_target(a)/pi_behavior(a)).
      discounts: [T, B] per-step discount (gamma * (1 - done)).
      rewards: [T, B].
      values: [T, B] value estimates V(x_t) under the target policy.
      bootstrap_value: [B] V(x_T).
      clip_rho_threshold: rho-hat clip (None = no clipping).
      clip_pg_rho_threshold: clip for the pg-advantage rhos (None = none).
      clip_c_threshold: c-hat clip.
      impl: ``"scan"`` (this reference op, reverse ``lax.scan``) or
        ``"pallas"`` (the fused kernel, ``ops/pallas_vtrace.py`` —
        interpreter-mode off-TPU; selected by ``RLArguments.use_pallas``).
    """
    if impl == "pallas":
        from scalerl_tpu.ops.pallas_vtrace import (
            vtrace_from_importance_weights_pallas,
        )

        return vtrace_from_importance_weights_pallas(
            log_rhos, discounts, rewards, values, bootstrap_value,
            clip_rho_threshold=clip_rho_threshold,
            clip_pg_rho_threshold=clip_pg_rho_threshold,
            clip_c_threshold=clip_c_threshold,
        )
    if impl != "scan":
        raise ValueError(f"impl must be 'scan' or 'pallas', got {impl!r}")
    rhos = jnp.exp(log_rhos)
    clipped_rhos = jnp.minimum(clip_rho_threshold, rhos) if clip_rho_threshold is not None else rhos
    cs = jnp.minimum(clip_c_threshold, rhos)

    # V(x_{t+1}) with bootstrap at the end.
    values_t_plus_1 = jnp.concatenate([values[1:], bootstrap_value[None]], axis=0)
    deltas = clipped_rhos * (rewards + discounts * values_t_plus_1 - values)

    def backward(acc: jnp.ndarray, xs):
        delta_t, discount_t, c_t = xs
        acc = delta_t + discount_t * c_t * acc
        return acc, acc

    _, vs_minus_v = jax.lax.scan(
        backward,
        jnp.zeros_like(bootstrap_value),
        (deltas, discounts, cs),
        reverse=True,
    )
    vs = vs_minus_v + values

    # Advantage for the policy gradient: r + gamma * vs_{t+1} - V(x_t).
    vs_t_plus_1 = jnp.concatenate([vs[1:], bootstrap_value[None]], axis=0)
    if clip_pg_rho_threshold is not None:
        clipped_pg_rhos = jnp.minimum(clip_pg_rho_threshold, rhos)
    else:
        clipped_pg_rhos = rhos
    pg_advantages = clipped_pg_rhos * (rewards + discounts * vs_t_plus_1 - values)

    return VTraceOutput(
        vs=jax.lax.stop_gradient(vs),
        pg_advantages=jax.lax.stop_gradient(pg_advantages),
    )


def vtrace_from_logits(
    behavior_logits: jnp.ndarray,
    target_logits: jnp.ndarray,
    actions: jnp.ndarray,
    discounts: jnp.ndarray,
    rewards: jnp.ndarray,
    values: jnp.ndarray,
    bootstrap_value: jnp.ndarray,
    clip_rho_threshold: Optional[float] = 1.0,
    clip_pg_rho_threshold: Optional[float] = 1.0,
    clip_c_threshold: float = 1.0,
    impl: str = "scan",
) -> VTraceOutput:
    """V-trace from behavior/target policy logits ([T, B, A]) and actions ([T, B])."""
    log_rhos = action_log_probs(target_logits, actions) - action_log_probs(
        behavior_logits, actions
    )
    return vtrace_from_importance_weights(
        log_rhos=log_rhos,
        discounts=discounts,
        rewards=rewards,
        values=values,
        bootstrap_value=bootstrap_value,
        clip_rho_threshold=clip_rho_threshold,
        clip_pg_rho_threshold=clip_pg_rho_threshold,
        clip_c_threshold=clip_c_threshold,
        impl=impl,
    )
