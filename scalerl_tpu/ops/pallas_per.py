"""Hierarchical prioritized-replay sampling: XLA two-level + Pallas kernel.

SURVEY.md §7 called cumsum-over-capacity "plan A" and a Pallas path "plan B
if this ever dominates the profile".  Both live here:

- :func:`hierarchical_sample` (XLA, any backend): split the priority plane
  into blocks; a tiny block-sum cumsum picks each sample's block, then only
  the selected blocks (``[S, block]``) are scanned — O(N + S·block) instead
  of a full O(N) cumsum materialized per sample batch, and the big array is
  read once, streaming.
- :func:`pallas_sample` (TPU): the within-block phase as a Pallas kernel
  with **scalar-prefetched block indices** — each grid step DMAs exactly one
  priority block HBM→VMEM via the prefetched index map (no ``[S, block]``
  gather materialization in HBM at all) and runs the cumsum+count search on
  the VPU.

Both produce the same sample for the same uniform targets (same float
summation order within blocks).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp


def _split_targets(
    flat_p: jnp.ndarray, targets: jnp.ndarray, block_size: int
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Phase 1 (shared): per-block sums -> block choice + residual target.

    Returns (blocks [nb, bs], block_idx [S], within_target [S]).
    """
    n = flat_p.shape[0]
    pad = (-n) % block_size
    if pad:
        flat_p = jnp.pad(flat_p, (0, pad))
    blocks = flat_p.reshape(-1, block_size)
    block_cum = jnp.cumsum(blocks.sum(axis=1))
    b_idx = jnp.clip(
        jnp.searchsorted(block_cum, targets, side="left"),
        0,
        blocks.shape[0] - 1,
    )
    prev = jnp.where(b_idx > 0, block_cum[b_idx - 1], 0.0)
    return blocks, b_idx.astype(jnp.int32), targets - prev


def hierarchical_sample(
    flat_p: jnp.ndarray, targets: jnp.ndarray, block_size: int = 1024
) -> jnp.ndarray:
    """Two-level proportional search; returns flat indices, one per target."""
    blocks, b_idx, within_t = _split_targets(flat_p, targets, block_size)
    rows = blocks[b_idx]                      # [S, bs]
    row_cum = jnp.cumsum(rows, axis=1)
    w_idx = jnp.sum(row_cum < within_t[:, None], axis=1)
    w_idx = jnp.clip(w_idx, 0, block_size - 1)
    return jnp.clip(
        b_idx * block_size + w_idx, 0, flat_p.shape[0] - 1
    ).astype(jnp.int32)


def _within_block_kernel(b_idx_ref, t_ref, p_ref, out_ref):
    """One sample per grid step: search the prefetch-selected block."""
    import jax.experimental.pallas as pl

    i = pl.program_id(0)
    t = t_ref[i, 0]
    cum = jnp.cumsum(p_ref[0, :])
    w = jnp.sum((cum < t).astype(jnp.int32))
    bs = p_ref.shape[-1]
    w = jnp.minimum(w, bs - 1)
    out_ref[i, 0] = b_idx_ref[i] * bs + w


def pallas_sample(
    flat_p: jnp.ndarray,
    targets: jnp.ndarray,
    block_size: int = 1024,
    interpret: bool = False,
) -> jnp.ndarray:
    """Pallas within-block search; distribution-identical to
    :func:`hierarchical_sample`."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    blocks, b_idx, within_t = _split_targets(flat_p, targets, block_size)
    S = targets.shape[0]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,              # b_idx steers the DMA index map
        grid=(S,),
        in_specs=[
            pl.BlockSpec((S, 1), lambda i, b_idx_ref: (0, 0)),
            pl.BlockSpec(
                (1, block_size), lambda i, b_idx_ref: (b_idx_ref[i], 0)
            ),
        ],
        out_specs=pl.BlockSpec((S, 1), lambda i, b_idx_ref: (0, 0)),
    )
    out = pl.pallas_call(
        _within_block_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, 1), jnp.int32),
        interpret=interpret,
    )(b_idx, within_t[:, None], blocks)
    return jnp.clip(out[:, 0], 0, flat_p.shape[0] - 1)


_SAMPLE_METHODS = ("cumsum", "hierarchical", "pallas")


def resolve_sample_method(method: str = "auto") -> str:
    """Resolve ``"auto"`` to the best concrete method for this backend.

    TPU -> ``pallas`` (the scalar-prefetch kernel; top-level and
    shard_map'd legality covered by ``tests_tpu/test_compiled_kernels.py``),
    anything else -> ``hierarchical`` (pure XLA, runs everywhere).
    The env var ``SCALERL_PER_METHOD`` overrides what ``auto`` resolves to
    (e.g. ``hierarchical`` to back out the kernel on TPU without touching
    call sites); an explicitly pinned method always wins, so tests that
    compare methods stay meaningful under the override.

    Buffers resolve ``"auto"`` ONCE at construction time (the
    ``PrioritizedReplayBuffer`` / sharded-replay constructors and the R2D2
    trainers all call this in ``__init__``) rather than inside their traced
    sample programs: trace-time resolution would silently pin whatever the
    env var / backend happened to be at FIRST trace, and later changes to
    ``SCALERL_PER_METHOD`` would be ignored without any signal.  A bare
    ``proportional_sample(..., method="auto")`` still resolves at call
    time for one-off use.
    """
    import os

    if method != "auto":
        if method not in _SAMPLE_METHODS:
            raise ValueError(
                f"unknown sampling method {method!r}; use one of "
                f"{('auto',) + _SAMPLE_METHODS}"
            )
        return method
    forced = os.environ.get("SCALERL_PER_METHOD")
    if forced:
        if forced not in _SAMPLE_METHODS:
            raise ValueError(
                f"SCALERL_PER_METHOD={forced!r} is not one of {_SAMPLE_METHODS}"
            )
        return forced
    return "pallas" if jax.default_backend() == "tpu" else "hierarchical"


def proportional_sample(
    flat_p: jnp.ndarray,
    targets: jnp.ndarray,
    method: str = "auto",
    block_size: int = 1024,
) -> jnp.ndarray:
    """Dispatch: ``auto`` (backend-resolved), ``cumsum`` (flat plan A),
    ``hierarchical``, or ``pallas``."""
    method = resolve_sample_method(method)
    if method == "cumsum":
        cum = jnp.cumsum(flat_p)
        idx = jnp.searchsorted(cum, targets, side="left")
        return jnp.clip(idx, 0, flat_p.shape[0] - 1).astype(jnp.int32)
    if method == "hierarchical":
        return hierarchical_sample(flat_p, targets, block_size)
    # resolve_sample_method validated; only "pallas" remains
    return pallas_sample(flat_p, targets, block_size)


@functools.partial(jax.jit, static_argnames=("method", "block_size"))
def _jitted_proportional_sample(flat_p, targets, method, block_size):
    return proportional_sample(flat_p, targets, method, block_size)
