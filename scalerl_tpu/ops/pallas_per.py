"""Hierarchical prioritized-replay sampling: XLA two-level + Pallas kernel.

SURVEY.md §7 called cumsum-over-capacity "plan A" and a Pallas path "plan B
if this ever dominates the profile".  Both live here:

- :func:`hierarchical_sample` (XLA, any backend): split the priority plane
  into blocks; a tiny block-sum cumsum picks each sample's block, then only
  the selected blocks (``[S, block]``) are scanned — O(N + S·block) instead
  of a full O(N) cumsum materialized per sample batch, and the big array is
  read once, streaming.
- :func:`pallas_sample` (TPU): the within-block phase as a Pallas kernel
  with **scalar-prefetched block indices** — each grid step DMAs exactly one
  priority block HBM→VMEM via the prefetched index map (no ``[S, block]``
  gather materialization in HBM at all) and runs the cumsum+count search on
  the VPU.

Both produce the same sample for the same uniform targets (same float
summation order within blocks).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp


def _split_targets(
    flat_p: jnp.ndarray, targets: jnp.ndarray, block_size: int
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Phase 1 (shared): per-block sums -> block choice + residual target.

    Returns (blocks [nb, bs], block_idx [S], within_target [S]).
    """
    n = flat_p.shape[0]
    pad = (-n) % block_size
    if pad:
        flat_p = jnp.pad(flat_p, (0, pad))
    blocks = flat_p.reshape(-1, block_size)
    block_cum = jnp.cumsum(blocks.sum(axis=1))
    b_idx = jnp.clip(
        jnp.searchsorted(block_cum, targets, side="left"),
        0,
        blocks.shape[0] - 1,
    )
    prev = jnp.where(b_idx > 0, block_cum[b_idx - 1], 0.0)
    return blocks, b_idx.astype(jnp.int32), targets - prev


def hierarchical_sample(
    flat_p: jnp.ndarray, targets: jnp.ndarray, block_size: int = 1024
) -> jnp.ndarray:
    """Two-level proportional search; returns flat indices, one per target."""
    blocks, b_idx, within_t = _split_targets(flat_p, targets, block_size)
    rows = blocks[b_idx]                      # [S, bs]
    row_cum = jnp.cumsum(rows, axis=1)
    w_idx = jnp.sum(row_cum < within_t[:, None], axis=1)
    w_idx = jnp.clip(w_idx, 0, block_size - 1)
    return jnp.clip(
        b_idx * block_size + w_idx, 0, flat_p.shape[0] - 1
    ).astype(jnp.int32)


def _within_block_kernel(b_idx_ref, t_ref, p_ref, out_ref):
    """One sample per grid step: search the prefetch-selected block."""
    import jax.experimental.pallas as pl

    i = pl.program_id(0)
    t = t_ref[i, 0]
    cum = jnp.cumsum(p_ref[0, :])
    w = jnp.sum((cum < t).astype(jnp.int32))
    bs = p_ref.shape[-1]
    w = jnp.minimum(w, bs - 1)
    out_ref[i, 0] = b_idx_ref[i] * bs + w


def pallas_sample(
    flat_p: jnp.ndarray,
    targets: jnp.ndarray,
    block_size: int = 1024,
    interpret: bool = None,
) -> jnp.ndarray:
    """Pallas within-block search; distribution-identical to
    :func:`hierarchical_sample`.

    ``interpret=None`` auto-resolves: compiled Mosaic on TPU, the Pallas
    interpreter elsewhere — so an explicitly pinned ``method="pallas"``
    (e.g. ``RLArguments.use_pallas`` on a CPU test run) works on every
    backend instead of failing to compile off-TPU."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    blocks, b_idx, within_t = _split_targets(flat_p, targets, block_size)
    S = targets.shape[0]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,              # b_idx steers the DMA index map
        grid=(S,),
        in_specs=[
            pl.BlockSpec((S, 1), lambda i, b_idx_ref: (0, 0)),
            pl.BlockSpec(
                (1, block_size), lambda i, b_idx_ref: (b_idx_ref[i], 0)
            ),
        ],
        out_specs=pl.BlockSpec((S, 1), lambda i, b_idx_ref: (0, 0)),
    )
    out = pl.pallas_call(
        _within_block_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, 1), jnp.int32),
        interpret=interpret,
    )(b_idx, within_t[:, None], blocks)
    return jnp.clip(out[:, 0], 0, flat_p.shape[0] - 1)


_SAMPLE_METHODS = ("cumsum", "hierarchical", "pallas")


def resolve_sample_method(method: str = "auto") -> str:
    """Resolve ``"auto"`` to the best concrete method for this backend.

    TPU -> ``pallas`` (the scalar-prefetch kernel; top-level and
    shard_map'd legality covered by ``tests_tpu/test_compiled_kernels.py``),
    anything else -> ``hierarchical`` (pure XLA, runs everywhere).
    The env var ``SCALERL_PER_METHOD`` overrides what ``auto`` resolves to
    (e.g. ``hierarchical`` to back out the kernel on TPU without touching
    call sites); an explicitly pinned method always wins, so tests that
    compare methods stay meaningful under the override.

    Buffers resolve ``"auto"`` ONCE at construction time (the
    ``PrioritizedReplayBuffer`` / sharded-replay constructors and the R2D2
    trainers all call this in ``__init__``) rather than inside their traced
    sample programs: trace-time resolution would silently pin whatever the
    env var / backend happened to be at FIRST trace, and later changes to
    ``SCALERL_PER_METHOD`` would be ignored without any signal.  A bare
    ``proportional_sample(..., method="auto")`` still resolves at call
    time for one-off use.
    """
    import os

    if method != "auto":
        if method not in _SAMPLE_METHODS:
            raise ValueError(
                f"unknown sampling method {method!r}; use one of "
                f"{('auto',) + _SAMPLE_METHODS}"
            )
        return method
    forced = os.environ.get("SCALERL_PER_METHOD")
    if forced:
        if forced not in _SAMPLE_METHODS:
            raise ValueError(
                f"SCALERL_PER_METHOD={forced!r} is not one of {_SAMPLE_METHODS}"
            )
        return forced
    return "pallas" if jax.default_backend() == "tpu" else "hierarchical"


def proportional_sample(
    flat_p: jnp.ndarray,
    targets: jnp.ndarray,
    method: str = "auto",
    block_size: int = 1024,
) -> jnp.ndarray:
    """Dispatch: ``auto`` (backend-resolved), ``cumsum`` (flat plan A),
    ``hierarchical``, or ``pallas``."""
    method = resolve_sample_method(method)
    if method == "cumsum":
        cum = jnp.cumsum(flat_p)
        idx = jnp.searchsorted(cum, targets, side="left")
        return jnp.clip(idx, 0, flat_p.shape[0] - 1).astype(jnp.int32)
    if method == "hierarchical":
        return hierarchical_sample(flat_p, targets, block_size)
    # resolve_sample_method validated; only "pallas" remains
    return pallas_sample(flat_p, targets, block_size)


@functools.partial(jax.jit, static_argnames=("method", "block_size"))
def _jitted_proportional_sample(flat_p, targets, method, block_size):
    return proportional_sample(flat_p, targets, method, block_size)


# ---------------------------------------------------------------------------
# fused priority / sum-tree update (the write half of the PER feedback loop)

_UPDATE_METHODS = ("xla", "pallas")


def resolve_update_method(method: str = "auto") -> str:
    """Resolve the priority-update implementation for this backend.

    Mirrors :func:`resolve_sample_method`: ``auto`` -> ``pallas`` on TPU
    (the aliased in-place scatter kernel), ``xla`` elsewhere (interpreter
    mode is correct but slow for a per-learn-step op).  The env var
    ``SCALERL_PER_UPDATE`` overrides what ``auto`` resolves to; an
    explicitly pinned method always wins.
    """
    import os

    if method != "auto":
        if method not in _UPDATE_METHODS:
            raise ValueError(
                f"unknown update method {method!r}; use one of "
                f"{('auto',) + _UPDATE_METHODS}"
            )
        return method
    forced = os.environ.get("SCALERL_PER_UPDATE")
    if forced:
        if forced not in _UPDATE_METHODS:
            raise ValueError(
                f"SCALERL_PER_UPDATE={forced!r} is not one of {_UPDATE_METHODS}"
            )
        return forced
    return "pallas" if jax.default_backend() == "tpu" else "xla"


def _pad_to_blocks(flat_p: jnp.ndarray, block_size: int) -> jnp.ndarray:
    pad = (-flat_p.shape[0]) % block_size
    return jnp.pad(flat_p, (0, pad)) if pad else flat_p


def _update_kernel_factory(M: int, with_sums: bool):
    """Grid step i owns block ``b_idx[i]`` and applies EVERY update whose
    block matches — idempotent per block, so a block revisited by a later
    grid step (whose input DMA races the earlier step's writeback under the
    double-buffered pipeline) recomputes the identical final content
    instead of losing the earlier write.  Updates apply in ascending order,
    so duplicate (block, lane) pairs are deterministic last-wins."""
    import jax.experimental.pallas as pl

    def kernel(b_idx_ref, w_idx_ref, blocks_ref, *rest):
        if with_sums:
            _sums_ref, newp_ref, out_blocks_ref, out_sums_ref = rest
        else:
            newp_ref, out_blocks_ref = rest
        i = pl.program_id(0)
        my_b = b_idx_ref[i]
        blk = blocks_ref[:]
        lane = jax.lax.broadcasted_iota(jnp.int32, blk.shape, 1)

        def body(j, blk):
            sel = (b_idx_ref[j] == my_b) & (lane == w_idx_ref[j])
            return jnp.where(sel, newp_ref[j, 0], blk)

        blk = jax.lax.fori_loop(0, M, body, blk)
        out_blocks_ref[:] = blk
        if with_sums:
            out_sums_ref[0, 0] = jnp.sum(blk)

    return kernel


def _pallas_update(
    blocks: jnp.ndarray,  # [nb, bs]
    block_sums,  # [nb] or None
    b_idx: jnp.ndarray,  # [M]
    w_idx: jnp.ndarray,  # [M]
    new_p: jnp.ndarray,  # [M]
    interpret: bool,
):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nb, bs = blocks.shape
    M = b_idx.shape[0]
    with_sums = block_sums is not None
    in_specs = [
        pl.BlockSpec((1, bs), lambda i, b, w: (b[i], 0)),
    ]
    out_specs = [pl.BlockSpec((1, bs), lambda i, b, w: (b[i], 0))]
    out_shape = [jax.ShapeDtypeStruct((nb, bs), jnp.float32)]
    operands = [blocks.astype(jnp.float32)]
    # the outputs alias their inputs (indices count the scalar-prefetch
    # operands): untouched blocks/sums keep their values with zero copies
    aliases = {2: 0}
    if with_sums:
        in_specs.append(pl.BlockSpec((1, 1), lambda i, b, w: (b[i], 0)))
        out_specs.append(pl.BlockSpec((1, 1), lambda i, b, w: (b[i], 0)))
        out_shape.append(jax.ShapeDtypeStruct((nb, 1), jnp.float32))
        operands.append(block_sums.astype(jnp.float32).reshape(nb, 1))
        aliases[3] = 1
    in_specs.append(
        pl.BlockSpec((M, 1), lambda i, b, w: (0, 0))  # all updates, VMEM
    )
    operands.append(new_p.astype(jnp.float32)[:, None])
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(M,),
        in_specs=in_specs,
        # out_specs/out_shape pytrees must match exactly: a bare leaf for
        # the plane-only variant, a 2-tuple when sums ride along
        out_specs=tuple(out_specs) if with_sums else out_specs[0],
    )
    out = pl.pallas_call(
        _update_kernel_factory(M, with_sums),
        grid_spec=grid_spec,
        out_shape=tuple(out_shape) if with_sums else out_shape[0],
        input_output_aliases=aliases,
        interpret=interpret,
    )(b_idx.astype(jnp.int32), w_idx.astype(jnp.int32), *operands)
    if with_sums:
        return out[0], out[1][:, 0]
    return out, None


def update_priorities_blocks(
    flat_p: jnp.ndarray,
    idx: jnp.ndarray,
    new_p: jnp.ndarray,
    block_sums=None,
    block_size: int = 1024,
    method: str = "auto",
    interpret=None,
):
    """Fused PER priority + two-level sum-tree update.

    Scatters ``new_p`` into the flat priority plane at ``idx`` and, when
    ``block_sums`` (the maintained per-block partial sums — the two-level
    "sum tree" :func:`hierarchical_sample`'s phase 1 consumes) is given,
    refreshes exactly the affected blocks' sums in the same pass.  Returns
    ``(new_flat_p, new_block_sums)`` (``new_block_sums`` is None when no
    sums were passed).

    Semantics: updates apply in ascending order, so duplicate indices are
    deterministic last-wins in BOTH implementations.  ``method="pallas"``
    runs the aliased in-place kernel — one block DMA per update, no full-
    plane traffic; ``"xla"`` is the reference (an ordered scatter loop +
    affected-block re-sum) the kernel is bit-tolerance-tested against;
    ``"auto"`` resolves per backend (:func:`resolve_update_method`).
    ``interpret=None`` auto-resolves like :func:`pallas_sample`.
    """
    method = resolve_update_method(method)
    n = flat_p.shape[0]
    idx = jnp.clip(idx.astype(jnp.int32), 0, n - 1)
    new_p = new_p.astype(jnp.float32)
    padded = _pad_to_blocks(flat_p.astype(jnp.float32), block_size)
    nb = padded.shape[0] // block_size
    if block_sums is not None and block_sums.shape[0] != nb:
        raise ValueError(
            f"block_sums has {block_sums.shape[0]} entries but the padded "
            f"plane has {nb} blocks of {block_size}"
        )
    b_idx = idx // block_size
    w_idx = idx % block_size

    if method == "xla":
        def body(j, p):
            return p.at[idx[j]].set(new_p[j])

        padded = jax.lax.fori_loop(0, idx.shape[0], body, padded)
        new_sums = None
        if block_sums is not None:
            rows = padded.reshape(nb, block_size)
            new_sums = block_sums.astype(jnp.float32).at[b_idx].set(
                jnp.sum(rows[b_idx], axis=1)
            )
        return padded[:n], new_sums

    blocks = padded.reshape(nb, block_size)
    new_blocks, new_sums = _pallas_update(
        blocks, block_sums, b_idx, w_idx, new_p,
        interpret=(
            jax.default_backend() != "tpu" if interpret is None else interpret
        ),
    )
    return new_blocks.reshape(-1)[:n], new_sums
