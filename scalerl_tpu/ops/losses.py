"""RL loss functions, pure and jit/grad-safe.

Parity targets:
- IMPALA losses (``scalerl/algorithms/impala/loss_fn.py:5-23``):
  ``compute_baseline_loss`` = 0.5 * sum(adv^2), ``compute_entropy_loss`` =
  sum(p * log p) (negative entropy; minimised, i.e. an entropy *bonus*),
  ``compute_policy_gradient_loss`` = sum(NLL(a) * advantage.detach()).
- DQN / double-DQN target + TD loss (``scalerl/algorithms/dqn/dqn_agent.py:
  136-180``), with optional element-wise importance weights for PER
  (``apex/worker.py:134-161``) and Huber option.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def baseline_loss(advantages: jnp.ndarray) -> jnp.ndarray:
    """0.5 * sum(advantages^2)."""
    return 0.5 * jnp.sum(jnp.square(advantages))


def entropy_loss(logits: jnp.ndarray) -> jnp.ndarray:
    """sum(p * log p): the negative entropy (minimising adds entropy bonus)."""
    log_policy = jax.nn.log_softmax(logits, axis=-1)
    policy = jnp.exp(log_policy)
    return jnp.sum(policy * log_policy)


def policy_gradient_loss(
    logits: jnp.ndarray,
    actions: jnp.ndarray,
    advantages: jnp.ndarray,
) -> jnp.ndarray:
    """sum over [T, B] of -log pi(a_t|x_t) * advantage (advantage detached)."""
    log_policy = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(log_policy, actions[..., None], axis=-1).squeeze(-1)
    return jnp.sum(nll * jax.lax.stop_gradient(advantages))


def double_dqn_targets(
    q_next_online: jnp.ndarray,
    q_next_target: jnp.ndarray,
    rewards: jnp.ndarray,
    discounts: jnp.ndarray,
    double_dqn: bool = True,
) -> jnp.ndarray:
    """TD targets: r + discount * Q_target(s', argmax_a Q_online(s', a)).

    With ``double_dqn=False`` the action selection uses the target net
    (vanilla DQN).  Shapes: q_* [B, A]; rewards/discounts [B].
    """
    if double_dqn:
        next_actions = jnp.argmax(q_next_online, axis=-1)
    else:
        next_actions = jnp.argmax(q_next_target, axis=-1)
    q_next = jnp.take_along_axis(q_next_target, next_actions[:, None], axis=-1).squeeze(-1)
    return jax.lax.stop_gradient(rewards + discounts * q_next)


def dqn_loss(
    q_values: jnp.ndarray,
    actions: jnp.ndarray,
    targets: jnp.ndarray,
    weights: Optional[jnp.ndarray] = None,
    huber_delta: Optional[float] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """TD loss for chosen actions; returns (loss, |td_error| for PER).

    Shapes: q_values [B, A], actions [B], targets [B], weights [B] or None.
    """
    q_sa = jnp.take_along_axis(q_values, actions[:, None], axis=-1).squeeze(-1)
    td_error = q_sa - targets
    if huber_delta is not None:
        abs_td = jnp.abs(td_error)
        quadratic = jnp.minimum(abs_td, huber_delta)
        per_elem = 0.5 * quadratic**2 + huber_delta * (abs_td - quadratic)
    else:
        per_elem = 0.5 * jnp.square(td_error)
    if weights is not None:
        per_elem = per_elem * weights
    return jnp.mean(per_elem), jnp.abs(jax.lax.stop_gradient(td_error))
