"""RL loss functions, pure and jit/grad-safe.

Parity targets:
- IMPALA losses (``scalerl/algorithms/impala/loss_fn.py:5-23``):
  ``compute_baseline_loss`` = 0.5 * sum(adv^2), ``compute_entropy_loss`` =
  sum(p * log p) (negative entropy; minimised, i.e. an entropy *bonus*),
  ``compute_policy_gradient_loss`` = sum(NLL(a) * advantage.detach()).
- DQN / double-DQN target + TD loss (``scalerl/algorithms/dqn/dqn_agent.py:
  136-180``), with optional element-wise importance weights for PER
  (``apex/worker.py:134-161``) and Huber option.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def baseline_loss(advantages: jnp.ndarray) -> jnp.ndarray:
    """0.5 * sum(advantages^2)."""
    return 0.5 * jnp.sum(jnp.square(advantages))


def entropy_loss(logits: jnp.ndarray) -> jnp.ndarray:
    """sum(p * log p): the negative entropy (minimising adds entropy bonus)."""
    log_policy = jax.nn.log_softmax(logits, axis=-1)
    policy = jnp.exp(log_policy)
    return jnp.sum(policy * log_policy)


def policy_gradient_loss(
    logits: jnp.ndarray,
    actions: jnp.ndarray,
    advantages: jnp.ndarray,
) -> jnp.ndarray:
    """sum over [T, B] of -log pi(a_t|x_t) * advantage (advantage detached)."""
    log_policy = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(log_policy, actions[..., None], axis=-1).squeeze(-1)
    return jnp.sum(nll * jax.lax.stop_gradient(advantages))


def clipped_surrogate_loss(
    new_logp: jnp.ndarray,
    behavior_logp: jnp.ndarray,
    advantages: jnp.ndarray,
    clip_range: float,
) -> Tuple[jnp.ndarray, dict]:
    """PPO clipped surrogate objective (Schulman et al. 2017, eq. 7).

    Sum convention over ``[T, B]`` like the other policy losses here.
    Advantages are detached; ``behavior_logp`` is the collection-time log
    probability of the taken action.  Returns ``(loss, aux)`` where aux
    holds detached diagnostics (``mean_ratio`` / ``mean_approx_kl`` — the
    low-variance k3 estimator ``E[(r-1) - log r]`` — / ``mean_clip_frac``),
    named per the ``mean_*`` metric contract (``agents/impala.py``).
    """
    log_ratio = new_logp - jax.lax.stop_gradient(behavior_logp)
    ratio = jnp.exp(log_ratio)
    adv = jax.lax.stop_gradient(advantages)
    unclipped = ratio * adv
    clipped = jnp.clip(ratio, 1.0 - clip_range, 1.0 + clip_range) * adv
    loss = -jnp.sum(jnp.minimum(unclipped, clipped))
    aux = {
        "mean_ratio": jnp.mean(ratio),
        "mean_approx_kl": jnp.mean((ratio - 1.0) - log_ratio),
        "mean_clip_frac": jnp.mean(
            (jnp.abs(ratio - 1.0) > clip_range).astype(jnp.float32)
        ),
    }
    aux = {k: jax.lax.stop_gradient(v) for k, v in aux.items()}
    return loss, aux


def double_dqn_targets(
    q_next_online: jnp.ndarray,
    q_next_target: jnp.ndarray,
    rewards: jnp.ndarray,
    discounts: jnp.ndarray,
    double_dqn: bool = True,
) -> jnp.ndarray:
    """TD targets: r + discount * Q_target(s', argmax_a Q_online(s', a)).

    With ``double_dqn=False`` the action selection uses the target net
    (vanilla DQN).  Shapes: q_* [B, A]; rewards/discounts [B].
    """
    if double_dqn:
        next_actions = jnp.argmax(q_next_online, axis=-1)
    else:
        next_actions = jnp.argmax(q_next_target, axis=-1)
    q_next = jnp.take_along_axis(q_next_target, next_actions[:, None], axis=-1).squeeze(-1)
    return jax.lax.stop_gradient(rewards + discounts * q_next)


def make_support(v_min: float, v_max: float, num_atoms: int) -> jnp.ndarray:
    """The fixed C51 atom grid ``z_i = v_min + i * dz``."""
    return jnp.linspace(v_min, v_max, num_atoms)


def categorical_q_values(logits: jnp.ndarray, support: jnp.ndarray) -> jnp.ndarray:
    """Expected Q per action from atom logits: ``[B, A, N] -> [B, A]``."""
    return jnp.sum(jax.nn.softmax(logits, axis=-1) * support, axis=-1)


def categorical_projection(
    next_probs: jnp.ndarray,
    rewards: jnp.ndarray,
    discounts: jnp.ndarray,
    support: jnp.ndarray,
) -> jnp.ndarray:
    """C51 projected Bellman target (Bellemare et al. 2017, Alg. 1).

    Shifts the next-state atom distribution by ``r + discount * z``, clips to
    the support range, and splits each shifted atom's mass linearly between
    its two neighboring grid points.  The reference declares the C51 flags
    (``rl_args.py:201-226``) but never implements this; TPU-shaped here as a
    dense one-hot matmul — ``[B, N, N]`` interpolation weights contracted on
    the MXU — instead of scatter-adds, which lower to serial HLO scatter.

    Shapes: next_probs ``[B, N]``, rewards/discounts ``[B]``, support ``[N]``;
    returns ``[B, N]``.
    """
    num_atoms = support.shape[0]
    v_min, v_max = support[0], support[-1]
    dz = (v_max - v_min) / (num_atoms - 1)
    # shifted sample positions for every source atom: [B, N]
    tz = jnp.clip(
        rewards[:, None] + discounts[:, None] * support[None, :], v_min, v_max
    )
    b = (tz - v_min) / dz  # fractional grid coordinates
    low = jnp.floor(b)
    up = jnp.ceil(b)
    # when b lands exactly on a grid point (low == up), all mass goes to it
    w_low = jnp.where(low == up, 1.0, up - b)  # [B, N]
    w_up = b - low
    grid = jnp.arange(num_atoms, dtype=b.dtype)  # [N]
    # dense interpolation tensor W[b, src, dst]: mass of source atom src
    # landing on destination atom dst
    w = w_low[..., None] * (low[..., None] == grid) + w_up[..., None] * (
        up[..., None] == grid
    )
    return jax.lax.stop_gradient(jnp.einsum("bs,bsd->bd", next_probs, w))


def c51_loss(
    logits: jnp.ndarray,
    actions: jnp.ndarray,
    target_probs: jnp.ndarray,
    weights: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Cross-entropy between projected target and predicted distribution.

    Shapes: logits ``[B, A, N]``, actions ``[B]``, target_probs ``[B, N]``.
    Returns (scalar loss, per-sample CE) — the per-sample cross-entropy is
    the standard C51 PER priority signal.
    """
    log_p = jax.nn.log_softmax(logits, axis=-1)  # [B, A, N]
    log_p_a = jnp.take_along_axis(
        log_p, actions[:, None, None].astype(jnp.int32), axis=1
    )[:, 0]  # [B, N]
    ce = -jnp.sum(target_probs * log_p_a, axis=-1)  # [B]
    per_elem = ce if weights is None else ce * weights
    return jnp.mean(per_elem), jax.lax.stop_gradient(ce)


def dqn_loss(
    q_values: jnp.ndarray,
    actions: jnp.ndarray,
    targets: jnp.ndarray,
    weights: Optional[jnp.ndarray] = None,
    huber_delta: Optional[float] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """TD loss for chosen actions; returns (loss, |td_error| for PER).

    Shapes: q_values [B, A], actions [B], targets [B], weights [B] or None.
    """
    q_sa = jnp.take_along_axis(q_values, actions[:, None], axis=-1).squeeze(-1)
    td_error = q_sa - targets
    if huber_delta is not None:
        abs_td = jnp.abs(td_error)
        quadratic = jnp.minimum(abs_td, huber_delta)
        per_elem = 0.5 * quadratic**2 + huber_delta * (abs_td - quadratic)
    else:
        per_elem = 0.5 * jnp.square(td_error)
    if weights is not None:
        per_elem = per_elem * weights
    return jnp.mean(per_elem), jnp.abs(jax.lax.stop_gradient(td_error))
