"""Pallas TPU paged decode attention: one query token against a block-paged
KV cache (the vLLM cache shape on the continuous-batching plane).

The hot op of ``genrl/continuous.py``'s persistent decode loop: every lane
holds ONE new query token and a page table pointing into a shared pool of
``[num_pages, page_size, H, D]`` K/V blocks, so attention must *gather*
each lane's context through its table instead of slicing a dense
``[B, S, H, D]`` cache.  Two implementations behind one contract:

- :func:`paged_attention_reference` — XLA gather: materialize each lane's
  pages (``k_pages[page_table]``), mask positions ``>= lengths``, explicit
  f32 softmax.  The parity oracle and the CPU-backend default (Pallas
  interpret mode would re-interpret the kernel per decode sub-step).
- :func:`paged_decode_attention` — the Pallas kernel: grid
  ``(B, H, num_pages_per_lane)`` with the page table and lengths as
  *scalar-prefetch* operands, so each kv step's ``BlockSpec`` index map
  reads ``page_table[b, j]`` and DMAs exactly that page from the pool into
  VMEM — HBM traffic is O(live tokens), never O(pool).  Online softmax
  with float32 accumulators in VMEM scratch persisting across the
  (innermost, sequential) page dimension; pages past a lane's length are
  skipped entirely via ``pl.when``.  Interpret mode off-TPU; Mosaic on TPU.

Grad-free by construction: decode is inference-only, no ``custom_vjp`` is
defined, and differentiating through ``pallas_call`` raises — the learner
recomputes logits with the dense training forward, never through this op.

Numerics contract (pinned at 1e-5 against the reference across contiguous,
fragmented, and partially-filled-last-page table layouts): masked scores
use -1e30 (not -inf) exactly like ``models/transformer._masked_attention``,
scores/accumulators are float32 regardless of input dtype, and every lane
must have ``lengths >= 1`` (the engine guarantees it: a lane attends at
least to the token it just wrote; dead lanes are masked downstream).
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_BIG = -1e30


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def resolve_paged_attn(impl: str = "auto") -> str:
    """``pallas`` on TPU, ``xla`` elsewhere; ``SCALERL_PAGED_ATTN``
    overrides what ``auto`` resolves to (the ``SCALERL_PER_METHOD`` /
    ``SCALERL_ITER_MODE`` escape-hatch pattern)."""
    impls = ("pallas", "xla")
    if impl == "auto":
        impl = os.environ.get("SCALERL_PAGED_ATTN", "") or (
            "pallas" if jax.default_backend() == "tpu" else "xla"
        )
    if impl not in impls:
        raise ValueError(
            f"paged attention impl must be auto | pallas | xla, got {impl!r}"
        )
    return impl


def paged_attention_reference(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,
    lengths: jnp.ndarray,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """XLA gather implementation — the oracle the kernel is pinned to.

    ``q``: ``[B, 1, H, D]`` (one query token per lane).  ``k_pages`` /
    ``v_pages``: ``[N, page_size, H, D]`` pools.  ``page_table``:
    ``[B, M]`` int32 page ids (junk entries must still be in ``[0, N)`` —
    the allocator's null page 0 — they are masked by ``lengths``).
    ``lengths``: ``[B]`` int32 valid-token counts (>= 1).  Returns
    ``[B, 1, H, D]``.
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    B = q.shape[0]
    N, ps = k_pages.shape[0], k_pages.shape[1]
    M = page_table.shape[1]
    # flat single-axis gather: XLA:CPU lowers row gathers of a 3-D operand
    # ~3x faster than fancy-indexing the 4-D pool (measured; the reshape
    # itself is a bitcast)
    idx = (
        page_table[:, :, None] * ps + jnp.arange(ps)[None, None, :]
    ).reshape(B, M * ps)
    k = k_pages.reshape(N * ps, *k_pages.shape[2:])[idx]
    v = v_pages.reshape(N * ps, *v_pages.shape[2:])[idx]
    qf = q[:, 0].astype(jnp.float32)  # [B, H, D]
    scores = jnp.einsum("bhd,bshd->bhs", qf, k.astype(jnp.float32)) * scale
    valid = jnp.arange(M * ps)[None, :] < lengths[:, None]  # [B, S]
    scores = jnp.where(valid[:, None, :], scores, jnp.float32(_NEG_BIG))
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhs,bshd->bhd", probs, v.astype(jnp.float32))
    return out[:, None].astype(q.dtype)


def _decode_kernel(
    pt_ref, len_ref, q_ref, k_ref, v_ref, o_ref, acc_sc, m_sc, l_sc,
    *, scale, page_size, num_pages_per_lane,
):
    b = pl.program_id(0)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        acc_sc[:] = jnp.zeros_like(acc_sc)
        m_sc[:] = jnp.full_like(m_sc, _NEG_BIG)
        l_sc[:] = jnp.zeros_like(l_sc)

    length = len_ref[b]
    live = j * page_size < length

    @pl.when(live)
    def _attend():
        q = q_ref[0, 0, 0, :].astype(jnp.float32)[None, :] * scale  # [1, D]
        k_blk = k_ref[0, :, 0, :].astype(jnp.float32)  # [ps, D]
        v_blk = v_ref[0, :, 0, :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [1, ps]
        pos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1
        )
        s = jnp.where(pos < length, s, jnp.float32(_NEG_BIG))
        m = m_sc[:]
        l = l_sc[:]
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_sc[:] = l * corr + p.sum(axis=-1, keepdims=True)
        m_sc[:] = m_new
        acc_sc[:] = acc_sc[:] * corr + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == num_pages_per_lane - 1)
    def _finish():
        o_ref[0, 0, 0, :] = (
            acc_sc[:] / jnp.maximum(l_sc[:], 1e-30)
        )[0].astype(o_ref.dtype)


def paged_decode_attention(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,
    lengths: jnp.ndarray,
    scale: Optional[float] = None,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Pallas paged decode attention; same contract as the reference.

    The page table and lengths ride as scalar-prefetch operands
    (``pltpu.PrefetchScalarGridSpec``): they land in SMEM before the
    kernel body runs, so the K/V ``BlockSpec`` index maps dereference
    ``page_table[b, j]`` to choose which pool page each grid step DMAs.
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if interpret is None:
        interpret = _interpret_default()
    B, T, H, D = q.shape
    if T != 1:
        raise ValueError(f"decode attention takes one query token, got T={T}")
    N, ps = k_pages.shape[0], k_pages.shape[1]
    M = page_table.shape[1]

    kernel = functools.partial(
        _decode_kernel, scale=scale, page_size=ps, num_pages_per_lane=M,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, H, M),
        in_specs=[
            pl.BlockSpec((1, 1, 1, D), lambda b, h, j, pt, ln: (b, 0, h, 0)),
            pl.BlockSpec(
                (1, ps, 1, D), lambda b, h, j, pt, ln: (pt[b, j], 0, h, 0)
            ),
            pl.BlockSpec(
                (1, ps, 1, D), lambda b, h, j, pt, ln: (pt[b, j], 0, h, 0)
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, 1, D), lambda b, h, j, pt, ln: (b, 0, h, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((1, D), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, 1, H, D), q.dtype),
        interpret=interpret,
    )(
        page_table.astype(jnp.int32),
        lengths.astype(jnp.int32),
        q,
        k_pages,
        v_pages,
    )


def make_paged_attn_fn(impl: str = "auto"):
    """The ``TransformerPolicy.paged_attn_fn`` seam: resolve once, close
    over the choice, keep the jitted decode program shape-stable."""
    resolved = resolve_paged_attn(impl)
    if resolved == "pallas":
        return paged_decode_attention
    return paged_attention_reference
