"""Ring attention: sequence-parallel exact attention over an ICI ring.

No counterpart exists in the reference (SURVEY.md §2.4: sequence/context
parallelism is **absent** — its longest temporal machinery is an LSTM unroll).
This op makes long-context first-class for the TPU build: sequences are
sharded over the mesh's ``sp`` axis, each device holds a ``[B, T/n, H, D]``
block of q/k/v, and k/v blocks rotate around the ring via
``jax.lax.ppermute`` while a streaming (flash-style) online softmax
accumulates exact attention — memory per device stays O(T/n), communication
rides neighbor-to-neighbor ICI hops, and the result is bitwise-equal math to
full attention (up to float reassociation).

Designed after the blockwise/ring formulation of Liu et al. (Ring Attention
with Blockwise Transformers, 2023); implementation is original and
shard_map-native.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def _online_block_update(o, l, m, s, v):
    """Streaming softmax accumulation for one kv block.

    o: [B, Tq, H, D] weighted-value accumulator
    l: [B, H, Tq]    softmax normalizer accumulator
    m: [B, H, Tq]    running row max
    s: [B, H, Tq, Tk] scaled (masked) scores for this block
    v: [B, Tk, H, D]
    """
    m_new = jnp.maximum(m, s.max(axis=-1))
    # fully-masked-so-far rows keep m=-inf; subtract 0 there so exp(-inf)=0
    # instead of exp(nan)
    safe_m = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.exp(s - safe_m[..., None])                    # [B,H,Tq,Tk]
    corr = jnp.exp(jnp.where(jnp.isneginf(m), -jnp.inf, m) - safe_m)
    l_new = l * corr + p.sum(axis=-1)
    o_new = o * corr.transpose(0, 2, 1)[..., None] + jnp.einsum(
        "bhqk,bkhd->bqhd", p, v
    )
    return o_new, l_new, m_new


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str = "sp",
    causal: bool = False,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Exact attention over sequence blocks sharded on ``axis_name``.

    Must run inside ``shard_map`` (or ``pjit``-manual) over a mesh with the
    ``axis_name`` axis.  Shapes are per-device blocks ``[B, T_local, H, D]``;
    ``causal`` masks by *global* position (block offset from the device's
    ring index).
    """
    B, T, H, D = q.shape
    n = jax.lax.psum(1, axis_name)          # static ring size
    idx = jax.lax.axis_index(axis_name)
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    q_pos = idx * T + jnp.arange(T)          # global positions of this block

    # accumulate in f32 regardless of input dtype (bf16 inputs stay bf16 on
    # the matmuls; the final division casts back)
    o0 = jnp.zeros((B, T, H, D), jnp.float32)
    l0 = jnp.zeros((B, H, T), jnp.float32)
    m0 = jnp.full((B, H, T), -jnp.inf, jnp.float32)

    def attend(o, l, m, k_blk, v_blk, src):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk).astype(jnp.float32) * scale
        if causal:
            k_pos = src * T + jnp.arange(T)
            visible = k_pos[None, :] <= q_pos[:, None]      # [Tq, Tk]
            s = jnp.where(visible[None, None], s, -jnp.inf)
        return _online_block_update(o, l, m, s, v_blk.astype(jnp.float32))

    # own block first (no communication) ...
    o, l, m = attend(o0, l0, m0, k, v, src=idx)

    def body(carry, r):
        o, l, m, k_blk, v_blk = carry
        # ... then rotate kv one hop (device i -> i+1) and consume: n-1
        # rotations total, so no dead transfer after the last block
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        o, l, m = attend(o, l, m, k_blk, v_blk, src=(idx - r) % n)
        return (o, l, m, k_blk, v_blk), None

    (o, l, _m, _k, _v), _ = jax.lax.scan(
        body, (o, l, m, k, v), jnp.arange(1, n)
    )
    l = jnp.where(l == 0.0, 1.0, l)          # fully-masked rows -> zeros
    return (o / l.transpose(0, 2, 1)[..., None]).astype(q.dtype)


def full_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Single-device reference attention, same [B, T, H, D] layout."""
    D = q.shape[-1]
    T = q.shape[1]
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        visible = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(visible[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v).astype(q.dtype)


def make_ring_attention_fn(mesh: Mesh, causal: bool = False, axis_name: str = "sp"):
    """shard_map ``ring_attention`` over global ``[B, T, H, D]`` arrays
    sequence-sharded on ``axis_name``."""
    from jax.experimental.shard_map import shard_map

    spec = P(None, axis_name, None, None)
    fn = functools.partial(ring_attention, axis_name=axis_name, causal=causal)
    return shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_rep=False,
    )
