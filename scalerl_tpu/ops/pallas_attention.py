"""Pallas TPU flash attention (forward + flash-style backward).

The hot op of the long-context path (``models/transformer.py`` /
``parallel/sequence.py``).  No counterpart exists in the reference — it has
no attention at all (SURVEY.md §5) — this kernel is part of the TPU build's
beyond-parity long-context stack: blockwise online-softmax attention that
never materializes the ``[T, T]`` score matrix, so HBM traffic stays
O(T·D) and VMEM holds one ``[block_q, block_k]`` tile at a time.

Layout matches :func:`scalerl_tpu.ops.ring_attention.full_attention`:
``q/k/v`` are ``[B, T, H, D]`` and the result is ``[B, Tq, H, D]``, so the
kernel drops into ``TransformerPolicy``'s pluggable ``attn_fn`` seam — and
into ring attention's *local* block step, composing kernel-level tiling
(this file) with device-level sequence sharding (``ring_attention``).

Differentiable: a ``jax.custom_vjp`` implements the flash backward — the
probability tiles are recomputed from the saved log-sum-exp rather than
stored, one kernel gridded over q blocks for ``dq`` and one gridded over
k blocks for ``dk``/``dv`` (the FlashAttention-2 split, so neither kernel
needs cross-grid accumulation).

On CPU hosts (tests, this image) the kernels run in Pallas interpret mode;
on TPU they compile to Mosaic.  Scores/accumulators are float32 regardless
of input dtype (bf16 inputs feed the MXU directly).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = float("-inf")


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _mask_block(
    i: int, j, q_len: int, k_len: int, block_q: int, block_k: int, causal: bool
):
    """Validity mask for score tile (q block ``i``, k block ``j``)."""
    q_pos = i * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    mask = (k_pos < k_len) & (q_pos < q_len)
    if causal:
        mask = mask & (k_pos <= q_pos)
    return mask


# ----------------------------------------------------------------------
# forward
# ----------------------------------------------------------------------
def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref,
    *, scale, causal, q_len, k_len, block_q, block_k, nk,
):
    i = pl.program_id(2)
    q = q_ref[0, :, 0, :].astype(jnp.float32) * scale  # [bq, D]
    D = q.shape[-1]
    acc0 = jnp.zeros((block_q, D), jnp.float32)
    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)

    if causal:
        hi = jnp.minimum(nk, pl.cdiv((i + 1) * block_q, block_k))
    else:
        hi = nk

    def body(j, carry):
        acc, m, l = carry
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), 0, :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), 0, :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bk]
        mask = _mask_block(i, j, q_len, k_len, block_q, block_k, causal)
        s = jnp.where(mask, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        safe_m = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.exp(s - safe_m)
        corr = jnp.exp(jnp.where(jnp.isneginf(m), _NEG_INF, m) - safe_m)
        l_new = l * corr + p.sum(axis=-1, keepdims=True)
        acc_new = acc * corr + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return acc_new, m_new, l_new

    acc, m, l = jax.lax.fori_loop(0, hi, body, (acc0, m0, l0))
    o_ref[0, :, 0, :] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    # log-sum-exp of the scaled scores per q row (fully-masked rows get -inf)
    lse = jnp.where(
        l[:, 0] > 0.0, m[:, 0] + jnp.log(jnp.maximum(l[:, 0], 1e-30)), _NEG_INF
    )
    lse_ref[0, 0, :] = lse


def _pad_t(x: jnp.ndarray, t_pad: int) -> jnp.ndarray:
    T = x.shape[1]
    if T == t_pad:
        return x
    return jnp.pad(x, ((0, 0), (0, t_pad - T), (0, 0), (0, 0)))


def _fwd(
    q, k, v, causal, scale, block_q, block_k, interpret
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    bq = min(block_q, _round_up(Tq, 8))
    bk = min(block_k, _round_up(Tk, 8))
    Tq_p, Tk_p = _round_up(Tq, bq), _round_up(Tk, bk)
    nq, nk = Tq_p // bq, Tk_p // bk
    qp, kp, vp = _pad_t(q, Tq_p), _pad_t(k, Tk_p), _pad_t(v, Tk_p)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, q_len=Tq, k_len=Tk,
        block_q=bq, block_k=bk, nk=nk,
    )
    o, lse = pl.pallas_call(
        kernel,
        grid=(B, H, nq),
        in_specs=[
            pl.BlockSpec((1, bq, 1, D), lambda b, h, i: (b, i, h, 0)),
            pl.BlockSpec((1, Tk_p, 1, D), lambda b, h, i: (b, 0, h, 0)),
            pl.BlockSpec((1, Tk_p, 1, D), lambda b, h, i: (b, 0, h, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, 1, D), lambda b, h, i: (b, i, h, 0)),
            pl.BlockSpec((1, 1, bq), lambda b, h, i: (b, h, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, Tq_p, H, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, Tq_p), jnp.float32),
        ],
        interpret=interpret,
    )(qp, kp, vp)
    return o[:, :Tq], lse


# ----------------------------------------------------------------------
# backward (FlashAttention-2 split: dq over q blocks, dk/dv over k blocks)
# ----------------------------------------------------------------------
def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
    *, scale, causal, q_len, k_len, block_q, block_k, nk,
):
    i = pl.program_id(2)
    q = q_ref[0, :, 0, :].astype(jnp.float32) * scale
    do = do_ref[0, :, 0, :].astype(jnp.float32)  # [bq, D]
    lse = lse_ref[0, 0, :][:, None]  # [bq, 1]
    delta = delta_ref[0, 0, :][:, None]  # [bq, 1]
    safe_lse = jnp.where(jnp.isneginf(lse), 0.0, lse)
    dq0 = jnp.zeros_like(q)

    if causal:
        hi = jnp.minimum(nk, pl.cdiv((i + 1) * block_q, block_k))
    else:
        hi = nk

    def body(j, dq):
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), 0, :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), 0, :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        mask = _mask_block(i, j, q_len, k_len, block_q, block_k, causal)
        p = jnp.where(mask, jnp.exp(s - safe_lse), 0.0)  # [bq, bk]
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)
        return dq + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    dq = jax.lax.fori_loop(0, hi, body, dq0)
    dq_ref[0, :, 0, :] = (dq * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    *, scale, causal, q_len, k_len, block_q, block_k, nq,
):
    j = pl.program_id(2)
    k_blk = k_ref[0, :, 0, :].astype(jnp.float32)  # [bk, D]
    v_blk = v_ref[0, :, 0, :].astype(jnp.float32)
    dk0 = jnp.zeros_like(k_blk)
    dv0 = jnp.zeros_like(v_blk)

    lo = (j * block_k) // block_q if causal else 0

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(i * block_q, block_q), 0, :].astype(jnp.float32) * scale
        do = do_ref[0, pl.ds(i * block_q, block_q), 0, :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(i * block_q, block_q)][:, None]
        delta = delta_ref[0, 0, pl.ds(i * block_q, block_q)][:, None]
        safe_lse = jnp.where(jnp.isneginf(lse), 0.0, lse)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        mask = _mask_block(i, j, q_len, k_len, block_q, block_k, causal)
        p = jnp.where(mask, jnp.exp(s - safe_lse), 0.0)  # [bq, bk]
        dv_new = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)
        dk_new = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return dk_new, dv_new

    nq_total = nq
    dk, dv = jax.lax.fori_loop(lo, nq_total, body, (dk0, dv0))
    # q was pre-scaled, so ds@q carries one factor of `scale` already — the
    # remaining factor belongs to dk only
    dk_ref[0, :, 0, :] = dk.astype(dk_ref.dtype)
    dv_ref[0, :, 0, :] = dv.astype(dv_ref.dtype)


def _bwd(
    causal, scale, block_q, block_k, interpret, residuals, g
):
    q, k, v, o, lse = residuals
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    bq = min(block_q, _round_up(Tq, 8))
    bk = min(block_k, _round_up(Tk, 8))
    Tq_p, Tk_p = _round_up(Tq, bq), _round_up(Tk, bk)
    nq, nk = Tq_p // bq, Tk_p // bk
    qp, kp, vp = _pad_t(q, Tq_p), _pad_t(k, Tk_p), _pad_t(v, Tk_p)
    dop, op = _pad_t(g, Tq_p), _pad_t(o, Tq_p)
    lse_p = jnp.pad(lse, ((0, 0), (0, 0), (0, Tq_p - Tq)))
    # delta_i = rowsum(dO_i * O_i) — the softmax-jacobian correction term
    delta = jnp.einsum("bqhd,bqhd->bhq", dop.astype(jnp.float32), op.astype(jnp.float32))

    dq_kernel = functools.partial(
        _bwd_dq_kernel, scale=scale, causal=causal, q_len=Tq, k_len=Tk,
        block_q=bq, block_k=bk, nk=nk,
    )
    dq = pl.pallas_call(
        dq_kernel,
        grid=(B, H, nq),
        in_specs=[
            pl.BlockSpec((1, bq, 1, D), lambda b, h, i: (b, i, h, 0)),
            pl.BlockSpec((1, Tk_p, 1, D), lambda b, h, i: (b, 0, h, 0)),
            pl.BlockSpec((1, Tk_p, 1, D), lambda b, h, i: (b, 0, h, 0)),
            pl.BlockSpec((1, bq, 1, D), lambda b, h, i: (b, i, h, 0)),
            pl.BlockSpec((1, 1, bq), lambda b, h, i: (b, h, i)),
            pl.BlockSpec((1, 1, bq), lambda b, h, i: (b, h, i)),
        ],
        out_specs=pl.BlockSpec((1, bq, 1, D), lambda b, h, i: (b, i, h, 0)),
        out_shape=jax.ShapeDtypeStruct((B, Tq_p, H, D), q.dtype),
        interpret=interpret,
    )(qp, kp, vp, dop, lse_p, delta)

    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, scale=scale, causal=causal, q_len=Tq, k_len=Tk,
        block_q=bq, block_k=bk, nq=nq,
    )
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(B, H, nk),
        in_specs=[
            pl.BlockSpec((1, Tq_p, 1, D), lambda b, h, j: (b, 0, h, 0)),
            pl.BlockSpec((1, bk, 1, D), lambda b, h, j: (b, j, h, 0)),
            pl.BlockSpec((1, bk, 1, D), lambda b, h, j: (b, j, h, 0)),
            pl.BlockSpec((1, Tq_p, 1, D), lambda b, h, j: (b, 0, h, 0)),
            pl.BlockSpec((1, 1, Tq_p), lambda b, h, j: (b, h, 0)),
            pl.BlockSpec((1, 1, Tq_p), lambda b, h, j: (b, h, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, 1, D), lambda b, h, j: (b, j, h, 0)),
            pl.BlockSpec((1, bk, 1, D), lambda b, h, j: (b, j, h, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, Tk_p, H, D), k.dtype),
            jax.ShapeDtypeStruct((B, Tk_p, H, D), v.dtype),
        ],
        interpret=interpret,
    )(qp, kp, vp, dop, lse_p, delta)
    return dq[:, :Tq], dk[:, :Tk], dv[:, :Tk]


# ----------------------------------------------------------------------
# public op
# ----------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Blockwise exact attention; same contract as ``full_attention``.

    ``q/k/v``: ``[B, T, H, D]`` (Tq may differ from Tk).  ``interpret=None``
    auto-selects Pallas interpret mode off-TPU.
    """
    out, _ = _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret)
    return out


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if interpret is None:
        interpret = _interpret_default()
    o, lse = _fwd(q, k, v, causal, scale, block_q, block_k, interpret)
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, scale, block_q, block_k, interpret, residuals, g):
    if scale is None:
        scale = 1.0 / (residuals[0].shape[-1] ** 0.5)
    if interpret is None:
        interpret = _interpret_default()
    return _bwd(causal, scale, block_q, block_k, interpret, residuals, g)


flash_attention.defvjp(_flash_fwd, _flash_bwd)
